"""Environment-variable backed configuration parameters.

TPU-native analogue of /root/reference/modin/config/envvars.py:38-1475.  All
variables use the ``MODIN_TPU_*`` prefix.  The execution-selection trio
(``Engine``/``StorageFormat``/``Backend``) mirrors the reference's bimap design
(envvars.py:401-473) with TPU-first defaults: the default execution is the
sharded-jax.Array storage format on the JAX engine.
"""

from __future__ import annotations

import os
import secrets
import warnings
from textwrap import dedent
from typing import Any, Optional

from modin_tpu.config.pubsub import (
    DeprecationDescriptor,
    ExactStr,
    Parameter,
    ValueSource,
    _TYPE_PARAMS,
)


class EnvironmentVariable(Parameter, type=str, abstract=True):
    """A parameter sourced from an environment variable."""

    varname: Optional[str] = None

    @classmethod
    def _get_raw_from_config(cls) -> str:
        if cls.varname is None:
            raise TypeError(f"{cls.__name__} does not have a varname")
        return os.environ[cls.varname]

    @classmethod
    def get_help(cls) -> str:
        help = f"{cls.varname}: {dedent(cls.__doc__ or 'Unknown').strip()}\n"
        help += f"\tProvide {_TYPE_PARAMS[cls.type].help}"
        if cls.choices:
            help += f" (valid examples are: {', '.join(str(c) for c in cls.choices)})"
        return help


class IsDebug(EnvironmentVariable, type=bool):
    """Force the serial in-process Python engine (debugging aid)."""

    varname = "MODIN_TPU_DEBUG"
    default = False


class Engine(EnvironmentVariable, type=str):
    """Task-execution engine: Jax (device), Python (serial, testing), Native (no-op)."""

    varname = "MODIN_TPU_ENGINE"
    choices = ("Jax", "Python", "Native")
    NOINIT_ENGINES = {"Python", "Native"}
    has_custom_engine = False

    @classmethod
    def _get_default(cls) -> str:
        if IsDebug.get():
            return "Python"
        try:
            import jax  # noqa: F401

            return "Jax"
        except ImportError:  # pragma: no cover - jax is a hard dep in practice
            return "Python"

    @classmethod
    def add_option(cls, choice: Any) -> Any:
        choice = super().add_option(choice)
        cls.NOINIT_ENGINES.add(choice)
        cls.has_custom_engine = True
        return choice


class StorageFormat(EnvironmentVariable, type=str):
    """Storage format: Tpu (sharded jax.Array columns), Pandas (block pandas), Native."""

    varname = "MODIN_TPU_STORAGE_FORMAT"
    choices = ("Tpu", "Pandas", "Native")

    @classmethod
    def _get_default(cls) -> str:
        return "Pandas" if Engine.get() in ("Python",) else "Tpu"


class Backend(EnvironmentVariable, type=str):
    """Shorthand for an (Engine, StorageFormat) pair, kept in sync both ways.

    Reference design: envvars.py:401-473 Backend<->Execution bimap.
    """

    varname = "MODIN_TPU_BACKEND"
    choices = ("Tpu", "Pandas", "Python_Test")
    _BACKEND_TO_EXECUTION: dict = {}
    _EXECUTION_TO_BACKEND: dict = {}

    @classmethod
    def register_backend(cls, name: str, execution) -> None:
        name = cls.add_option(name)
        if name in cls._BACKEND_TO_EXECUTION:
            raise ValueError(f"Backend '{name}' is already registered")
        cls._BACKEND_TO_EXECUTION[name] = execution
        cls._EXECUTION_TO_BACKEND[execution] = name

    @classmethod
    def get_backend_for_execution(cls, execution):
        return cls._EXECUTION_TO_BACKEND[execution]

    @classmethod
    def get_execution_for_backend(cls, backend: Optional[str] = None):
        if backend is None:
            backend = cls.get()
        backend = _TYPE_PARAMS[cls.type].normalize(backend)
        if backend not in cls._BACKEND_TO_EXECUTION:
            raise ValueError(f"Unknown backend '{backend}'")
        return cls._BACKEND_TO_EXECUTION[backend]

    @classmethod
    def _get_default(cls) -> str:
        from modin_tpu.core.execution.utils import Execution

        try:
            return cls._EXECUTION_TO_BACKEND[
                Execution(StorageFormat.get(), Engine.get())
            ]
        except KeyError:
            return "Tpu"


class CpuCount(EnvironmentVariable, type=int):
    """How many CPU cores to use for host-side (pandas-fallback) work."""

    varname = "MODIN_TPU_CPUS"

    @classmethod
    def _get_default(cls) -> int:
        import multiprocessing

        return multiprocessing.cpu_count()


class DeviceCount(EnvironmentVariable, type=int):
    """How many accelerator devices the mesh spans (defaults to all visible)."""

    varname = "MODIN_TPU_DEVICES"

    @classmethod
    def _get_default(cls) -> int:
        try:
            import jax

            return jax.device_count()
        except Exception:
            return 1


class MeshShape(EnvironmentVariable, type=tuple):
    """Logical device mesh shape as (rows, cols) shards, e.g. '8,1'.

    The TPU-native analogue of the reference's 2-D partition grid
    (NPartitions x column splits): the row axis shards dataframe rows over
    ICI neighbors; the col axis (usually 1) shards very wide frames.
    """

    varname = "MODIN_TPU_MESH_SHAPE"

    @classmethod
    def _get_default(cls) -> tuple:
        return (DeviceCount.get(), 1)


class NPartitions(EnvironmentVariable, type=int):
    """Number of row shards for the partitioned (non-device) storage formats."""

    varname = "MODIN_TPU_NPARTITIONS"

    @classmethod
    def _get_default(cls) -> int:
        return max(CpuCount.get(), DeviceCount.get())


class Memory(EnvironmentVariable, type=int):
    """How much host memory (bytes) the runtime may use for spill buffers."""

    varname = "MODIN_TPU_MEMORY"
    default = None

    @classmethod
    def get(cls):  # Memory may legitimately be unset
        try:
            return super().get()
        except TypeError:
            return None


class BenchmarkMode(EnvironmentVariable, type=bool):
    """Force synchronous execution (block_until_ready) after every operator."""

    varname = "MODIN_TPU_BENCHMARK_MODE"
    default = False


class LogMode(EnvironmentVariable, type=str):
    """Tracing mode: disable, enable (api only), enable_api_only."""

    varname = "MODIN_TPU_LOG_MODE"
    choices = ("Enable", "Disable", "Enable_Api_Only")
    default = "Disable"

    @classmethod
    def enable(cls):
        cls.put("Enable")

    @classmethod
    def disable(cls):
        cls.put("Disable")

    @classmethod
    def enable_api_only(cls):
        cls.put("Enable_Api_Only")


class LogMemoryInterval(EnvironmentVariable, type=int):
    """Seconds between memory-profile samples when logging is enabled."""

    varname = "MODIN_TPU_LOG_MEMORY_INTERVAL"
    default = 5

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(f"Log memory interval should be > 0, passed value {value}")
        super().put(value)


class LogFileSize(EnvironmentVariable, type=int):
    """Max size (MB) of one log file before rotation."""

    varname = "MODIN_TPU_LOG_FILE_SIZE"
    default = 10

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(f"Log file size should be > 0 MB, passed value {value}")
        super().put(value)


class MetricsMode(EnvironmentVariable, type=str):
    """Emit API timing metrics to registered handlers (enable/disable)."""

    varname = "MODIN_TPU_METRICS_MODE"
    choices = ("Enable", "Disable")
    default = "Enable"

    @classmethod
    def enable(cls):
        cls.put("Enable")

    @classmethod
    def disable(cls):
        cls.put("Disable")


class ProgressBar(EnvironmentVariable, type=bool):
    """Show a tqdm progress bar over outstanding device computations."""

    varname = "MODIN_TPU_PROGRESS_BAR"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)

    @classmethod
    def _check_new_value_ok(cls, value) -> None:
        if value and BenchmarkMode.get():
            raise ValueError("ProgressBar isn't compatible with BenchmarkMode")


class RangePartitioning(EnvironmentVariable, type=bool):
    """Use range-partitioning (sample->pivots->all-to-all) impls for groupby/sort/merge."""

    varname = "MODIN_TPU_RANGE_PARTITIONING"
    default = False


class LazyExecution(EnvironmentVariable, type=str):
    """Deferred-execution mode: Auto (rely on async dispatch), On, Off."""

    varname = "MODIN_TPU_LAZY_EXECUTION"
    choices = ("Auto", "On", "Off")
    default = "Auto"


class DynamicPartitioning(EnvironmentVariable, type=bool):
    """Fuse small partitions into axis-level computations dynamically."""

    varname = "MODIN_TPU_DYNAMIC_PARTITIONING"
    default = False


class MinRowPartitionSize(EnvironmentVariable, type=int):
    """Minimum rows per row shard (avoid tiny shards that waste device tiles)."""

    varname = "MODIN_TPU_MIN_ROW_PARTITION_SIZE"
    default = 32

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(f"Min row partition size should be > 0, passed value {value}")
        super().put(value)


class MinColumnPartitionSize(EnvironmentVariable, type=int):
    """Minimum columns per column shard."""

    varname = "MODIN_TPU_MIN_COLUMN_PARTITION_SIZE"
    default = 8

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Min column partition size should be > 0, passed value {value}"
            )
        super().put(value)


class TestDatasetSize(EnvironmentVariable, type=str):
    """Dataset size profile for the benchmark suite."""

    varname = "MODIN_TPU_TEST_DATASET_SIZE"
    choices = ("Small", "Normal", "Big")
    default = None


class AsvImplementation(EnvironmentVariable, type=ExactStr):
    """Which implementation the asv-style benchmarks should exercise."""

    varname = "MODIN_TPU_ASV_USE_IMPL"
    choices = ("modin_tpu", "pandas")
    default = "modin_tpu"


class TrackFileLeaks(EnvironmentVariable, type=bool):
    """Audit IO reads for leaked file descriptors (ResourceWarning on leak).

    Off by default: the /proc/self/fd scan costs on every read, and some
    formats legitimately retain descriptors (mmap).  The test suite turns it
    on globally (tests/conftest.py), mirroring the reference's test-conftest
    use of its flag (reference: envvars.py:893)."""

    varname = "MODIN_TPU_TEST_TRACK_FILE_LEAKS"
    default = False


class PersistentPickle(EnvironmentVariable, type=bool):
    """Pickle dataframes by value (portable) rather than by device reference."""

    varname = "MODIN_TPU_PERSISTENT_PICKLE"
    default = False


class TpuNumpy(EnvironmentVariable, type=bool):
    """Use the modin_tpu.numpy array type for numpy-returning APIs."""

    varname = "MODIN_TPU_NUMPY"
    default = False


class AutoSwitchBackend(EnvironmentVariable, type=bool):
    """Let the cost calculator auto-move frames between device and host backends.

    Off by default (matching the reference's MODIN_AUTO_SWITCH_BACKENDS):
    implicit relocation changes result backend types across the API, so the
    user opts in.
    """

    varname = "MODIN_TPU_AUTO_SWITCH_BACKENDS"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class NativePandasMaxRows(EnvironmentVariable, type=int):
    """Frames at or below this many rows prefer the in-process pandas backend."""

    varname = "MODIN_TPU_NATIVE_PANDAS_MAX_ROWS"
    default = 10_000_000


class NativePandasTransferThreshold(EnvironmentVariable, type=int):
    """Max rows the cost model will transfer host->device without complaint."""

    varname = "MODIN_TPU_NATIVE_PANDAS_TRANSFER_THRESHOLD"
    default = 10_000_000


class DevicePutChunkBytes(EnvironmentVariable, type=int):
    """Chunk size (bytes) for host->device streaming of huge columns."""

    varname = "MODIN_TPU_DEVICE_PUT_CHUNK_BYTES"
    default = 1 << 30


class Float64Policy(EnvironmentVariable, type=str):
    """float64 handling on device: Native (x64), Downcast (f32 compute)."""

    varname = "MODIN_TPU_FLOAT64_POLICY"
    choices = ("Native", "Downcast")
    default = "Native"


class CacheDir(EnvironmentVariable, type=ExactStr):
    """Directory for host-side build artifacts (the native CSV chunker's
    compiled .so cache).  Distinct from CompilationCacheDir, which holds
    XLA executables."""

    varname = "MODIN_TPU_CACHE_DIR"

    @classmethod
    def _get_default(cls) -> str:
        import pathlib

        return str(pathlib.Path.home() / ".cache" / "modin_tpu")


class CompilationCacheDir(EnvironmentVariable, type=ExactStr):
    """Directory for jax's persistent compilation cache ('' disables).

    Compiled XLA executables are reused across processes, which matters
    doubly on the tunneled TPU where every fresh compile is a 20-40s
    remote round-trip.  TPU-native analogue of the reference pre-warming
    its worker pools once per cluster.
    """

    varname = "MODIN_TPU_COMPILATION_CACHE_DIR"

    @classmethod
    def _get_default(cls) -> str:
        import pathlib

        return str(pathlib.Path.home() / ".cache" / "modin_tpu" / "jax_cache")


class ResilienceMode(EnvironmentVariable, type=str):
    """Fault-tolerant device execution (retry/backoff, per-path breakers).

    Enable (default): device failures at the engine seam are classified
    (DeviceOOM / DeviceLost / TransientDeviceError), transient ones retried
    with backoff, and each ``_try_*`` device path is guarded by a circuit
    breaker that degrades it to the pandas fallback when unhealthy.
    Disable: raw runtime errors propagate exactly as before.
    """

    varname = "MODIN_TPU_RESILIENCE_MODE"
    choices = ("Enable", "Disable")
    default = "Enable"

    @classmethod
    def enable(cls):
        cls.put("Enable")

    @classmethod
    def disable(cls):
        cls.put("Disable")


class ResilienceRetries(EnvironmentVariable, type=int):
    """Max retries for a TransientDeviceError at the engine seam."""

    varname = "MODIN_TPU_RESILIENCE_RETRIES"
    default = 2

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(f"Resilience retries should be >= 0, passed value {value}")
        super().put(value)


class ResilienceBackoffS(EnvironmentVariable, type=float):
    """Base of the exponential retry backoff, seconds (doubles per attempt)."""

    varname = "MODIN_TPU_RESILIENCE_BACKOFF_S"
    default = 0.05

    @classmethod
    def put(cls, value: float) -> None:
        if value < 0:
            raise ValueError(f"Resilience backoff should be >= 0, passed value {value}")
        super().put(value)


class ResilienceWatchdogS(EnvironmentVariable, type=float):
    """Wall-clock watchdog on materialize/wait, seconds (0 disables).

    A device fetch that outlives the watchdog raises WatchdogTimeout (a
    DeviceLost) instead of hanging the query on a wedged tunnel forever.
    Off by default: every watched call costs one daemon-thread handoff.
    """

    varname = "MODIN_TPU_RESILIENCE_WATCHDOG_S"
    default = 0.0


class ResilienceBreakerThreshold(EnvironmentVariable, type=int):
    """Consecutive strikes (failures or latency violations) that trip a
    device-path circuit breaker open."""

    varname = "MODIN_TPU_RESILIENCE_BREAKER_THRESHOLD"
    default = 5

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Breaker threshold should be > 0, passed value {value}"
            )
        super().put(value)


class ResilienceBreakerCooldownS(EnvironmentVariable, type=float):
    """Seconds an open breaker waits before admitting a half-open probe."""

    varname = "MODIN_TPU_RESILIENCE_BREAKER_COOLDOWN_S"
    default = 30.0


class ResilienceLatencyBudgetS(EnvironmentVariable, type=float):
    """Per-call latency budget for guarded device paths, seconds (0 = no
    budget).  A call that completes but overruns the budget strikes its
    breaker: a pathologically slow kernel degrades like a failing one."""

    varname = "MODIN_TPU_RESILIENCE_LATENCY_BUDGET_S"
    default = 0.0


class RecoveryMode(EnvironmentVariable, type=str):
    """Lineage-based device-column recovery (graftguard).

    Enable (default): every DeviceColumn carries a lineage record
    (host-materialization / io-source / op-replay); on DeviceLost the
    recovery manager re-seats lost columns on a fresh device and the
    failed engine call is retried, and DeviceOOM gets an evict-then-retry
    leg before any pandas fallback.  Disable: PR-1 behavior (DeviceLost is
    terminal for resident columns, OOM falls straight back).
    """

    varname = "MODIN_TPU_RECOVERY_MODE"
    choices = ("Enable", "Disable")
    default = "Enable"

    @classmethod
    def enable(cls):
        cls.put("Enable")

    @classmethod
    def disable(cls):
        cls.put("Disable")


class DeviceMemoryBudget(EnvironmentVariable, type=int):
    """Device-memory budget (bytes) for resident column buffers (unset =
    no budget).  When set, the pre-flight admission controller at the
    ``deploy`` seam spills cold columns to host before a dispatch that
    would overflow the budget, instead of eating a reactive OOM."""

    varname = "MODIN_TPU_DEVICE_MEMORY_BUDGET"
    default = None

    @classmethod
    def get(cls):  # like Memory: legitimately unset means "no budget"
        try:
            return super().get()
        except TypeError:
            return None


class LineageMaxDepth(EnvironmentVariable, type=int):
    """Max op-replay chain length a lineage record may carry.  A column
    whose chain would exceed it is host-checkpointed at creation (exact
    host copy fetched once), cutting the chain to depth 0."""

    varname = "MODIN_TPU_LINEAGE_MAX_DEPTH"
    default = 8

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Lineage max depth should be > 0, passed value {value}"
            )
        super().put(value)


class SpillRetries(EnvironmentVariable, type=int):
    """How many evict-then-retry rounds a DeviceOOM gets at the engine
    seam before the failure is treated as terminal (0 disables the leg)."""

    varname = "MODIN_TPU_SPILL_RETRIES"
    default = 1

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(f"Spill retries should be >= 0, passed value {value}")
        super().put(value)


class SpillTargetFraction(EnvironmentVariable, type=float):
    """Fraction of resident device bytes one OOM-eviction round tries to
    spill (cold-first).  1.0 spills everything spillable."""

    varname = "MODIN_TPU_SPILL_TARGET_FRACTION"
    default = 0.5

    @classmethod
    def put(cls, value: float) -> None:
        if not 0.0 < value <= 1.0:
            raise ValueError(
                f"Spill target fraction should be in (0, 1], passed value {value}"
            )
        super().put(value)


class KernelRouterMode(EnvironmentVariable, type=str):
    """Substrate-aware routing of the sort-shaped reduction families
    (median / quantile / nunique / mode) between the device kernels and the
    pandas host kernels (graftsort).

    Auto (default): a calibrated cost model picks whichever side is
    predicted faster at the observed (rows, strategy, substrate); frames
    below ``KernelRouterMinRows`` always stay on device (the decision is
    noise there and device residency is worth more).  Device: always run
    the device kernels (pre-router behavior).  Host: always decline to the
    pandas fallback (operator escape hatch for a substrate where the
    device sort is known-bad).
    """

    varname = "MODIN_TPU_KERNEL_ROUTER"
    choices = ("Auto", "Device", "Host")
    default = "Auto"


class KernelRouterMinRows(EnvironmentVariable, type=int):
    """Row count below which ``auto`` routing always picks the device
    kernel without consulting (or running) the calibration: at small n the
    host/device gap is measurement noise and keeping results device-resident
    is worth more than the crossover."""

    varname = "MODIN_TPU_KERNEL_ROUTER_MIN_ROWS"
    default = 1 << 20

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Router min rows should be >= 0, passed value {value}"
            )
        super().put(value)


class KernelRouterHistBound(EnvironmentVariable, type=int):
    """Largest value range (max - min + 1) for which an integer /
    dictionary-coded column takes the O(n) segment-sum histogram fast path
    for ``nunique``/``mode`` instead of the O(n log n) sort kernel."""

    varname = "MODIN_TPU_KERNEL_ROUTER_HIST_BOUND"
    default = 1 << 20

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Histogram bound should be > 0, passed value {value}"
            )
        super().put(value)


class KernelRouterCalibrationRows(EnvironmentVariable, type=int):
    """Rows the one-shot router calibration times its micro-kernels at.
    The calibration result is cached to ``CacheDir`` per substrate, so the
    cost is paid once per machine, not once per process."""

    varname = "MODIN_TPU_KERNEL_ROUTER_CALIBRATION_ROWS"
    default = 1 << 18

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Calibration rows should be > 0, passed value {value}"
            )
        super().put(value)


class SpmdMode(EnvironmentVariable, type=str):
    """graftmesh layout routing: local single-program kernels vs sharded
    collective kernels (range_shuffle all_to_all) for the collective-eligible
    ops (sort_values, the sorted-representation build, merge-join).

    Auto (default): the kernel router's calibrated crossover model decides
    per op — a sharded sort pays bucketize + all_to_all + per-shard local
    sorts against one global device sort, so the winner depends on mesh
    shape, row count, and interconnect bandwidth; frames below
    ``SpmdMinRows`` (and every frame on a single-shard mesh) stay local.
    Local: never take the sharded path.  Sharded: always take it when the
    mesh has >= 2 row shards (tests/bench force legs).
    """

    varname = "MODIN_TPU_SPMD"
    choices = ("Auto", "Local", "Sharded")
    default = "Auto"


class SpmdMinRows(EnvironmentVariable, type=int):
    """Row count below which ``Auto`` SPMD routing always stays local
    without consulting (or running) the calibration: at small n the
    collective launch overhead dominates and the decision is noise."""

    varname = "MODIN_TPU_SPMD_MIN_ROWS"
    default = 1 << 18

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"SPMD min rows should be >= 0, passed value {value}"
            )
        super().put(value)


class StreamMode(EnvironmentVariable, type=str):
    """graftstream out-of-core residency routing: resident single-pass
    kernels vs the windowed streaming executor (modin_tpu/streaming/) for
    frames/sources larger than the device-memory budget.

    Auto (default): the kernel router's ``decide_residency`` leg decides
    per op — estimated bytes against the device ledger's headroom; with no
    ``MODIN_TPU_DEVICE_MEMORY_BUDGET`` set everything stays resident (one
    attribute read on the hot path).  Resident: never stream.  Windowed:
    always stream when the op family supports it (tests/bench pin legs).
    """

    varname = "MODIN_TPU_STREAM"
    choices = ("Auto", "Resident", "Windowed")
    default = "Auto"


class StreamWindowBytes(EnvironmentVariable, type=int):
    """Explicit streaming window size in source bytes; 0 (default) derives
    the window from the device budget so ``1 + prefetch_depth`` windows
    (plus a 2x kernel working-set allowance) fit under it by construction."""

    varname = "MODIN_TPU_STREAM_WINDOW_BYTES"
    default = 0

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Stream window bytes should be >= 0, passed value {value}"
            )
        super().put(value)


class StreamPrefetch(EnvironmentVariable, type=int):
    """Windows prefetched ahead of the consuming kernel (0 = fully serial:
    parse, deploy, consume, drop, repeat).  The default of 1 double-buffers:
    window i+1's byte-range parse + host->device transfer overlaps window
    i's kernel, with the window size shrunk so both stay under budget."""

    varname = "MODIN_TPU_STREAM_PREFETCH"
    default = 1

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Stream prefetch depth should be >= 0, passed value {value}"
            )
        super().put(value)


class StreamMaxGroups(EnvironmentVariable, type=int):
    """Bound on the streaming groupby's partial-state table (distinct groups
    accumulated across windows).  Past it the streaming executor degrades to
    the resident path — whose high-cardinality groupby already routes
    through the range_shuffle — instead of growing host state unbounded."""

    varname = "MODIN_TPU_STREAM_MAX_GROUPS"
    default = 1 << 20

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Stream max groups should be > 0, passed value {value}"
            )
        super().put(value)


class PlanScanCacheBytes(EnvironmentVariable, type=int):
    """Byte bound on graftplan's per-origin materialized-scan cache.

    Each cached entry pins a fully materialized query compiler; with
    out-of-core-sized sources even the old four-entry FIFO was a multi-GB
    host leak, so eviction is now driven by the entries' measured bytes
    (coldest-first, ``plan.scan.cache_evict``).  0 disables caching
    entirely — every force() re-reads."""

    varname = "MODIN_TPU_PLAN_SCAN_CACHE_BYTES"
    default = 1 << 28

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Plan scan cache bytes should be >= 0, passed value {value}"
            )
        super().put(value)


class PlanMode(EnvironmentVariable, type=str):
    """graftplan whole-query deferred planning.

    Auto (default): supported reads (local plain-file read_csv/read_table)
    defer into a logical plan; chained plan-capable calls (project / filter /
    elementwise map / reduce / groupby_agg / sort) extend the plan, and any
    materialization point (repr, to_pandas, index access, an op with no plan
    node) optimizes the plan (dead-column pruning, projection pushdown into
    the byte-range readers, filter pushdown, CSE, map->reduce fusion) and
    lowers it through the eager seams.  Off: never defer — today's eager
    behavior exactly.  Force: Auto plus re-entering planning for
    plan-capable calls on already-materialized TPU frames (Source-rooted
    plans), so rewrites keep applying after materialization points.
    """

    varname = "MODIN_TPU_PLAN"
    choices = ("Auto", "Off", "Force")
    default = "Auto"


class PlanMaxPasses(EnvironmentVariable, type=int):
    """Rewrite-pass budget for graftplan's fixpoint rule engine: each pass
    applies the whole rule catalog once, and optimization stops at fixpoint
    or after this many passes — a misbehaving rule cannot wedge a query."""

    varname = "MODIN_TPU_PLAN_MAX_PASSES"
    default = 8

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Plan pass budget should be > 0, passed value {value}"
            )
        super().put(value)


class OptMode(EnvironmentVariable, type=str):
    """graftopt unified cost-based optimization (plan/optimizer.py).

    Auto (default): each plan materialization runs one joint ``choose()``
    pass over the optimized plan — a cost model seeded from the kernel
    router's calibration table, the graftcost substrate peaks, and
    PERF_HISTORY priors annotates every node with its execution-strategy
    legs (device/host, local/sharded, fused/staged, resident/windowed),
    the rewrite engine gates rules on modeled cost, and lowering re-plans
    the remaining segment mid-query when measured walls, ledger pressure,
    or compile-storm level diverge from the estimates.  Off: the five
    routers decide independently at their own layers — bit-for-bit the
    pre-graftopt behavior, with zero optimizer allocations.
    """

    varname = "MODIN_TPU_OPT"
    choices = ("Auto", "Off")
    default = "Auto"


class OptReplanFactor(EnvironmentVariable, type=float):
    """Mid-query re-plan threshold for graftopt (plan/optimizer.py).

    A lowered node whose measured wall exceeds its plan-time estimate by
    more than this factor (and clears the absolute noise floor) triggers a
    re-optimization of the not-yet-lowered plan segment through the same
    ``choose()`` pass, with the measured/estimated ratio folded in as a
    correction on the calibrated device-side coefficients."""

    varname = "MODIN_TPU_OPT_REPLAN_FACTOR"
    default = 4.0

    @classmethod
    def put(cls, value: float) -> None:
        if value <= 1.0:
            raise ValueError(
                f"Re-plan factor should be > 1, passed value {value}"
            )
        super().put(value)


class FusedCacheSize(EnvironmentVariable, type=int):
    """Bound on the fused-executable cache in ops/lazy.py (entries, LRU).

    Each entry pins a jitted XLA executable; long sessions with varying
    expression shapes previously grew the cache without limit.  0 disables
    the bound (the pre-LRU behavior)."""

    varname = "MODIN_TPU_FUSED_CACHE_SIZE"
    default = 256

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Fused cache size should be >= 0, passed value {value}"
            )
        super().put(value)


class FuseMode(EnvironmentVariable, type=str):
    """graftfuse whole-plan compilation: compile the entire post-scan
    segment of an optimized plan (filter/map/project chain plus its
    reduce or groupby_agg tail) into ONE donated, bucket-padded XLA
    program (plan/fuse.py).

    Auto (default): the kernel router's ``decide_compile`` leg decides per
    materialization — frames below ``MODIN_TPU_FUSE_MIN_ROWS`` stay on the
    staged path, where per-op trace cost beats the dispatch savings.
    Staged: never fuse across the filter boundary (the pre-graftfuse
    lowering).  Fused: always fuse where the segment shape supports it
    (tests and bench legs pin sides).
    """

    varname = "MODIN_TPU_FUSE"
    choices = ("Auto", "Staged", "Fused")
    default = "Auto"


class FuseMinRows(EnvironmentVariable, type=int):
    """Row floor for the Auto fused-compilation decision (graftfuse).

    Below it, ``decide_compile`` keeps the staged path: tracing and
    compiling a whole-plan program costs milliseconds, which a tiny
    frame's saved dispatch never earns back — and unit-test-sized frames
    stay deterministically on the staged kernels."""

    varname = "MODIN_TPU_FUSE_MIN_ROWS"
    default = 32768


class MetersEnabled(EnvironmentVariable, type=bool):
    """graftmeter in-process metric aggregation: counters, gauges, and
    fixed-bucket histograms over the ``emit_metric`` stream, with
    ``snapshot()``/``reset()`` and Prometheus/JSON exposition
    (modin_tpu/observability/meters.py + exposition.py).

    Off by default: the disabled mode costs one module-attribute check per
    ``emit_metric`` call and allocates no aggregation objects
    (``meter_alloc_count()`` asserts exactly that, graftscope-style).
    ``query_stats()`` / ``explain(analyze=True)`` activate per-query
    accounting for their scope regardless of this switch.
    """

    varname = "MODIN_TPU_METERS"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class MetersMaxSeries(EnvironmentVariable, type=int):
    """Cap on distinct aggregated metric names the graftmeter registry will
    hold (cardinality guard: runaway interpolated segments cannot grow the
    registry without bound).  Names past the cap are dropped and counted in
    the snapshot: ``dropped_series`` (distinct refused names) and
    ``dropped_observations`` (refused emissions)."""

    varname = "MODIN_TPU_METERS_MAX_SERIES"
    default = 2048

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Meter series cap should be > 0, passed value {value}"
            )
        super().put(value)


class CostCapture(EnvironmentVariable, type=str):
    """graftcost XLA cost-model capture (modin_tpu/observability/costs.py):
    per-signature flops/bytes/transcendentals from ``cost_analysis()``,
    padding-waste accounting at the device padding sites, and the achieved
    FLOP/s / bandwidth / roofline join in ``query_stats()`` and
    ``explain(analyze=True)``.

    - ``Auto`` (default): capture is active exactly while graftmeter
      accounting is (``MODIN_TPU_METERS=1`` or an open ``query_stats()``
      scope) — zero overhead otherwise;
    - ``On``: always capture (cost_analysis via the compile-free AOT
      ``lower()`` path);
    - ``Full``: also capture ``memory_analysis()`` (peak/temp/argument
      bytes) — pays one extra AOT backend compile per billed compile, with
      the compile-ledger listener suppressed so the extra compile is never
      billed as workload;
    - ``Off``: never capture, even while accounting is on.
    """

    varname = "MODIN_TPU_COST_CAPTURE"
    default = "Auto"
    choices = ("Auto", "On", "Full", "Off")


class PerfGateTolerance(EnvironmentVariable, type=float):
    """Regression tolerance for the perf-history gate
    (scripts/perf_history.py): a new bench run whose op wall exceeds the
    best recorded same-(op, substrate, rows) wall by more than this factor
    fails the gate.  1.5 absorbs CPU-substrate scheduler noise while still
    rejecting a 2x regression outright."""

    varname = "MODIN_TPU_PERF_GATE_TOLERANCE"
    default = 1.5

    @classmethod
    def put(cls, value: float) -> None:
        if value < 1.0:
            raise ValueError(
                f"Perf gate tolerance should be >= 1.0, passed value {value}"
            )
        super().put(value)


class PerfGateNoiseFloorS(EnvironmentVariable, type=float):
    """Absolute noise floor (seconds) for the perf-history gate: a wall
    within this many seconds of the best recorded wall never fails the
    gate, regardless of the ratio.  Sub-millisecond op walls on a shared
    CPU substrate are timer-jitter-dominated — a 0.8ms-vs-1.4ms delta is
    scheduler noise, not a regression — so the ratio tolerance only
    applies once the absolute delta clears this floor."""

    varname = "MODIN_TPU_PERF_GATE_NOISE_FLOOR_S"
    default = 0.005

    @classmethod
    def put(cls, value: float) -> None:
        if value < 0.0:
            raise ValueError(
                f"Perf gate noise floor should be >= 0, passed value {value}"
            )
        super().put(value)


class ServingEnabled(EnvironmentVariable, type=bool):
    """graftgate multi-tenant serving: query admission control, latency
    budgets, per-tenant fairness, and graceful degradation under
    concurrent load (modin_tpu/serving/).

    Off by default: ``serving.submit`` is a transparent direct call —
    bit-for-bit the single-query behavior — and the seam checks cost one
    module-attribute read (``context.CONTEXT_ON``), allocating nothing
    (``serving.context_alloc_count()`` asserts it, graftscope-style).
    """

    varname = "MODIN_TPU_SERVING"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class ServingMaxConcurrent(EnvironmentVariable, type=int):
    """Queries the admission gate lets run simultaneously.  Each admitted
    query also reserves its estimated device bytes (tenant cost EWMA, or
    ``device_budget / max_concurrent`` for an unknown tenant) against the
    ``MODIN_TPU_DEVICE_MEMORY_BUDGET`` headroom."""

    varname = "MODIN_TPU_SERVING_MAX_CONCURRENT"
    default = 4

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Serving max-concurrent should be > 0, passed value {value}"
            )
        super().put(value)


class ServingQueueDepth(EnvironmentVariable, type=int):
    """Bounded admission wait queue: queries past max-concurrent wait here
    (weighted-fair wake order); past this depth they are shed with a typed
    ``QueryRejected`` + retry-after hint.  0 = never queue, shed
    immediately at saturation."""

    varname = "MODIN_TPU_SERVING_QUEUE_DEPTH"
    default = 16

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Serving queue depth should be >= 0, passed value {value}"
            )
        super().put(value)


class ServingDefaultDeadlineMs(EnvironmentVariable, type=float):
    """Latency budget (milliseconds) for queries submitted without an
    explicit ``deadline_ms`` (0 = unbounded).  The budget rides the query
    as a cancellation token checked at the engine-seam boundaries; expiry
    raises a typed ``DeadlineExceeded`` with overshoot bounded by one
    engine attempt."""

    varname = "MODIN_TPU_SERVING_DEFAULT_DEADLINE_MS"
    default = 0.0

    @classmethod
    def put(cls, value: float) -> None:
        if value < 0:
            raise ValueError(
                f"Serving default deadline should be >= 0, passed value {value}"
            )
        super().put(value)


class ServingTenantWeights(EnvironmentVariable, type=ExactStr):
    """Per-tenant fairness weights as ``"name=weight,name=weight"`` (e.g.
    ``"alice=3,bob=1"``; unlisted tenants weigh 1.0).  A tenant's token
    bucket holds ``weight * max_concurrent`` tokens refilling at that rate
    per second, and the saturated gate wakes queued tenants
    fewest-in-flight-per-weight first."""

    varname = "MODIN_TPU_SERVING_TENANT_WEIGHTS"
    default = ""


class ServingDegradedHighWater(EnvironmentVariable, type=float):
    """Device-ledger fraction of ``MODIN_TPU_DEVICE_MEMORY_BUDGET`` past
    which admitted queries route to the host/pandas path (degraded mode)
    instead of queueing behind a pressured device; an OPEN device-path
    breaker triggers the same routing regardless of residency."""

    varname = "MODIN_TPU_SERVING_DEGRADED_HIGH_WATER"
    default = 0.9

    @classmethod
    def put(cls, value: float) -> None:
        if not 0.0 < value <= 1.0:
            raise ValueError(
                f"Degraded high-water should be in (0, 1], passed value {value}"
            )
        super().put(value)


class FleetEnabled(EnvironmentVariable, type=bool):
    """graftfleet replicated serving: a coordinator spawns and supervises
    N replica serving processes (each with its own virtual mesh, admission
    gate, and watch exporter on an ephemeral port), routes tenant queries
    over a local socket RPC with deadline propagation, detects replica
    failure (heartbeat loss / liveness-probe timeout / dead socket on
    dispatch), drains and redistributes tenants weighted by each
    survivor's typed-shed rate, and respawns dead replicas warm from the
    dataset manifest plus graftview's artifact export/ingest seam
    (modin_tpu/fleet/).

    Off by default: no coordinator, no sockets, no threads —
    ``fleet.submit`` is one module-attribute check away from the local
    ``serving.submit`` path, allocating nothing
    (``fleet_alloc_count()`` asserts it, graftscope-style).
    """

    varname = "MODIN_TPU_FLEET"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class FleetReplicas(EnvironmentVariable, type=int):
    """How many replica serving processes ``start_fleet()`` spawns."""

    varname = "MODIN_TPU_FLEET_REPLICAS"
    default = 2

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Fleet replica count should be > 0, passed value {value}"
            )
        super().put(value)


class FleetHeartbeatS(EnvironmentVariable, type=float):
    """Seconds between replica heartbeats to the coordinator.  A replica
    whose heartbeat goes silent for ~3 intervals gets one liveness probe
    (fresh dial + ping on its RPC socket); probe failure declares it
    lost.  The monitor re-reads this every tick, so a live retune takes
    effect at the next wakeup."""

    varname = "MODIN_TPU_FLEET_HEARTBEAT_S"
    default = 0.5

    @classmethod
    def put(cls, value: float) -> None:
        if value <= 0:
            raise ValueError(
                f"Fleet heartbeat interval should be > 0, passed value {value}"
            )
        super().put(value)


class FleetRespawn(EnvironmentVariable, type=bool):
    """Respawn a lost replica (fresh process, generation + 1) and re-warm
    it from the dataset manifest + graftview artifact export before
    routing to it again.  Off: the fleet runs degraded on the survivors
    (tests pin legs)."""

    varname = "MODIN_TPU_FLEET_RESPAWN"
    default = True


class FleetCoordAddress(EnvironmentVariable, type=ExactStr):
    """INTERNAL: ``host:port`` of the coordinator's control listener.  Set
    by the coordinator in a replica's spawn environment; never set by
    hand (a replica with no coordinator to dial exits immediately)."""

    varname = "MODIN_TPU_FLEET_COORD"
    default = ""


class FleetReplicaIndex(EnvironmentVariable, type=int):
    """INTERNAL: this replica's slot index in the coordinator's table.
    Set by the coordinator in a replica's spawn environment."""

    varname = "MODIN_TPU_FLEET_INDEX"
    default = -1


class FleetReplicaGeneration(EnvironmentVariable, type=int):
    """INTERNAL: this replica's spawn generation (bumped on every
    respawn so stale hellos/heartbeats from a resumed corpse are
    ignored).  Set by the coordinator in a replica's spawn environment."""

    varname = "MODIN_TPU_FLEET_GEN"
    default = 0


class FleetTestCrash(EnvironmentVariable, type=ExactStr):
    """INTERNAL: fault-injection leg for the test suite — ``warm`` makes
    a replica ``os._exit(3)`` when the warm RPC arrives (the
    crash-during-respawn case).  Set one-shot by
    ``ReplicaFaultInjector.crash_next_respawn()``; never set by hand."""

    varname = "MODIN_TPU_FLEET_TEST_CRASH"
    default = ""


class ViewsMode(EnvironmentVariable, type=str):
    """graftview derived-artifact cache (modin_tpu/views/): whole reduction
    results, nunique/mode/median answers, small groupby output tables, and
    the sorted representations cached per (op fingerprint, column identity,
    device epoch, mesh shape) and shared across every query on the same
    buffers — with append-only (``concat``) growth folding ONLY the
    appended tail into algebraic artifacts instead of recomputing.

    Auto (default): consult and maintain artifacts on the device hot
    paths.  Off: never consult the registry — bit-for-bit the pre-graftview
    behavior, at the cost of one module-attribute read per gated hook
    (``views.VIEWS_ON``, the graftscope zero-overhead-when-off contract).
    The pre-existing sorted-representation cache is NOT gated here: it
    predates graftview and keeps its own semantics in both modes.
    """

    varname = "MODIN_TPU_VIEWS"
    choices = ("Auto", "Off")
    default = "Auto"


class ViewsMaxEntries(EnvironmentVariable, type=int):
    """Cap on live artifacts in the graftview registry; the coldest
    entries are evicted (``view.evict``) past it.  Bounds per-process
    memory under serving workloads that mint many distinct (op, column)
    pairs."""

    varname = "MODIN_TPU_VIEWS_MAX_ENTRIES"
    default = 4096

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Views entry cap should be > 0, passed value {value}"
            )
        super().put(value)


class ViewsHostBudget(EnvironmentVariable, type=int):
    """Host-byte budget for artifact STATE (scalar results, groupby partial
    tables) held by the graftview registry; coldest artifacts evicted past
    it.  Device payloads are budgeted separately by the device ledger
    (``MODIN_TPU_DEVICE_MEMORY_BUDGET``), where pressure drops them before
    any real column spills."""

    varname = "MODIN_TPU_VIEWS_HOST_BUDGET"
    default = 128 * 1024 * 1024

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Views host budget should be > 0, passed value {value}"
            )
        super().put(value)


class ViewsMaxGroups(EnvironmentVariable, type=int):
    """Group-count bound for cacheable groupby output tables (graftview):
    results with more groups than this are never cached or folded — the
    partial-state table must stay small enough that host-side combining
    beats device recomputation, exactly the bound graftstream's windowed
    groupby applies via ``MODIN_TPU_STREAM_MAX_GROUPS``."""

    varname = "MODIN_TPU_VIEWS_MAX_GROUPS"
    default = 65536

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Views group bound should be > 0, passed value {value}"
            )
        super().put(value)


class ViewsMaxChain(EnvironmentVariable, type=int):
    """Append-link chain bound for the graftview registry: a fold lookup
    walks at most this many parent links, and ``note_append`` compacts a
    column's chain (re-anchoring its link past artifact-less intermediate
    tokens, ``view.chain_compact``) once its depth crosses the bound.
    Thousands of micro-batch appends (graftfeed) would otherwise make the
    chain walk O(appends) per lookup — or, at the old hardcoded 8-hop cap,
    silently lose foldability after eight un-queried appends."""

    varname = "MODIN_TPU_VIEWS_MAX_CHAIN"
    default = 64

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Views chain bound should be > 0, passed value {value}"
            )
        super().put(value)


class IngestEnabled(EnvironmentVariable, type=bool):
    """graftfeed continuous ingestion (modin_tpu/ingest/): named ``Feed``
    objects accepting append/upsert micro-batches with schema validation,
    registered live views maintained incrementally on every ingest, and
    staleness-bounded reads (``fresh_within_ms``) wired through the
    serving admission gate.

    Off by default: no feed or view object exists and nothing on any hot
    path allocates (``modin_tpu.ingest.ingest_alloc_count()`` asserts it,
    graftscope-style) — bit-for-bit the pre-graftfeed behavior.
    """

    varname = "MODIN_TPU_INGEST"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class IngestFoldEvery(EnvironmentVariable, type=int):
    """Fold registered live views every N accepted micro-batches (1, the
    default, maintains every view synchronously on every ingest).  Larger
    values trade freshness for ingest throughput: pending batches
    accumulate fold lag, which staleness-bounded reads observe — a read
    whose ``fresh_within_ms`` bound the lag exceeds forces a synchronous
    fold of the backlog."""

    varname = "MODIN_TPU_INGEST_FOLD_EVERY"
    default = 1

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"Ingest fold cadence should be > 0, passed value {value}"
            )
        super().put(value)


class IngestRetentionRows(EnvironmentVariable, type=int):
    """Default per-feed retention bound, in rows (0 = unbounded).  When a
    feed crosses it, whole oldest micro-batches are trimmed off the frame
    prefix (``ingest.trim.rows``) and every live view refolds from its
    retained per-batch partials — no full recompute, and still-foldable
    graftview artifacts on the retained frame stay valid.  ``create_feed``
    accepts a per-feed override."""

    varname = "MODIN_TPU_INGEST_RETENTION_ROWS"
    default = 0

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Ingest retention rows should be >= 0, passed value {value}"
            )
        super().put(value)


class IngestRetentionAgeS(EnvironmentVariable, type=float):
    """Default per-feed retention age bound, in seconds (0 = unbounded):
    micro-batches whose arrival time is older than this are trimmed off
    the feed's prefix on the next ingest, same trim path as the row
    bound.  ``create_feed`` accepts a per-feed override."""

    varname = "MODIN_TPU_INGEST_RETENTION_AGE_S"
    default = 0.0

    @classmethod
    def put(cls, value: float) -> None:
        if value < 0:
            raise ValueError(
                f"Ingest retention age should be >= 0, passed value {value}"
            )
        super().put(value)


class IngestFoldLagMs(EnvironmentVariable, type=float):
    """graftwatch ``fold_lag`` tripwire threshold, milliseconds: the rule
    fires (and captures a rate-limited evidence bundle) when any live
    view's fold lag — the age of its oldest unfolded micro-batch —
    exceeds this while the watch sampler is running."""

    varname = "MODIN_TPU_INGEST_FOLD_LAG_MS"
    default = 1000.0

    @classmethod
    def put(cls, value: float) -> None:
        if value <= 0:
            raise ValueError(
                f"Ingest fold-lag threshold should be > 0, passed value {value}"
            )
        super().put(value)


class WalDir(EnvironmentVariable, type=ExactStr):
    """Root directory for graftwal durability state (per-feed WAL
    segments, checkpoints, meta.json).  '' (the default) resolves to
    ``<MODIN_TPU_CACHE_DIR>/wal``.  ``open_feed(..., durability_dir=...)``
    overrides per call."""

    varname = "MODIN_TPU_WAL_DIR"
    default = ""


class WalFsync(EnvironmentVariable, type=ExactStr):
    """graftwal fsync policy for WAL record writes:

    - ``PerBatch`` (default): fsync after every accepted micro-batch —
      an acked batch survives power loss;
    - ``GroupCommit``: a flusher thread fsyncs dirty segments every
      ``MODIN_TPU_WAL_GROUP_COMMIT_MS`` — bounded loss window, near-Off
      ingest rate;
    - ``Off``: no explicit fsync — survives process crash (the page
      cache persists), not power loss.
    """

    varname = "MODIN_TPU_WAL_FSYNC"
    # ExactStr: the plain str type title-cases ("GroupCommit" ->
    # "Groupcommit"), so the policy names validate here, not via `choices`
    default = "PerBatch"

    @classmethod
    def put(cls, value: str) -> None:
        if value not in ("PerBatch", "GroupCommit", "Off"):
            raise ValueError(
                f"Unsupported value {value!r} for WalFsync; choose one "
                "of ('PerBatch', 'GroupCommit', 'Off')"
            )
        super().put(value)


class WalGroupCommitMs(EnvironmentVariable, type=float):
    """Group-commit flush interval, milliseconds — the loss window under
    ``MODIN_TPU_WAL_FSYNC=GroupCommit`` (ignored by the other policies)."""

    varname = "MODIN_TPU_WAL_GROUP_COMMIT_MS"
    default = 25.0

    @classmethod
    def put(cls, value: float) -> None:
        if value <= 0:
            raise ValueError(
                f"WAL group-commit interval should be > 0, passed value {value}"
            )
        super().put(value)


class WalSegmentBytes(EnvironmentVariable, type=int):
    """WAL segment roll threshold, bytes: the writer starts a new
    ``wal_<first_seq>.seg`` file past this size, and checkpoint
    truncation deletes whole covered segments (reclaim granularity)."""

    varname = "MODIN_TPU_WAL_SEGMENT_BYTES"
    default = 4_194_304

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"WAL segment size should be > 0, passed value {value}"
            )
        super().put(value)


class WalMaxReplayBatches(EnvironmentVariable, type=int):
    """Replay-time bound: a checkpoint is taken once the WAL tail past
    the newest checkpoint exceeds this many records, so crash recovery
    never replays more than ~this many batches."""

    varname = "MODIN_TPU_WAL_MAX_REPLAY_BATCHES"
    default = 256

    @classmethod
    def put(cls, value: int) -> None:
        if value <= 0:
            raise ValueError(
                f"WAL replay bound should be > 0, passed value {value}"
            )
        super().put(value)


class FleetDurabilityDir(EnvironmentVariable, type=ExactStr):
    """INTERNAL: graftwal root a fleet replica recovers durable feeds
    from on warm-up.  Set by the coordinator in a replica's spawn
    environment when the fleet is constructed with a durability dir;
    never set by hand."""

    varname = "MODIN_TPU_FLEET_DURABILITY_DIR"
    default = ""


class TraceEnabled(EnvironmentVariable, type=bool):
    """graftscope structured tracing: spans at the API / query-compiler /
    engine-seam / shuffle-IO layers, the compile ledger's hit accounting,
    and the flight-recorder ring.

    Off by default: the disabled mode costs one module-attribute check per
    instrumented call and allocates no span objects.  ``profile()``
    activates collection for its block regardless of this switch.
    """

    varname = "MODIN_TPU_TRACE"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class LockdepEnabled(EnvironmentVariable, type=bool):
    """graftdep runtime lock-order validation: every ``named_lock`` /
    ``named_rlock`` acquisition is checked against the declared partial
    order in concurrency/registry.py, per-thread acquisition stacks are
    tracked, and an observed inversion raises ``LockdepViolation`` (and
    flight-dumps the witness pair).

    Debug mode for the concurrency suites and smoke gates, not
    production: the disabled mode costs one module-attribute check per
    acquisition and allocates nothing.  Read raw at import time by
    concurrency/lockdep.py (locks are constructed before the config
    layer is importable); declared here so the switch is typed and
    documented like every other knob.
    """

    varname = "MODIN_TPU_LOCKDEP"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class TraceFlightRecorderSize(EnvironmentVariable, type=int):
    """How many recent spans the flight-recorder ring buffer retains while
    tracing is on (0 disables the ring and its fault dumps)."""

    varname = "MODIN_TPU_TRACE_FLIGHT_RECORDER_SIZE"
    default = 1024

    @classmethod
    def put(cls, value: int) -> None:
        if value < 0:
            raise ValueError(
                f"Flight recorder size should be >= 0, passed value {value}"
            )
        super().put(value)


class TraceDir(EnvironmentVariable, type=ExactStr):
    """Directory flight-recorder trace dumps are written to."""

    varname = "MODIN_TPU_TRACE_DIR"
    default = ".modin_tpu/traces"


class WatchEnabled(EnvironmentVariable, type=bool):
    """graftwatch always-on serving telemetry: a background sampler thread
    folds the meter registry, ledger gauges, gate depth, and compile-ledger
    deltas into bounded time-series rings every
    ``MODIN_TPU_WATCH_INTERVAL_S``; a stdlib HTTP exporter serves
    ``/metrics`` / ``/statusz`` / ``/debug/queries`` on
    ``MODIN_TPU_WATCH_PORT``; per-tenant SLO burn rates
    (``MODIN_TPU_WATCH_SLO_MS``) and anomaly tripwires run over the rings
    (modin_tpu/observability/watch/).

    Off by default: no sampler or exporter thread exists, and the one hot
    path the service touches (per-query SLO observation at the serving
    gate) costs one module-attribute check and allocates nothing
    (``watch_alloc_count()`` asserts it, graftscope-style).
    """

    varname = "MODIN_TPU_WATCH"
    default = False

    @classmethod
    def enable(cls):
        cls.put(True)

    @classmethod
    def disable(cls):
        cls.put(False)


class WatchIntervalS(EnvironmentVariable, type=float):
    """Seconds between graftwatch sampler ticks (ring sample spacing).
    The sampler re-reads this every tick, so a live retune takes effect
    at the next wakeup."""

    varname = "MODIN_TPU_WATCH_INTERVAL_S"
    default = 1.0

    @classmethod
    def put(cls, value: float) -> None:
        if value <= 0:
            raise ValueError(
                f"Watch interval should be > 0, passed value {value}"
            )
        super().put(value)


class WatchPort(EnvironmentVariable, type=int):
    """TCP port the graftwatch HTTP exporter binds on 127.0.0.1 while the
    service runs (``/metrics``, ``/statusz``, ``/debug/queries``).  0 (the
    default) binds an OS-assigned ephemeral port — read the live port back
    with ``modin_tpu.observability.watch.httpd_port()``; -1 disables the
    exporter entirely (rings/SLO/tripwires still run)."""

    varname = "MODIN_TPU_WATCH_PORT"
    default = 0

    @classmethod
    def put(cls, value: int) -> None:
        if value < -1 or value > 65535:
            raise ValueError(
                f"Watch port should be -1 (exporter off), 0 (ephemeral), "
                f"or a valid TCP port, passed value {value}"
            )
        super().put(value)


class WatchSloMs(EnvironmentVariable, type=ExactStr):
    """Per-tenant latency objectives (milliseconds) for graftwatch SLO
    burn-rate tracking, ``"default=250,alice=50"`` style (same parser
    shape as ``MODIN_TPU_SERVING_TENANT_WEIGHTS``; a bare number such as
    ``"250"`` is shorthand for ``default=250``).  The ``default`` entry
    applies to every tenant without its own; empty (the default) tracks
    latency observations but computes no burn rates."""

    varname = "MODIN_TPU_WATCH_SLO_MS"
    default = ""


class DocModule(EnvironmentVariable, type=ExactStr):
    """Alternate module to source API docstrings from (reference: envvars.py:1338)."""

    varname = "MODIN_TPU_DOC_MODULE"
    default = "pandas"


class ReadSqlEngine(EnvironmentVariable, type=str):
    """Engine to use when reading SQL tables."""

    varname = "MODIN_TPU_READ_SQL_ENGINE"
    choices = ("Pandas", "Connectorx")
    default = "Pandas"


class StateId(EnvironmentVariable, type=ExactStr):
    """Unique id of this session (used for log directories)."""

    varname = "MODIN_TPU_STATE_ID"

    @classmethod
    def _get_default(cls) -> str:
        return secrets.token_hex(8)


def _register_builtin_backends() -> None:
    """Wire the canonical Backend <-> (StorageFormat, Engine) bimap
    (reference: envvars.py:401-473)."""
    from modin_tpu.core.execution.utils import Execution

    Backend._BACKEND_TO_EXECUTION.clear()
    Backend._EXECUTION_TO_BACKEND.clear()
    Backend._BACKEND_TO_EXECUTION["Tpu"] = Execution("Tpu", "Jax")
    Backend._EXECUTION_TO_BACKEND[Execution("Tpu", "Jax")] = "Tpu"
    Backend._BACKEND_TO_EXECUTION["Pandas"] = Execution("Native", "Native")
    Backend._EXECUTION_TO_BACKEND[Execution("Native", "Native")] = "Pandas"
    Backend._BACKEND_TO_EXECUTION["Python_Test"] = Execution("Pandas", "Python")
    Backend._EXECUTION_TO_BACKEND[Execution("Pandas", "Python")] = "Python_Test"


_register_builtin_backends()


def _check_vars() -> None:
    """Warn on MODIN_TPU_* env vars that don't match any known parameter."""
    valid = {
        obj.varname
        for obj in globals().values()
        if isinstance(obj, type)
        and issubclass(obj, EnvironmentVariable)
        and not obj.is_abstract
        and obj.varname is not None
    }
    found = {name for name in os.environ if name.startswith("MODIN_TPU_")}
    unknown = found - valid
    if unknown:
        warnings.warn(
            f"Found unknown environment variable{'s' if len(unknown) > 1 else ''}, "
            f"please check {'their' if len(unknown) > 1 else 'its'} spelling: "
            + ", ".join(sorted(unknown))
        )


_check_vars()
