"""graftfeed — continuous ingestion & registered live views.

The feature-store serving scenario (ROADMAP item 4, arXiv 2001.00888's
incremental-view-maintenance gap): named :class:`~modin_tpu.ingest.feed.
Feed`\\ s accept append/upsert micro-batches with schema validation,
grow one modin frame through the ordinary ``concat`` path (graftplan
pushdown on the delta, graftview append links on the frame), and
maintain **registered live views** — filtered / top-k / windowed /
scalar / groupby aggregates — incrementally on every ingest via the fold
algebra in live.py.  Reads are staleness-bounded (``fresh_within_ms``)
and admitted, like the ingest itself, through graftgate's one admission
gate; freshness feeds per-view SLO burn in graftwatch plus the
``fold_lag`` tripwire.

``MODIN_TPU_INGEST=0`` (the default) is bit-for-bit pre-graftfeed:
:func:`create_feed` refuses, no hot path consults this package, and
:func:`ingest_alloc_count` stays 0 over any non-ingest workload — the
same zero-overhead-when-off contract as graftscope/graftwatch.
"""

from __future__ import annotations

from typing import Any

#: Module-level fast path: True while MODIN_TPU_INGEST=1.  The ONE
#: attribute anything ingest-adjacent checks before doing work.
INGEST_ON: bool = False


def _on_ingest_enabled(param: Any) -> None:
    global INGEST_ON
    INGEST_ON = bool(param.get())


from modin_tpu.config import IngestEnabled as _IngestEnabled  # noqa: E402

_IngestEnabled.subscribe(_on_ingest_enabled)

from modin_tpu.ingest.errors import (  # noqa: E402,F401
    IngestError,
    IngestRejected,
    ViewNotIncrementalizable,
)
from modin_tpu.ingest.feed import (  # noqa: E402,F401
    Feed,
    ViewRead,
    create_feed,
    drop_feed,
    feeds,
    get_feed,
    max_fold_lag_ms,
    open_feed,
    reset,
)
from modin_tpu.ingest.live import (  # noqa: E402,F401
    LiveView,
    ingest_alloc_count,
)

__all__ = [
    "Feed",
    "INGEST_ON",
    "IngestError",
    "IngestRejected",
    "LiveView",
    "ViewNotIncrementalizable",
    "ViewRead",
    "create_feed",
    "drop_feed",
    "feeds",
    "get_feed",
    "ingest_alloc_count",
    "max_fold_lag_ms",
    "open_feed",
    "reset",
]
