"""graftfeed feeds: named continuous-ingestion targets with live views.

A :class:`Feed` owns one growing modin frame.  Micro-batches (pandas
frame / dict-of-columns / CSV text) are schema-validated (typed
:class:`~modin_tpu.ingest.errors.IngestRejected` on mismatch), then
appended through ``pd.concat`` — the ordinary graftplan path, so the
delta rides pushdown/pruning and graftview's ``concat_rows`` append
links keep ad-hoc queries on the frame folding.  Registered live views
(live.py) are maintained per batch: every fold leaves a per-batch
partial in the view log AND updates the running state, which is what
lets retention trims refold without touching row data.

Admission: appends and reads are both submitted through graftgate's ONE
admission gate (``serving.submit``) under the caller's tenant, so ingest
traffic bills against the same tenant buckets as queries.  Staleness:
``read(..., fresh_within_ms=...)`` serves the maintained artifact when
the fold lag (age of the oldest unfolded batch) is inside the bound and
forces a synchronous fold otherwise; every read feeds the per-view SLO
ring in graftwatch and the ``view.lag_ms`` histogram, and the watch
``fold_lag`` tripwire fires off :func:`max_fold_lag_ms`.

Locking: ``ingest.feeds`` guards the name table; each feed's ``ingest.
feed`` rlock serializes its frame/log/view state.  Metric fan-out always
runs after the locks release (the PR 9 gate-lock lesson).
"""

from __future__ import annotations

import io
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from modin_tpu.concurrency import named_lock, named_rlock
from modin_tpu.ingest.errors import IngestError, IngestRejected
from modin_tpu.ingest.live import LiveView, note_alloc
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability.spans import span

#: test seam for the smoke's injected slow-fold phase: seconds slept per
#: batch inside the fold loop (0.0 in production)
_FOLD_DELAY_S = 0.0


class _BatchRecord:
    """One admitted micro-batch: its sequence number, row span, arrival
    stamps, and (until folded into every view) the host rows."""

    __slots__ = ("seq", "rows", "abs_start", "t_mono", "t_wall", "pdf")

    def __init__(self, seq: int, rows: int, abs_start: int, pdf: Any) -> None:
        note_alloc()
        self.seq = seq
        self.rows = rows
        self.abs_start = abs_start
        self.t_mono = time.monotonic()
        self.t_wall = time.time()
        self.pdf = pdf


class ViewRead:
    """One staleness-bounded read's answer + its freshness evidence."""

    __slots__ = (
        "value", "lag_ms", "forced", "covered_rows", "base_offset", "seq",
    )

    def __init__(self, value, lag_ms, forced, covered_rows, base_offset, seq):
        self.value = value
        self.lag_ms = lag_ms
        self.forced = forced
        self.covered_rows = covered_rows
        self.base_offset = base_offset
        self.seq = seq

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"<ViewRead lag={self.lag_ms:.1f}ms forced={self.forced} "
            f"covered={self.covered_rows}>"
        )


def _config():
    import modin_tpu.config as config

    return config


class Feed:
    """One named ingestion target.  Constructed via :func:`create_feed`."""

    def __init__(self, name: str, schema: Dict[str, Any],
                 key: Optional[str] = None,
                 retention_rows: Optional[int] = None,
                 retention_age_s: Optional[float] = None) -> None:
        import pandas

        import modin_tpu.pandas as mpd

        note_alloc()
        self.name = name
        self.schema: "OrderedDict[str, np.dtype]" = OrderedDict(
            (col, np.dtype(dt)) for col, dt in schema.items()
        )
        if key is not None and key not in self.schema:
            raise IngestError(
                f"feed {name!r}: key column {key!r} is not in the schema"
            )
        if retention_rows is not None and retention_rows < 0:
            raise IngestError(
                f"feed {name!r}: retention_rows must be >= 0, "
                f"got {retention_rows}"
            )
        if retention_age_s is not None and retention_age_s < 0:
            raise IngestError(
                f"feed {name!r}: retention_age_s must be >= 0, "
                f"got {retention_age_s}"
            )
        self.key = key
        #: per-feed retention overrides; None falls back to the
        #: MODIN_TPU_INGEST_RETENTION_ROWS / _AGE_S defaults at trim time
        self.retention_rows = retention_rows
        self.retention_age_s = retention_age_s
        self._lock = named_rlock("ingest.feed")
        self._mirror = pandas.DataFrame(
            {c: pandas.Series(dtype=d) for c, d in self.schema.items()}
        )
        self._frame = mpd.DataFrame(self._mirror)
        self._batches: "deque[_BatchRecord]" = deque()
        self._pending: "deque[_BatchRecord]" = deque()  # not yet folded
        self._views: Dict[str, LiveView] = {}
        self._key_index: Dict[Any, int] = {}  # key value -> retained position
        self._seq = -1
        self._rows = 0
        self._base_offset = 0  # absolute id of the first retained row
        #: graftwal manager when the feed was opened durable=True; None is
        #: the whole durability cost for ordinary feeds (the zero-overhead
        #: contract — one attribute check on the hot paths)
        self._wal = None

    # -- public surface (admitted through the serving gate) ------------ #

    @property
    def frame(self):
        """The feed's modin frame (ad-hoc queries fold via graftview)."""
        return self._frame

    @property
    def rows(self) -> int:
        return self._rows

    def append(self, batch: Any, tenant: str = "default"):
        """Admit one append micro-batch; returns the new retained row
        count.  Raises :class:`IngestRejected` on schema mismatch (and,
        on a keyed feed, when the batch repeats an existing key — that is
        :meth:`upsert`'s job)."""
        pdf = self._admit(batch)
        from modin_tpu import serving

        return serving.submit(
            self._append_sync, pdf, False,
            tenant=tenant, label=f"ingest.{self.name}",
        )

    def upsert(self, batch: Any, tenant: str = "default"):
        """Admit one upsert micro-batch (keyed feeds): rows whose key
        exists update in place (batch last-wins), the rest append."""
        if self.key is None:
            emit_metric("ingest.reject", 1)
            self._reject("not_keyed", detail="feed has no key column")
        pdf = self._admit(batch)
        from modin_tpu import serving

        return serving.submit(
            self._append_sync, pdf, True,
            tenant=tenant, label=f"ingest.{self.name}",
        )

    def register_view(self, name: str, plan: Dict[str, Any]) -> LiveView:
        """Register a named live view, maintained on every ingest from now
        on (existing retained rows fold in as the view's bootstrap
        partial).  Refuses non-incrementalizable plans with a typed
        :class:`ViewNotIncrementalizable` — never silently recomputed."""
        try:
            view = LiveView(self.name, name, plan, self.schema)
        except Exception:
            emit_metric("ingest.view.refused", 1)
            raise
        dur = self._wal
        # refused plans raise above, so a registration only reaches the
        # WAL once it validated; pickling happens here, outside the lock
        encoded = dur.encode_register(name, plan) if dur is not None else None
        dur_events = [] if dur is not None else None
        try:
            with self._lock:
                if name in self._views:
                    raise IngestError(
                        f"feed {self.name!r}: view {name!r} already registered"
                    )
                if encoded is not None:
                    # on disk BEFORE the view exists in memory
                    dur.log_encoded(encoded, dur_events)
                # graftlint: disable=LOCK-BLOCKING -- _FOLD_DELAY_S is a test-only fault hook (default 0.0); folding under the feed lock IS the contract: views advance atomically w.r.t. appends and trims
                self._fold_pending_locked()
                if self._rows:
                    view.rebuild(self._mirror, self._base_offset, self._seq)
                else:
                    view.folded_seq = self._seq
                self._views[name] = view
        finally:
            if dur is not None:
                dur.fanout(dur_events)
        return view

    def read(self, view_name: str, fresh_within_ms: Optional[float] = None,
             tenant: str = "default") -> ViewRead:
        """One staleness-bounded read, admitted under ``tenant``: serves
        the maintained state when fold lag <= ``fresh_within_ms``, else
        folds the pending batches synchronously first."""
        from modin_tpu import serving

        return serving.submit(
            self._read_sync, view_name, fresh_within_ms,
            tenant=tenant, label=f"ingest.read.{self.name}",
        )

    def fold_now(self) -> None:
        """Fold every pending batch (tests / draining)."""
        with self._lock:
            # graftlint: disable=LOCK-BLOCKING -- _FOLD_DELAY_S is a test-only fault hook (default 0.0); folding under the feed lock IS the contract: views advance atomically w.r.t. appends and trims
            folded = self._fold_pending_locked()
        if folded:
            emit_metric("ingest.fold", folded)

    def fold_lag_ms(self) -> float:
        with self._lock:
            return self._fold_lag_ms_locked()

    def views(self) -> List[str]:
        with self._lock:
            return sorted(self._views)

    def recompute(self, view_name: str) -> Any:
        """The view's answer recomputed FROM SCRATCH through the modin
        frame (the graftplan query path — no maintained state consulted):
        the differential baseline and the bench's recompute leg."""
        with self._lock:
            view = self._views.get(view_name)
            if view is None:
                raise IngestError(
                    f"feed {self.name!r} has no view {view_name!r}"
                )
            plan = view.plan
            kind = view.kind
            frame = self._frame
        col = plan.get("column")
        if kind == "scalar":
            return getattr(frame[col], plan["agg"])()
        if kind == "filtered":
            pcol, op, val = plan["predicate"]
            lhs = frame[pcol]
            mask = {
                ">": lhs > val, ">=": lhs >= val, "<": lhs < val,
                "<=": lhs <= val, "==": lhs == val, "!=": lhs != val,
            }[op]
            return getattr(frame[col][mask], plan["agg"])()
        if kind == "groupby":
            grouped = frame.groupby(plan["by"])[col]
            agg = plan["agg"]
            if agg == "size":
                result = frame.groupby(plan["by"]).size()
            else:
                result = getattr(grouped, agg)()
            return result._to_pandas() if hasattr(result, "_to_pandas") else result
        # topk / windowed recompute over the materialized frame
        pdf = frame._to_pandas().reset_index(drop=True)
        if kind == "topk":
            return pdf[col].nlargest(plan["k"], keep="first")
        import pandas

        ts = pdf[plan["time_column"]]
        keep = ts.notna()
        keys = np.floor(
            ts[keep].to_numpy(dtype=np.float64) / plan["bucket_s"]
        ).astype(np.int64)
        agg = plan["agg"]
        grouped = pdf[col][keep].groupby(keys)
        return getattr(grouped, agg)()

    # -- internals ----------------------------------------------------- #

    def _reject(self, reason: str, **kwargs) -> None:
        """Raise the typed rejection.  Raise-only on purpose: callers emit
        the ``ingest.reject`` counter AFTER any held locks release (the
        PR 9 gate-lock lesson — a slow metric handler must never stall
        appends/reads/trims holding the feed rlock)."""
        raise IngestRejected(self.name, reason, **kwargs)

    def _admit(self, batch: Any) -> Any:
        """Normalize an incoming batch outside any lock, counting
        rejections here where no lock is held."""
        try:
            return self._normalize(batch)
        except IngestRejected:
            emit_metric("ingest.reject", 1)
            raise

    def _normalize(self, batch: Any) -> Any:
        """Coerce an incoming batch (pandas / dict / CSV text) to a
        schema-exact pandas frame, or raise :class:`IngestRejected`."""
        import pandas

        if isinstance(batch, str):
            try:
                pdf = pandas.read_csv(io.StringIO(batch))
            except Exception as err:
                self._reject("malformed", detail=f"CSV parse failed: {err}")
        elif isinstance(batch, dict):
            try:
                pdf = pandas.DataFrame(batch)
            except Exception as err:
                self._reject("malformed", detail=str(err))
        elif isinstance(batch, pandas.DataFrame):
            pdf = batch.copy()
        elif hasattr(batch, "_to_pandas"):
            pdf = batch._to_pandas()
        else:
            self._reject(
                "unsupported_type", got=type(batch).__name__,
                expected="DataFrame | dict | CSV text",
            )
        got_cols = set(pdf.columns)
        for col in self.schema:
            if col not in got_cols:
                self._reject("missing_column", column=col)
        for col in pdf.columns:
            if col not in self.schema:
                self._reject("extra_column", column=str(col))
        pdf = pdf[list(self.schema)].reset_index(drop=True)
        for col, want in self.schema.items():
            got = pdf[col].dtype
            if got == want:
                continue
            if np.can_cast(got, want, casting="safe"):
                pdf[col] = pdf[col].astype(want)
            else:
                self._reject(
                    "dtype", column=col, expected=str(want), got=str(got)
                )
        return pdf

    def _append_sync(self, pdf: Any, is_upsert: bool) -> int:
        dur = self._wal
        # serialize the batch for the WAL outside every lock (pickle is a
        # LOCK-BLOCKING operation); None = nothing to log (non-durable
        # feed, degraded breaker, or this call IS the replay)
        encoded = dur.encode_batch(pdf, is_upsert) if dur is not None else None
        dur_events = [] if dur is not None else None
        try:
            try:
                rows, upserted, appended, folded, trimmed = (
                    self._append_locked(pdf, is_upsert, encoded, dur_events)
                )
            except IngestRejected:
                # key-violation rejects raise under the feed rlock; the
                # counter fans out here, after it released
                emit_metric("ingest.reject", 1)
                raise
        finally:
            # wal.* events (including those of a refusing DurabilityError
            # path, e.g. an exhausted ENOSPC reclaim) fan out lock-free
            if dur is not None:
                dur.fanout(dur_events)
        if appended:
            emit_metric("ingest.batch", 1)
            emit_metric("ingest.rows", appended)
        if upserted:
            emit_metric("ingest.upsert", upserted)
        if folded:
            emit_metric("ingest.fold", folded)
        if trimmed:
            emit_metric("ingest.trim.rows", trimmed)
        if dur is not None:
            dur.maybe_checkpoint()
        return rows

    def _append_locked(self, pdf: Any, is_upsert: bool,
                       encoded=None, dur_events=None):
        import pandas

        import modin_tpu.pandas as mpd

        upserted = appended = folded = trimmed = 0
        with span("ingest.append", layer="APP", feed=self.name,
                  rows=len(pdf)):
            with self._lock:
                if not is_upsert and self.key is not None and len(pdf):
                    # key violations reject BEFORE the WAL sees the batch
                    # (rejects are never logged); moved ahead of the log
                    # call from the elif below for exactly that ordering
                    dup = pdf[self.key].duplicated(keep=False)
                    if bool(dup.any()):
                        self._reject(
                            "duplicate_key",
                            column=self.key,
                            detail="batch repeats a key; keys must be "
                            "unique within an append",
                        )
                    for k in pdf[self.key]:
                        if k in self._key_index:
                            self._reject(
                                "key_exists", column=self.key, got=k,
                                detail="append repeats a stored key — use "
                                "upsert",
                            )
                if encoded is not None and len(pdf):
                    # write-ahead: the record is on disk (per the fsync
                    # policy) before ANY in-memory mutation below; an
                    # exhausted-ENOSPC DurabilityError refuses the batch
                    # here with the feed state untouched
                    self._wal.log_encoded(encoded, dur_events)
                if is_upsert and len(pdf):
                    # batch last-wins among duplicate keys
                    pdf = pdf.drop_duplicates(
                        subset=[self.key], keep="last"
                    ).reset_index(drop=True)
                    hit = pdf[self.key].map(
                        lambda k: k in self._key_index
                    ).to_numpy(dtype=bool)
                    updates, pdf = pdf[hit], pdf[~hit].reset_index(drop=True)
                    if len(updates):
                        positions = [
                            self._key_index[k] for k in updates[self.key]
                        ]
                        for col in self.schema:
                            self._mirror.loc[
                                positions, col
                            ] = updates[col].to_numpy()
                        self._rebuild_frame_locked(mpd)
                        self._rebuild_views_locked()
                        upserted = len(updates)
                if len(pdf):
                    self._seq += 1
                    rec = _BatchRecord(
                        self._seq, len(pdf),
                        self._base_offset + self._rows, pdf,
                    )
                    if self.key is not None:
                        base = self._rows
                        for i, k in enumerate(pdf[self.key]):
                            self._key_index[k] = base + i
                    self._mirror = pandas.concat(
                        [self._mirror, pdf], ignore_index=True
                    )
                    self._frame = mpd.concat(
                        [self._frame, mpd.DataFrame(pdf)], ignore_index=True
                    )
                    self._rows += len(pdf)
                    self._batches.append(rec)
                    self._pending.append(rec)
                    appended = len(pdf)
                    every = int(_config().IngestFoldEvery.get())
                    if every <= 1 or (self._seq + 1) % every == 0:
                        # graftlint: disable=LOCK-BLOCKING -- _FOLD_DELAY_S is a test-only fault hook (default 0.0); folding under the feed lock IS the contract: views advance atomically w.r.t. appends and trims
                        folded = self._fold_pending_locked()
                trimmed = self._trim_locked()
                rows = self._rows
        return rows, upserted, appended, folded, trimmed

    def _rebuild_frame_locked(self, mpd) -> None:
        self._frame = mpd.DataFrame(self._mirror)

    def _rebuild_views_locked(self) -> None:
        """Collapse every view to a bootstrap partial over the retained
        frame (upsert / bootstrap-intersecting trim): the exact-rebuild
        path.  Pending batches are covered by the rebuild, so they drain."""
        self._pending.clear()
        for rec in self._batches:
            rec.pdf = None
        rebuilt = 0
        for view in self._views.values():
            view.rebuild(self._mirror, self._base_offset, self._seq)
            rebuilt += 1
        if rebuilt:
            emit_metric("ingest.rebuild", rebuilt)

    def _fold_pending_locked(self) -> int:
        folded = 0
        while self._pending:
            rec = self._pending.popleft()
            with span("ingest.fold", layer="APP", feed=self.name,
                      seq=rec.seq):
                if _FOLD_DELAY_S > 0.0:
                    time.sleep(_FOLD_DELAY_S)
                for view in self._views.values():
                    view.fold_batch(rec.seq, rec.pdf, rec.abs_start)
            rec.pdf = None
            folded += 1
        return folded

    def _fold_lag_ms_locked(self) -> float:
        if not self._pending:
            return 0.0
        return (time.monotonic() - self._pending[0].t_mono) * 1e3

    def _trim_locked(self) -> int:
        """Retention: drop oldest whole batches past the row-count / age
        bounds.  Views refold from their retained per-batch partials —
        host-side combines only, no recompute (unless the trim reaches
        into a view's bootstrap span, which forces its exact rebuild)."""
        config = _config()
        max_rows = (
            int(self.retention_rows) if self.retention_rows is not None
            else int(config.IngestRetentionRows.get())
        )
        max_age = (
            float(self.retention_age_s) if self.retention_age_s is not None
            else float(config.IngestRetentionAgeS.get())
        )
        now = time.monotonic()
        dropped: List[_BatchRecord] = []
        remaining = self._rows
        while len(self._batches) > 1 and (
            (max_rows > 0 and remaining > max_rows)
            or (max_age > 0.0 and now - self._batches[0].t_mono > max_age)
        ):
            rec = self._batches.popleft()
            remaining -= rec.rows
            dropped.append(rec)
        if not dropped:
            return 0
        import modin_tpu.pandas as mpd

        trimmed_rows = sum(rec.rows for rec in dropped)
        dropped_seqs = [rec.seq for rec in dropped]
        pending_dropped = {rec.seq for rec in dropped}
        self._pending = deque(
            rec for rec in self._pending if rec.seq not in pending_dropped
        )
        self._mirror = self._mirror.iloc[trimmed_rows:].reset_index(drop=True)
        self._rows -= trimmed_rows
        self._base_offset += trimmed_rows
        self._rebuild_frame_locked(mpd)
        if self.key is not None:
            self._key_index = {
                k: pos for k, pos in (
                    (row[self.key], i)
                    for i, row in enumerate(
                        self._mirror.to_dict(orient="records")
                    )
                )
            }
        needs_rebuild = False
        for view in self._views.values():
            if view.drop_batches(dropped_seqs):
                needs_rebuild = True
        if needs_rebuild:
            self._rebuild_views_locked()
        return trimmed_rows

    def _read_sync(self, view_name: str,
                   fresh_within_ms: Optional[float]) -> ViewRead:
        forced = False
        with span("ingest.read", layer="APP", feed=self.name,
                  view=view_name):
            with self._lock:
                view = self._views.get(view_name)
                if view is None:
                    raise IngestError(
                        f"feed {self.name!r} has no view {view_name!r}"
                    )
                lag = self._fold_lag_ms_locked()
                if fresh_within_ms is not None and lag > fresh_within_ms:
                    forced = True
                    # graftlint: disable=LOCK-BLOCKING -- _FOLD_DELAY_S is a test-only fault hook (default 0.0); folding under the feed lock IS the contract: views advance atomically w.r.t. appends and trims
                    self._fold_pending_locked()
                    lag = 0.0
                value = view.value(self._base_offset)
                pending_rows = sum(rec.rows for rec in self._pending)
                covered = self._rows - pending_rows
                result = ViewRead(
                    value, lag, forced, covered, self._base_offset,
                    view.folded_seq,
                )
        if forced:
            emit_metric("ingest.read.forced_fold", 1)
        else:
            emit_metric("ingest.read.served", 1)
        emit_metric("view.lag_ms", lag)
        from modin_tpu.observability import watch as _watch

        if _watch.WATCH_ON:
            _watch.observe_view_read(
                f"{self.name}/{view_name}", lag / 1e3
            )
        return result


# --------------------------------------------------------------------- #
# the feeds table
# --------------------------------------------------------------------- #

_FEEDS_LOCK = named_lock("ingest.feeds")
_feeds: Dict[str, Feed] = {}


def create_feed(name: str, schema: Dict[str, Any],
                key: Optional[str] = None,
                retention_rows: Optional[int] = None,
                retention_age_s: Optional[float] = None) -> Feed:
    """Create and register a named feed.  Requires ``MODIN_TPU_INGEST=1``
    (the subsystem is off by default — the zero-overhead contract).
    ``retention_rows`` / ``retention_age_s`` override the
    ``MODIN_TPU_INGEST_RETENTION_ROWS`` / ``_AGE_S`` defaults for this
    feed (0 = unbounded, None = inherit the knob)."""
    from modin_tpu import ingest as _ingest

    if not _ingest.INGEST_ON:
        raise IngestError(
            "continuous ingestion is disabled; set MODIN_TPU_INGEST=1 "
            "(config.IngestEnabled.enable())"
        )
    feed = Feed(name, schema, key=key, retention_rows=retention_rows,
                retention_age_s=retention_age_s)
    with _FEEDS_LOCK:
        if name in _feeds:
            raise IngestError(f"feed {name!r} already exists")
        _feeds[name] = feed
    return feed


def open_feed(name: str, schema: Optional[Dict[str, Any]] = None,
              key: Optional[str] = None,
              retention_rows: Optional[int] = None,
              retention_age_s: Optional[float] = None,
              durable: bool = False,
              durability_dir: Optional[str] = None) -> Feed:
    """:func:`create_feed`, plus the graftwal door.  ``durable=False``
    (the default) is exactly ``create_feed`` — the durability package is
    not even imported, so ordinary feeds stay bit-for-bit unchanged.

    ``durable=True`` lazy-imports ``modin_tpu.durability`` and opens a
    write-ahead-logged feed under ``durability_dir`` (default:
    ``MODIN_TPU_WAL_DIR``, else ``<MODIN_TPU_CACHE_DIR>/wal``).  A fresh
    feed needs a ``schema``; an existing durability directory is
    RECOVERED — newest valid checkpoint plus WAL-tail replay through the
    ordinary ingest path, run under the serving gate as a maintenance
    query — and ``schema`` may then be omitted (it is read from the
    feed's ``meta.json``; supplying a contradicting one is a typed
    ``DurabilityError``)."""
    if not durable:
        if schema is None:
            raise IngestError(
                f"feed {name!r}: a non-durable open_feed needs a schema"
            )
        return create_feed(name, schema, key=key,
                           retention_rows=retention_rows,
                           retention_age_s=retention_age_s)
    from modin_tpu import ingest as _ingest

    if not _ingest.INGEST_ON:
        raise IngestError(
            "continuous ingestion is disabled; set MODIN_TPU_INGEST=1 "
            "(config.IngestEnabled.enable())"
        )
    from modin_tpu import durability as _durability

    feed = _durability.open_durable_feed(
        name, schema, key=key, retention_rows=retention_rows,
        retention_age_s=retention_age_s, root_dir=durability_dir,
    )
    with _FEEDS_LOCK:
        conflict = name in _feeds
        if not conflict:
            _feeds[name] = feed
    if conflict:
        feed._wal.close()  # outside the table lock (join is blocking)
        raise IngestError(f"feed {name!r} already exists")
    return feed


def get_feed(name: str) -> Feed:
    with _FEEDS_LOCK:
        feed = _feeds.get(name)
    if feed is None:
        raise IngestError(f"no feed named {name!r}")
    return feed


def drop_feed(name: str) -> None:
    with _FEEDS_LOCK:
        feed = _feeds.pop(name, None)
    if feed is not None and feed._wal is not None:
        # final fsync + flusher join happen OUTSIDE the table lock
        feed._wal.close()


def feeds() -> List[str]:
    with _FEEDS_LOCK:
        return sorted(_feeds)


def max_fold_lag_ms() -> float:
    """The worst fold lag across every live feed — what the graftwatch
    ``fold_lag`` tripwire evaluates each sampler tick."""
    with _FEEDS_LOCK:
        snapshot = list(_feeds.values())
    lag = 0.0
    for feed in snapshot:
        lag = max(lag, feed.fold_lag_ms())
    return lag


def reset() -> None:
    """Drop every feed (tests)."""
    with _FEEDS_LOCK:
        snapshot = list(_feeds.values())
        _feeds.clear()
    for feed in snapshot:
        if feed._wal is not None:
            feed._wal.close()
