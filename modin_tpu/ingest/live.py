"""graftfeed registered live views: the fold algebra past scalar/groupby.

graftview (views/incremental.py) folds scalar reductions and groupby
partial tables across an appended tail.  A *registered* live view extends
that algebra to the three shapes the feature-store workload needs —

- **filtered** scalar aggregates: the predicate is applied to each
  micro-batch before its partial folds, so a view over ``x where y > 0``
  maintains exactly like a plain scalar view;
- **top-k**: each batch contributes its own ``nlargest(k)`` rows keyed by
  absolute row id.  A row outside its batch's top-k has >= k dominators in
  that batch alone, so it can never enter the global top-k — the bounded
  per-batch partials are *exact*, and ties replay pandas' ``keep="first"``
  order because partials concatenate in batch (= position) order;
- **windowed** time-bucketed aggregates: per-bucket scalar partials keyed
  ``floor(t / bucket_s)``.  A fold only touches the buckets present in the
  new batch, so closed buckets are frozen by construction; late rows fold
  exactly into their (old) bucket and are counted on the view.

Maintenance is two-level: every folded batch leaves a per-batch partial in
the view's log *and* is folded into the running state.  Reads are O(1)
off the running state; a retention trim drops the trimmed batches'
partials and refolds the state from the retained log — pure host-side
combine work, no row data touched, which is what "trim never invalidates
still-foldable view state" means mechanically.

Exactness matches graftview's documented contract: count/min/max/any/all
and integer sum/prod folds are bit-exact; float sum/prod/mean folds
re-associate the fp accumulation (fold order is batch order) within the
differential tolerance.  Everything else is refused at registration with
a typed :class:`~modin_tpu.ingest.errors.ViewNotIncrementalizable` —
never silently recomputed.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

from modin_tpu.ingest.errors import ViewNotIncrementalizable
from modin_tpu.views.incremental import (
    FOLDABLE_GROUPBYS,
    FOLDABLE_REDUCES,
    combine_groupby,
    combine_mean,
    combine_scalar,
)

#: scalar aggregates a live view may maintain (graftview's foldable set)
SCALAR_AGGS = frozenset(FOLDABLE_REDUCES)
#: groupby aggregates (graftview's foldable set: sum/count/min/max/mean/size)
GROUPBY_AGGS = frozenset(FOLDABLE_GROUPBYS)
#: windowed per-bucket aggregates (any/all excluded: no pandas groupby
#: ground truth worth promising for boolean buckets)
WINDOW_AGGS = frozenset({"sum", "count", "min", "max", "mean"})
#: predicate operators a filtered view accepts
PREDICATE_OPS = frozenset({">", ">=", "<", "<=", "==", "!="})

#: the aggregates graftview explicitly does NOT fold — named in refusals
NON_FOLDABLE_AGGS = frozenset(
    {"var", "std", "sem", "skew", "kurt", "median", "nunique", "mode",
     "quantile"}
)

_alloc_count = 0


def note_alloc() -> None:
    global _alloc_count
    _alloc_count += 1


def ingest_alloc_count() -> int:
    """graftfeed objects ever constructed (feeds, views, batch records) —
    the MODIN_TPU_INGEST=0 zero-alloc assertion counter."""
    return _alloc_count


# --------------------------------------------------------------------- #
# scalar partial algebra (shared by scalar / filtered / windowed kinds)
# --------------------------------------------------------------------- #

#: pandas empty-series reduction identities, per aggregate
_EMPTY_SCALAR = {
    "sum": np.float64(0.0),
    "count": np.int64(0),
    "prod": np.float64(1.0),
    "min": np.float64(np.nan),
    "max": np.float64(np.nan),
    "mean": np.float64(np.nan),
    "any": np.bool_(False),
    "all": np.bool_(True),
}


def _scalar_partial(series: Any, agg: str) -> Any:
    """One batch's contribution for a scalar aggregate: the pandas result
    itself, except mean which carries its (mean, valid-count) pair.

    An empty series (a filtered batch matching zero predicate rows) has
    no min/max contribution: pandas answers NaN there, which is NOT the
    fold identity — folded into an int-dtyped running state it would
    poison the state and the next fmin/fmax fold would drop all history.
    ``None`` is the skip sentinel :func:`_scalar_fold` understands;
    :func:`_scalar_value` still answers the pandas empty reduction for an
    all-empty view.
    """
    if agg == "mean":
        return (series.mean(), int(series.count()))
    if len(series) == 0 and agg in ("min", "max"):
        return None
    return getattr(series, agg)()


def _scalar_fold(agg: str, state: Any, part: Any) -> Any:
    if part is None:  # empty-batch partial: the fold identity, skip
        return state
    if state is None:
        return part
    if agg == "mean":
        mean, k = combine_mean(state[0], state[1], part[0], part[1])
        return (mean, k)
    return combine_scalar(agg, True, state, part)


def _scalar_value(agg: str, state: Any) -> Any:
    if state is None:
        return _EMPTY_SCALAR[agg]
    if agg == "mean":
        return np.float64(state[0]) if state[1] else np.float64(np.nan)
    return state


# --------------------------------------------------------------------- #
# the view
# --------------------------------------------------------------------- #


class LiveView:
    """One registered, incrementally-maintained query over a feed.

    Construction validates the plan and refuses non-incrementalizable
    shapes; :meth:`fold_batch` absorbs one micro-batch; :meth:`value`
    answers O(1) off the running state; :meth:`drop_batches` +
    :meth:`refold` service retention trims; :meth:`rebuild` collapses the
    whole log to one bootstrap partial (upserts, bootstrap-intersecting
    trims — the exact-rebuild escape hatch).
    """

    def __init__(self, feed: str, name: str, plan: Dict[str, Any],
                 schema: Dict[str, Any]) -> None:
        note_alloc()
        self.feed = feed
        self.name = name
        self.plan = dict(plan)
        self.kind = self._validate(schema)
        #: bootstrap partial covering every batch with seq <= _bootstrap_seq
        self._bootstrap: Any = None
        self._bootstrap_seq = -1
        #: seq -> per-batch partial, insertion order = fold (= batch) order
        self._partials: "OrderedDict[int, Any]" = OrderedDict()
        self._state: Any = None
        self.folded_seq = -1
        self.folds = 0
        self.rebuilds = 0
        self.late_buckets = 0

    # -- validation ---------------------------------------------------- #

    def _refuse(self, reason: str, detail: str = "") -> None:
        raise ViewNotIncrementalizable(self.name, reason, detail)

    def _need_column(self, col: Any, schema: Dict[str, Any]) -> None:
        if col not in schema:
            self._refuse("unknown_column", f"column {col!r} not in feed schema")

    def _validate(self, schema: Dict[str, Any]) -> str:
        plan = self.plan
        kind = plan.get("kind")
        if kind not in ("scalar", "groupby", "filtered", "topk", "windowed"):
            self._refuse("unknown_kind", f"kind={kind!r}")
        self._need_column(plan.get("column"), schema)
        if kind in ("scalar", "filtered", "groupby", "windowed"):
            agg = plan.get("agg")
            allowed = {
                "scalar": SCALAR_AGGS, "filtered": SCALAR_AGGS,
                "groupby": GROUPBY_AGGS, "windowed": WINDOW_AGGS,
            }[kind]
            if agg not in allowed:
                if kind == "filtered" and agg is None:
                    # an agg-less filtered registration is a row-set view:
                    # its state is O(matching rows), unbounded under a
                    # sustained stream — refuse instead of pretending
                    self._refuse(
                        "row_view_unbounded",
                        "filtered views need an aggregate; bare row sets "
                        "grow without bound under continuous ingest",
                    )
                known = (
                    agg in NON_FOLDABLE_AGGS or agg in SCALAR_AGGS
                    or agg in GROUPBY_AGGS
                )
                if known:
                    self._refuse(
                        "non_foldable_agg",
                        f"agg={agg!r} has no exact fold for kind={kind!r}",
                    )
                self._refuse(
                    "unknown_agg", f"agg={agg!r} is not a recognized aggregate"
                )
        if kind == "filtered":
            pred = plan.get("predicate")
            if (
                not isinstance(pred, (tuple, list)) or len(pred) != 3
                or pred[1] not in PREDICATE_OPS
            ):
                self._refuse("bad_predicate", f"predicate={pred!r}")
            self._need_column(pred[0], schema)
        if kind == "groupby":
            self._need_column(plan.get("by"), schema)
        if kind == "topk":
            k = plan.get("k")
            if not isinstance(k, int) or isinstance(k, bool) or k <= 0:
                self._refuse("bad_k", f"k={k!r}")
            if np.dtype(schema[plan["column"]]).kind not in "iuf":
                self._refuse(
                    "bad_column_dtype",
                    f"top-k needs a numeric column, got "
                    f"{schema[plan['column']]}",
                )
        if kind == "windowed":
            tcol = plan.get("time_column")
            if tcol is None:
                self._refuse("bad_window", "time_column is required")
            self._need_column(tcol, schema)
            bucket = plan.get("bucket_s")
            if not isinstance(bucket, (int, float)) or bucket <= 0:
                self._refuse("bad_window", f"bucket_s={bucket!r}")
            if np.dtype(schema[tcol]).kind not in "iuf":
                self._refuse(
                    "bad_window",
                    f"time column must be numeric seconds, got "
                    f"{schema[tcol]}",
                )
        return kind

    # -- per-batch partials -------------------------------------------- #

    def _partial(self, pdf: Any, abs_start: int) -> Any:
        plan = self.plan
        col = plan["column"]
        if self.kind == "scalar":
            return _scalar_partial(pdf[col], plan["agg"])
        if self.kind == "filtered":
            pcol, op, val = plan["predicate"]
            lhs = pdf[pcol]
            mask = {
                ">": lhs > val, ">=": lhs >= val, "<": lhs < val,
                "<=": lhs <= val, "==": lhs == val, "!=": lhs != val,
            }[op]
            return _scalar_partial(pdf[col][mask], plan["agg"])
        if self.kind == "groupby":
            by, agg = plan["by"], plan["agg"]
            grouped = pdf.groupby(by)[col]
            if agg == "mean":
                return (grouped.mean(), grouped.count())
            if agg == "size":
                return pdf.groupby(by).size()
            return getattr(grouped, agg)()
        if self.kind == "topk":
            s = pdf[col].copy()
            s.index = np.arange(abs_start, abs_start + len(s), dtype=np.int64)
            return s.nlargest(plan["k"], keep="first")
        # windowed: bucket -> scalar partial (NaN timestamps drop, matching
        # pandas groupby dropna)
        tcol, agg = plan["time_column"], plan["agg"]
        bucket_s = plan["bucket_s"]
        ts = pdf[tcol]
        keep = ts.notna()
        sub, ts = pdf[col][keep], ts[keep]
        keys = np.floor(ts.to_numpy(dtype=np.float64) / bucket_s).astype(
            np.int64
        )
        out: Dict[int, Any] = {}
        for key, series in sub.groupby(keys):
            out[int(key)] = _scalar_partial(series, agg)
        return out

    def _fold(self, state: Any, part: Any) -> Any:
        plan = self.plan
        if self.kind in ("scalar", "filtered"):
            return _scalar_fold(plan["agg"], state, part)
        if self.kind == "groupby":
            if state is None:
                return part
            agg = plan["agg"]
            if agg == "mean":
                means, counts = combine_groupby(
                    "mean", state[0], part[0], state[1], part[1]
                )
                return (means, counts)
            combined, _ = combine_groupby(
                "sum" if agg == "size" else agg, state, part
            )
            return combined
        if self.kind == "topk":
            if state is None:
                return part.copy()
            import pandas

            # state rows all precede the new batch's absolute ids, so the
            # concat order replays pandas keep="first" tie order exactly
            return pandas.concat([state, part]).nlargest(
                self.plan["k"], keep="first"
            )
        # windowed
        if state is None:
            state = {}
        else:
            state = dict(state)
        if state and part:
            newest = max(state)
            self.late_buckets += sum(1 for b in part if b < newest)
        agg = plan["agg"]
        for bucket, p in part.items():
            state[bucket] = _scalar_fold(agg, state.get(bucket), p)
        return state

    # -- maintenance entry points (feed lock held) --------------------- #

    def fold_batch(self, seq: int, pdf: Any, abs_start: int) -> None:
        part = self._partial(pdf, abs_start)
        self._partials[seq] = part
        self._state = self._fold(self._state, part)
        self.folded_seq = seq
        self.folds += 1

    def refold(self) -> None:
        """Rebuild the running state from bootstrap + retained partials —
        pure host-side combines, no row data (retention trims)."""
        state = None
        if self._bootstrap is not None:
            state = self._fold(None, self._bootstrap)
        for part in self._partials.values():
            state = self._fold(state, part)
        self._state = state

    def drop_batches(self, seqs: Any) -> bool:
        """Forget trimmed batches' partials; returns True when the
        bootstrap partial was invalidated (caller must :meth:`rebuild`)."""
        for seq in seqs:
            self._partials.pop(seq, None)
        if self._bootstrap is not None and any(
            seq <= self._bootstrap_seq for seq in seqs
        ):
            self._bootstrap = None
            self._bootstrap_seq = -1
            return True
        self.refold()
        return False

    def rebuild(self, pdf: Any, abs_start: int, through_seq: int) -> None:
        """Collapse the whole retained frame into one bootstrap partial —
        the exact-rebuild path for upserts (in-place value changes no fold
        can express; the top-k eviction-boundary ambiguity lands here too)
        and bootstrap-intersecting trims."""
        self._partials.clear()
        self._bootstrap = self._partial(pdf, abs_start) if len(pdf) else None
        self._bootstrap_seq = through_seq
        self.folded_seq = through_seq
        self.rebuilds += 1
        self.refold()

    # -- reads --------------------------------------------------------- #

    def value(self, base_offset: int = 0) -> Any:
        """The maintained answer, shaped like its pandas ground truth.

        Scalar/filtered -> numpy scalar; groupby -> key-sorted Series;
        topk -> value-descending Series positioned against the CURRENT
        retained frame (absolute ids shifted by ``base_offset``);
        windowed -> bucket-index-sorted Series.
        """
        import pandas

        plan = self.plan
        if self.kind in ("scalar", "filtered"):
            return _scalar_value(plan["agg"], self._state)
        if self.kind == "groupby":
            if self._state is None:
                return pandas.Series(dtype=np.float64)
            if plan["agg"] == "mean":
                return self._state[0].copy()
            return self._state.copy()
        if self.kind == "topk":
            if self._state is None:
                return pandas.Series(dtype=np.float64)
            out = self._state.copy()
            out.index = out.index - base_offset
            return out
        if self._state is None:
            return pandas.Series(dtype=np.float64)
        agg = plan["agg"]
        buckets = sorted(self._state)
        return pandas.Series(
            [_scalar_value(agg, self._state[b]) for b in buckets],
            index=np.asarray(buckets, dtype=np.int64),
        )
