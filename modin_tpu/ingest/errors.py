"""graftfeed typed errors.

Deliberate leaf module (no modin_tpu imports): the serving and watch
layers may reference these types without pulling the ingest machinery in.
"""

from __future__ import annotations

from typing import Any, Optional


class IngestError(Exception):
    """Base class for every graftfeed error."""


class IngestRejected(IngestError):
    """A micro-batch failed feed admission: schema/dtype validation, a
    malformed payload, or a key violation.  ``reason`` is a stable slug
    (``missing_column`` / ``extra_column`` / ``dtype`` / ``malformed`` /
    ``unsupported_type`` / ``duplicate_key`` / ``key_exists`` /
    ``not_keyed``) so callers can branch without parsing the message."""

    def __init__(
        self,
        feed: str,
        reason: str,
        detail: str = "",
        column: Optional[str] = None,
        expected: Any = None,
        got: Any = None,
    ) -> None:
        self.feed = feed
        self.reason = reason
        self.column = column
        self.expected = expected
        self.got = got
        bits = [f"feed {feed!r} rejected batch: {reason}"]
        if column is not None:
            bits.append(f"column={column!r}")
        if expected is not None:
            bits.append(f"expected={expected}")
        if got is not None:
            bits.append(f"got={got}")
        if detail:
            bits.append(detail)
        super().__init__(" ".join(bits))


class ViewNotIncrementalizable(IngestError):
    """``register_view`` refused the plan: its maintenance under appends
    has no exact fold.  Never silently recomputed — the caller either
    changes the plan or runs the query ad hoc.  ``reason`` is a stable
    slug (``unknown_kind`` / ``unknown_column`` / ``non_foldable_agg`` /
    ``unknown_agg`` / ``row_view_unbounded`` / ``bad_predicate`` /
    ``bad_k`` / ``bad_column_dtype`` / ``bad_window``);
    docs/architecture.md carries the decision table."""

    def __init__(self, name: str, reason: str, detail: str = "") -> None:
        self.name = name
        self.reason = reason
        msg = f"view {name!r} is not incrementalizable: {reason}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
