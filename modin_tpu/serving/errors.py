"""Typed serving errors — the defined failure modes under concurrent load.

The serving contract (docs/architecture.md §"Serving & admission control")
is that a query submitted through the admission gate has exactly three
outcomes: it completes bit-exact, it is **rejected** before consuming
device resources (:class:`QueryRejected`, with a retry-after hint so a
well-behaved client backs off instead of hammering), or it is **aborted**
when its latency budget expires (:class:`DeadlineExceeded`).  Nothing else
is a legal serving outcome — an untyped exception escaping the gate is a
bug, and the chaos acceptance suite (scripts/serving_smoke.py) asserts it.

These are *serving* decisions, deliberately disjoint from the
infrastructure taxonomy in core/execution/resilience.py: a
``DeviceFailure`` means the accelerator runtime misbehaved; a
``ServingError`` means the system is protecting itself (or the caller's
budget) on purpose.  ``classify_device_error`` therefore never captures
them — they propagate through the engine seam untouched.
"""

from __future__ import annotations

from typing import Optional


class ServingError(RuntimeError):
    """Base for typed serving outcomes (admission control / deadlines)."""

    kind = "serving"


class QueryRejected(ServingError):
    """The admission gate refused the query before any work ran.

    ``reason`` is one of the shed causes (``queue_full``,
    ``tenant_throttled``, ``tenant_unhealthy``, ``queue_wait_deadline``);
    ``retry_after_s`` is the gate's estimate of when capacity returns —
    a load balancer maps it onto HTTP 429 + Retry-After.
    """

    kind = "rejected"

    def __init__(
        self, message: str, reason: str = "queue_full",
        retry_after_s: Optional[float] = None,
    ):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(ServingError):
    """The query's latency budget expired mid-flight and it was aborted.

    Raised at the seam boundaries the cancellation token is checked at
    (engine attempt start, retry/backoff sleeps, spill/evict passes,
    fused-chain materialization, plan lowering) — so the overshoot past
    the deadline is bounded by one engine attempt, never by the query's
    full runtime.  ``where`` names the seam that observed expiry.
    """

    kind = "deadline"

    def __init__(
        self, message: str, deadline_s: float = 0.0, where: str = "",
    ):
        super().__init__(message)
        self.deadline_s = deadline_s
        self.where = where
