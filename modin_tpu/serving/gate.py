"""graftgate: the bounded admission gate + the ``submit`` query front end.

Every robustness layer below this one (resilience retry/breakers, graftguard
lineage recovery, device-memory admission at ``deploy``) assumes one query
at a time.  This module is the multi-tenant front door that makes hundreds
of concurrent sessions a *defined* workload instead of an unbounded
pile-up:

- **Admission + backpressure.**  At most ``MODIN_TPU_SERVING_MAX_CONCURRENT``
  queries run; each admitted query *reserves* its estimated device bytes
  (the tenant's graftcost EWMA, or the conservative
  ``device_budget / max_concurrent`` default for unknown tenants) against
  the ``_DeviceLedger`` budget, so admission decisions happen BEFORE work
  lands on the device rather than after an OOM.  Excess load waits in a
  queue bounded by ``MODIN_TPU_SERVING_QUEUE_DEPTH``; past that, queries
  are **shed** with a typed :class:`~.errors.QueryRejected` carrying a
  retry-after hint.  Nothing ever waits unboundedly by accident: a queued
  query with a deadline spends its budget waiting and aborts typed.

- **Deadlines.**  ``deadline_ms`` (default
  ``MODIN_TPU_SERVING_DEFAULT_DEADLINE_MS``) becomes a
  :class:`~.context.CancellationToken` threaded through the engine seams;
  see serving/context.py for the seam-boundary check sites and the
  bounded-overshoot contract.

- **Fairness + health.**  Weighted token buckets and per-tenant circuit
  breakers (serving/tenants.py): a tenant past its weighted rate is
  throttled, a tenant whose queries keep striking device-path breakers is
  quarantined for the breaker cooldown — never the whole system.  When
  the gate is saturated, the wake order among queued tenants is
  weighted-fair (fewest in-flight per weight unit first), not FIFO-by-luck.

- **Degraded mode.**  When a device-path breaker is OPEN or the device
  ledger is past ``MODIN_TPU_SERVING_DEGRADED_HIGH_WATER`` of its budget,
  admitted queries are routed to the host/pandas path (``@device_path``
  families short-circuit, exactly like an open breaker) with a
  ``serving.degraded`` metric — queueing behind a sick device is the one
  thing a latency-budgeted query must never do.

Zero-overhead-when-off: ``MODIN_TPU_SERVING=0`` (the default) makes
``submit`` a direct call of the query function — no token, no scope, no
allocation (asserted via ``context.context_alloc_count``), and the seam
checks elsewhere see ``context.CONTEXT_ON`` False.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import meters as graftmeter
from modin_tpu.observability import spans as graftscope
from modin_tpu.observability import watch as _watch
from modin_tpu.serving import context as _context
from modin_tpu.serving import tenants as _tenants
from modin_tpu.serving.errors import DeadlineExceeded, QueryRejected, ServingError

#: Module-level fast path: the MODIN_TPU_SERVING switch.
SERVING_ON: bool = False

#: Fallback retry-after hint (seconds) for a tenant with no wall history.
_DEFAULT_RETRY_AFTER_S = 0.05

#: Conservative cost default when no device budget is configured: admission
#: then bounds only concurrency/queue/fairness, not bytes.
_NO_BUDGET_COST = 0.0


def _device_budget() -> Optional[int]:
    from modin_tpu.core import memory as _memory

    return _memory._DEVICE_BUDGET


def _device_resident() -> int:
    from modin_tpu.core import memory as _memory

    return _memory.device_ledger.total_bytes()


def _device_breaker_open() -> bool:
    """Is any *device-path* breaker currently OPEN?  (Tenant/ad-hoc breakers
    do not count: a sick tenant must not degrade everyone else's queries.)"""
    from modin_tpu.core.execution.resilience import (
        DEVICE_PATH_FAMILIES,
        breaker_snapshot,
    )

    return any(
        state == "open"
        for name, state in breaker_snapshot().items()
        if name in DEVICE_PATH_FAMILIES
    )


class _Waiter:
    """One queued admission request (its own event: targeted wakeups)."""

    __slots__ = ("tenant", "weight", "cost", "seq", "event")

    def __init__(self, tenant: str, weight: float, cost: float, seq: int):
        self.tenant = tenant
        self.weight = weight
        self.cost = cost
        self.seq = seq
        self.event = threading.Event()


class Permit:
    """Proof of admission; carries the per-query serving decisions."""

    __slots__ = (
        "tenant", "cost_bytes", "degraded", "queue_wait_s", "admitted_at",
    )

    def __init__(
        self, tenant: str, cost_bytes: float, degraded: bool,
        queue_wait_s: float,
    ):
        self.tenant = tenant
        self.cost_bytes = cost_bytes
        self.degraded = degraded
        self.queue_wait_s = queue_wait_s
        self.admitted_at = time.monotonic()


class AdmissionGate:
    """The process-wide bounded admission gate (one instance, module-level)."""

    def __init__(self) -> None:
        self._lock = named_lock("serving.gate")
        self._running = 0
        self._reserved_bytes = 0.0
        self._inflight: dict = {}  # tenant -> running count
        self._waiters: list = []
        self._seq = 0
        # lifetime counters for snapshots / the bench section
        self.admitted = 0
        self.queued = 0
        self.shed = 0
        self.degraded_count = 0
        self.completed = 0
        # recent shed timestamps (monotonic): the windowed typed-shed rate
        # graftfleet uses as its backpressure signal when redistributing
        # drained tenants across survivors
        self._shed_times: deque = deque(maxlen=256)

    # -- config ---------------------------------------------------------- #

    @staticmethod
    def _max_concurrent() -> int:
        from modin_tpu.config import ServingMaxConcurrent

        return max(int(ServingMaxConcurrent.get()), 1)

    @staticmethod
    def _queue_depth() -> int:
        from modin_tpu.config import ServingQueueDepth

        return max(int(ServingQueueDepth.get()), 0)

    @staticmethod
    def _high_water() -> float:
        from modin_tpu.config import ServingDegradedHighWater

        return float(ServingDegradedHighWater.get())

    # -- admission ------------------------------------------------------- #

    def _cost_estimate(self, tenant: str) -> float:
        budget = _device_budget()
        if budget is None:
            return _NO_BUDGET_COST
        default = budget / self._max_concurrent()
        return _tenants.registry.cost_estimate(tenant, default)

    def _fits(self, cost: float) -> bool:
        """Slot + byte headroom check (caller holds the lock)."""
        if self._running >= self._max_concurrent():
            return False
        budget = _device_budget()
        if budget is None or self._running == 0:
            # admit-one rule: a query estimated past the whole budget must
            # still be runnable alone (deploy-seam spill handles the rest);
            # otherwise it would queue forever behind nothing
            return True
        return self._reserved_bytes + cost <= budget

    def _next_waiter(self) -> Optional["_Waiter"]:
        """Weighted-fair head-of-queue: fewest in-flight per weight unit,
        FIFO within a tie (caller holds the lock)."""
        if not self._waiters:
            return None
        return min(
            self._waiters,
            key=lambda w: (
                self._inflight.get(w.tenant, 0) / max(w.weight, 1e-9),
                w.seq,
            ),
        )

    def _wake(self) -> None:
        """Signal the waiter whose turn it is (caller holds the lock)."""
        head = self._next_waiter()
        if head is not None:
            head.event.set()

    def _shed(self, tenant: str, reason: str, retry_after_s: float) -> None:
        # called WITHOUT the gate lock (metric fan-out must never run
        # under it); only the counter bump takes it
        with self._lock:
            self.shed += 1
            self._shed_times.append(time.monotonic())
        emit_metric("serving.shed", 1)
        emit_metric(f"serving.tenant.{_tenants.sanitize(tenant)}.{reason}", 1)
        _tenants.registry.note_shed(tenant)
        raise QueryRejected(
            f"query for tenant {tenant!r} rejected ({reason}); retry after "
            f"~{retry_after_s * 1e3:.0f}ms",
            reason=reason,
            retry_after_s=retry_after_s,
        )

    def acquire(
        self,
        tenant: str,
        token: Optional[_context.CancellationToken],
    ) -> Permit:
        """Admit, queue, or shed — the serving decision tree.

        Order: tenant health (breaker) -> tenant rate (token bucket) ->
        capacity (slots + byte headroom) -> bounded queue -> shed.
        """
        breaker = _tenants.breaker_for(tenant)
        if not breaker.allow():
            from modin_tpu.config import ResilienceBreakerCooldownS

            self._shed(
                tenant, "unhealthy", float(ResilienceBreakerCooldownS.get())
            )
        spent, retry_after = _tenants.registry.try_spend(tenant)
        if not spent:
            self._shed(tenant, "throttled", retry_after)

        cost = self._cost_estimate(tenant)
        weight = _tenants.registry.get(tenant).weight
        wait_t0 = time.perf_counter()
        waiter: Optional[_Waiter] = None
        queue_len = None
        with self._lock:
            if self._fits(cost) and not self._waiters:
                self._reserve_locked(tenant, cost)
            elif len(self._waiters) >= self._queue_depth():
                queue_len = len(self._waiters)
            else:
                self._seq += 1
                waiter = _Waiter(tenant, weight, cost, self._seq)
                self._waiters.append(waiter)
                self.queued += 1
        if waiter is None and queue_len is None:
            return self._finalize_admit(tenant, cost, 0.0)
        if queue_len is not None:
            # queue is full at max concurrency: the soonest realistic
            # retry is one queue drain away — and the tenant's rate token
            # comes back: this is a capacity verdict, not a rate one, and
            # a polite retrying client must not drain its bucket into a
            # bogus "throttled" quarantine
            wall = _tenants.registry.wall_hint(tenant, _DEFAULT_RETRY_AFTER_S)
            hint = wall * (1 + queue_len / self._max_concurrent())
            _tenants.registry.refund(tenant)
            self._shed(tenant, "queue_full", hint)
        emit_metric("serving.queued", 1)
        try:
            while True:
                remaining = token.remaining_s() if token is not None else None
                if remaining is not None and remaining <= 0:
                    # budget spent in the queue: typed abort, never a hang
                    # (the rate token comes back — nothing ever ran)
                    _tenants.registry.refund(tenant)
                    emit_metric(
                        f"serving.tenant.{_tenants.sanitize(tenant)}.deadline",
                        1,
                    )
                    token.check("serving.queue")  # raises DeadlineExceeded
                    raise DeadlineExceeded(  # unreachable backstop
                        "deadline expired while queued", where="serving.queue"
                    )
                waiter.event.wait(
                    timeout=min(remaining, 0.5) if remaining is not None else 0.5
                )
                with self._lock:
                    waiter.event.clear()
                    head = self._next_waiter()
                    if head is waiter and self._fits(waiter.cost):
                        self._waiters.remove(waiter)
                        waiter = None
                        wait_s = time.perf_counter() - wait_t0
                        self._reserve_locked(tenant, cost)
                        # capacity may admit more than one queued query
                        self._wake()
                    elif head is not None and head is not waiter:
                        # the wakeup landed on the wrong waiter (the fair
                        # head changed after release() signalled us): pass
                        # it on, or freed capacity idles until the next
                        # 0.5s poll — straight into admitted-p99
                        head.event.set()
                if waiter is None:
                    emit_metric("serving.queue_wait_s", wait_s)
                    return self._finalize_admit(tenant, cost, wait_s)
        finally:
            if waiter is not None:  # deadline abort: leave the queue clean
                with self._lock:
                    if waiter in self._waiters:
                        self._waiters.remove(waiter)
                    self._wake()

    def _reserve_locked(self, tenant: str, cost: float) -> None:
        """Counter/reservation mutations only — the caller holds the gate
        lock, so nothing here may fan out to metric handlers, scan breaker
        state, or touch other subsystems' locks."""
        self._running += 1
        self._reserved_bytes += cost
        self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
        self.admitted += 1

    def _finalize_admit(
        self, tenant: str, cost: float, queue_wait_s: float
    ) -> Permit:
        """The admission's observable side — degraded-route evaluation
        (breaker snapshot + ledger read) and metric fan-out — run WITHOUT
        the gate lock: one slow metric handler must not stall every other
        thread's admission decision."""
        _tenants.registry.note_admitted(tenant)
        degraded = self._degraded_route()
        if degraded:
            with self._lock:
                self.degraded_count += 1
            emit_metric("serving.degraded", 1)
            emit_metric(
                f"serving.tenant.{_tenants.sanitize(tenant)}.degraded", 1
            )
        emit_metric("serving.admit", 1)
        emit_metric(f"serving.tenant.{_tenants.sanitize(tenant)}.admit", 1)
        return Permit(tenant, cost, degraded, queue_wait_s)

    def _degraded_route(self) -> bool:
        """Route this admission to the host path?  (breaker-open device, or
        ledger past the high-water fraction of its budget)."""
        if _device_breaker_open():
            return True
        budget = _device_budget()
        if budget is None:
            return False
        return _device_resident() >= self._high_water() * budget

    def release(self, permit: Permit) -> None:
        with self._lock:
            self._running = max(self._running - 1, 0)
            self._reserved_bytes = max(
                self._reserved_bytes - permit.cost_bytes, 0.0
            )
            count = self._inflight.get(permit.tenant, 0) - 1
            if count <= 0:
                self._inflight.pop(permit.tenant, None)
            else:
                self._inflight[permit.tenant] = count
            self.completed += 1
            self._wake()
        _tenants.registry.note_release(permit.tenant)

    # -- introspection --------------------------------------------------- #

    def _shed_rate_locked(self, window_s: float = 5.0) -> float:
        """Typed sheds per second over the trailing window (caller holds
        the lock).  This is the routable backpressure signal graftfleet
        weighs survivors by when redistributing drained tenants."""
        cutoff = time.monotonic() - window_s
        recent = sum(1 for t in self._shed_times if t >= cutoff)
        return recent / window_s

    def shed_rate(self, window_s: float = 5.0) -> float:
        with self._lock:
            return self._shed_rate_locked(window_s)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": SERVING_ON,
                "running": self._running,
                "queued": len(self._waiters),
                "reserved_bytes": self._reserved_bytes,
                "admitted": self.admitted,
                "ever_queued": self.queued,
                "shed": self.shed,
                "shed_rate": self._shed_rate_locked(),
                "degraded": self.degraded_count,
                "completed": self.completed,
                "max_concurrent": self._max_concurrent(),
                "queue_depth": self._queue_depth(),
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._waiters.clear()
            self._running = 0
            self._reserved_bytes = 0.0
            self._inflight.clear()
            self._seq = 0
            self.admitted = self.queued = self.shed = 0
            self.degraded_count = self.completed = 0
            self._shed_times.clear()


gate = AdmissionGate()


def counter_sample() -> tuple:
    """``(queued, running)`` read lock-free: racy-by-design telemetry
    reads (the chrome-trace counter tracks sample this at every span
    finish and the graftwatch sampler every tick) — taking the gate lock
    here would serialize traced threads against the admission path."""
    return (len(gate._waiters), gate._running)

#: Reentrancy marker: depth of submit() frames on this thread.  An
#: admitted query that submits another query must NOT go back through the
#: gate — at saturation it would queue behind the slot its own caller
#: holds and deadlock (MAX_CONCURRENT=1 + nested submit = permanent hang
#: without this).  The inner call runs under the outer permit: its own
#: deadline token still nests via the context stack, but slots, tenant
#: buckets, and byte reservations belong to the outer admission.
_tls = threading.local()


def serving_snapshot() -> dict:
    """Gate + tenant state for dashboards / debugging.

    With graftwatch running, the per-tenant SLO burn verdicts ride along
    under ``"slo"`` — an ADVISORY health signal next to the breaker
    states (the gate surfaces it, it never sheds because of it)."""
    snap = gate.snapshot()
    snap["tenants"] = _tenants.registry.snapshot()
    if _watch.WATCH_ON:
        snap["slo"] = _watch.slo_health()
    # coordinator-aware: with a graftfleet coordinator live in THIS process
    # the replica table rides along (sys.modules probe — reading a snapshot
    # must never import, let alone start, the fleet machinery)
    import sys as _sys

    _fleet = _sys.modules.get("modin_tpu.fleet")
    if _fleet is not None and _fleet.FLEET_ON:
        coordinator = _fleet.get_coordinator()
        if coordinator is not None:
            snap["fleet"] = coordinator.snapshot()
    return snap


# ---------------------------------------------------------------------- #
# the query front end
# ---------------------------------------------------------------------- #


def submit(
    fn: Callable[..., Any],
    *args: Any,
    tenant: str = "default",
    deadline_ms: Optional[float] = None,
    label: Optional[str] = None,
    **kwargs: Any,
) -> Any:
    """Run one query under admission control, returning its result.

    With serving off (``MODIN_TPU_SERVING=0``, the default) this is a
    direct call of ``fn`` — bit-for-bit the single-query behavior, zero
    allocations.  With serving on, the call is admitted (or typed-rejected)
    by the gate, runs under a :class:`~.context.QueryContext` carrying its
    deadline/cancellation token and degraded-route flag, and is accounted
    in a ``query_stats`` scope whose rollup feeds the tenant's cost EWMA
    and health breaker.

    ``deadline_ms=None`` takes ``MODIN_TPU_SERVING_DEFAULT_DEADLINE_MS``
    (0 = unbounded); ``deadline_ms=0`` forces unbounded for this query.
    """
    if not SERVING_ON:
        return fn(*args, **kwargs)
    if deadline_ms is None:
        from modin_tpu.config import ServingDefaultDeadlineMs

        deadline_ms = float(ServingDefaultDeadlineMs.get())
    qlabel = label or getattr(fn, "__name__", "query")
    token = (
        _context.CancellationToken(deadline_ms / 1e3, qlabel)
        if deadline_ms and deadline_ms > 0
        else None
    )
    if getattr(_tls, "depth", 0) > 0:
        # nested submit on an already-admitted thread: run under the outer
        # permit (re-entering the gate would deadlock at saturation); the
        # inner deadline/degraded context still nests and unwinds
        outer = _context.current_context()
        ctx = _context.QueryContext(
            token if token is not None else (outer.token if outer else None),
            outer.degraded if outer is not None else False,
            tenant,
            qlabel,
        )
        previous = _context.enter_context(ctx)
        try:
            return fn(*args, **kwargs)
        finally:
            _context.exit_context(previous)
    sp = None
    if graftscope.TRACE_ON:
        sp = graftscope.start_span(
            "serving.admit",
            layer="PANDAS-API",
            attrs={"tenant": tenant, "label": qlabel},
        )
    try:
        permit = gate.acquire(tenant, token)
    except ServingError:
        if sp is not None:
            graftscope.finish_span(sp, status="error")
        raise
    if sp is not None:
        sp.attrs["queue_wait_s"] = round(permit.queue_wait_s, 6)
        sp.attrs["degraded"] = permit.degraded
        graftscope.finish_span(sp)
    ctx = _context.QueryContext(token, permit.degraded, tenant, qlabel)
    previous = _context.enter_context(ctx)
    _tls.depth = getattr(_tls, "depth", 0) + 1
    t0 = time.perf_counter()
    stats = None
    failure_kind = None
    try:
        with graftscope.span(
            "serving.query",
            layer="PANDAS-API",
            tenant=tenant,
            label=qlabel,
            degraded=permit.degraded,
        ):
            with graftmeter.query_stats(qlabel) as stats:
                return fn(*args, **kwargs)
    except ServingError:
        failure_kind = "serving"
        raise
    except Exception as err:
        from modin_tpu.core.execution.resilience import classify_device_error

        if classify_device_error(err) is not None:
            failure_kind = "device"
        raise
    finally:
        _tls.depth -= 1
        _context.exit_context(previous)
        gate.release(permit)
        wall_s = time.perf_counter() - t0
        emit_metric("serving.query_wall_s", wall_s)
        if _watch.WATCH_ON:
            # per-tenant latency series for graftwatch SLO burn tracking
            # (one module-attribute check when watch is off)
            _watch.observe_query(tenant, wall_s, failure_kind)
        _finish_accounting(tenant, stats, wall_s, failure_kind)


def _finish_accounting(
    tenant: str, stats: Any, wall_s: float, failure_kind: Optional[str]
) -> None:
    """Fold the query's rollup into tenant cost/health state (never raises
    into the caller's result path)."""
    try:
        cost_bytes = 0.0
        trips = 0
        if stats is not None:
            if getattr(stats, "stream_windows", 0):
                # graftstream: a windowed query's est_bytes accumulates the
                # whole dataset's estimated traffic across windows, but its
                # device footprint is the window double-buffer — bill the
                # measured HBM high-water so out-of-core queries stop
                # inflating the tenant's EWMA into auto-shed territory
                cost_bytes = float(stats.hbm_high_water or 0.0)
            else:
                cost_bytes = float(stats.est_bytes or 0.0) or float(
                    stats.hbm_high_water or 0.0
                )
            trips = int(getattr(stats, "breaker_trips", 0))
        _tenants.registry.observe(tenant, cost_bytes, wall_s)
        breaker = _tenants.breaker_for(tenant)
        if failure_kind == "device" or trips > 0:
            # the query kept striking device paths (or died on a terminal
            # device failure): one strike for the tenant's health breaker
            breaker.record_failure()
        elif failure_kind is None:
            breaker.record_success()
        outcome = {
            None: "complete",
            "serving": "deadline",
            "device": "device_failure",
        }.get(failure_kind, "complete")
        emit_metric(
            f"serving.tenant.{_tenants.sanitize(tenant)}.{outcome}", 1
        )
    except Exception:
        pass


# ---------------------------------------------------------------------- #
# config wiring
# ---------------------------------------------------------------------- #


def _on_serving_param(param: Any) -> None:
    global SERVING_ON
    SERVING_ON = bool(param.get())


from modin_tpu.config import ServingEnabled as _ServingEnabled  # noqa: E402

_ServingEnabled.subscribe(_on_serving_param)
