"""Per-query serving context: deadline/cancellation tokens + degraded routing.

One thread runs one admitted query at a time; this module carries that
query's serving state — its :class:`CancellationToken` (deadline) and its
degraded-route flag — on a thread-local, exactly the way graftscope spans
and graftmeter QueryStats scopes ride their own thread-locals.  The seam
checks (``engine_call`` attempt start and backoff sleeps, the device-memory
spill pass, ``run_fused`` materialization, plan lowering) all gate on ONE
module attribute, :data:`CONTEXT_ON`, so the default-off mode
(``MODIN_TPU_SERVING=0`` and no ad-hoc deadline scope) costs one attribute
read per seam crossing and allocates nothing —
:func:`context_alloc_count` lets tests assert exactly that, mirroring
``spans.span_alloc_count()`` / ``meters.meter_alloc_count()``.

Cross-thread propagation mirrors spans/meters too: the resilience watchdog
worker adopts the owner's context via :func:`snapshot_context` /
:func:`seed_thread_context`, so a deadline expiring inside a watched thunk
aborts with the same typed error it would on the owning thread.  Seeding
always *replaces* the thread's context (a pooled worker reused across
queries must never keep a previous query's deadline).

This module is a leaf on purpose — it imports only the metric stream — so
core/execution/resilience.py can import it at module scope without a cycle
(serving/__init__ loads only ``errors`` and ``context`` eagerly; the gate
machinery is lazy).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from modin_tpu.concurrency import named_lock, named_rlock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.serving.errors import DeadlineExceeded

#: Module-level fast path: True while at least one serving query context
#: (or ad-hoc deadline scope) is active anywhere in the process.  Seam
#: checks read this ONE attribute before doing anything else.
CONTEXT_ON: bool = False

_active = 0
_active_lock = named_lock("serving.context_active")

_tls = threading.local()  # .ctx: the innermost QueryContext on this thread

_alloc_count = 0  # QueryContext objects ever constructed (zero-alloc assert)

#: Collective-safe dispatch serialization.  Concurrent threads enqueueing
#: sharded XLA programs onto the same device mesh can interleave their
#: per-device executions — and two programs with cross-device collectives
#: that reach the per-device queues in different orders DEADLOCK at the
#: collective rendezvous (reproduced on the 8-device virtual CPU mesh:
#: two AllReduce run_ids each waiting forever for the other's
#: participants; real multi-chip meshes have the same launch-order
#: hazard).  While a serving context is active, the engine seam wraps
#: every deploy/put attempt in this lock so program enqueue is one global
#: order across threads.  Reentrant: a recovery pass re-deploys from
#: inside a failed attempt's handling on the same thread.
dispatch_lock = named_rlock("resilience.dispatch")

# test seam, resilience-style: patched to simulate clock advance
_now = time.monotonic


def context_alloc_count() -> int:
    """How many query contexts this process has ever constructed.

    The disabled-mode contract is *zero new allocations*; tests snapshot
    this counter around a workload run with serving off.
    """
    return _alloc_count


class CancellationToken:
    """One query's latency budget: a monotonic deadline plus a manual
    cancel flag.  Checked (never polled) at seam boundaries; expiry and
    cancellation both surface as :class:`DeadlineExceeded`."""

    __slots__ = ("deadline_at", "deadline_s", "label", "_cancelled", "_raised")

    def __init__(self, deadline_s: Optional[float], label: str = "query"):
        self.deadline_s = deadline_s
        self.deadline_at = (
            _now() + deadline_s if deadline_s is not None else None
        )
        self.label = label
        self._cancelled = False
        self._raised = False

    def cancel(self) -> None:
        """Abort the query at its next seam crossing (client disconnect)."""
        self._cancelled = True

    def cancelled(self) -> bool:
        return self._cancelled

    def remaining_s(self) -> Optional[float]:
        """Seconds of budget left (None = unbounded; <= 0 = expired)."""
        if self._cancelled:
            return 0.0
        if self.deadline_at is None:
            return None
        return self.deadline_at - _now()

    def expired(self) -> bool:
        remaining = self.remaining_s()
        return remaining is not None and remaining <= 0

    def check(self, where: str = "") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is gone."""
        if not self.expired():
            return
        if not self._raised:
            # once per token: the owner and a seeded watchdog worker can
            # both observe expiry, but the query died exactly once
            self._raised = True
            emit_metric("serving.deadline_exceeded", 1)
        budget = self.deadline_s if self.deadline_s is not None else 0.0
        verb = "cancelled" if self._cancelled else (
            f"exceeded its {budget * 1e3:.0f}ms deadline"
        )
        raise DeadlineExceeded(
            f"query {self.label!r} {verb} (observed at {where or 'seam'}; "
            "MODIN_TPU_SERVING_DEFAULT_DEADLINE_MS)",
            deadline_s=budget,
            where=where,
        )


class QueryContext:
    """The serving state one admitted query carries across the seams."""

    __slots__ = ("token", "degraded", "tenant", "label")

    def __init__(
        self,
        token: Optional[CancellationToken],
        degraded: bool = False,
        tenant: str = "default",
        label: str = "query",
    ):
        global _alloc_count
        _alloc_count += 1
        self.token = token
        self.degraded = degraded
        self.tenant = tenant
        self.label = label


# ---------------------------------------------------------------------- #
# thread-local plumbing (callers check CONTEXT_ON first)
# ---------------------------------------------------------------------- #


def current_context() -> Optional[QueryContext]:
    return getattr(_tls, "ctx", None)


def current_token() -> Optional[CancellationToken]:
    ctx = getattr(_tls, "ctx", None)
    return ctx.token if ctx is not None else None


def degraded_active() -> bool:
    """Is this thread's query routed to the host/pandas path?"""
    ctx = getattr(_tls, "ctx", None)
    return ctx is not None and ctx.degraded


def check_deadline(where: str = "") -> None:
    """Seam check: raise DeadlineExceeded when the thread's budget is gone.

    No-op without an active token — callers pre-gate on :data:`CONTEXT_ON`
    so the common path never even reaches this call.
    """
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None and ctx.token is not None:
        ctx.token.check(where)


def remaining_s() -> Optional[float]:
    """This thread's remaining budget (None = no active deadline)."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or ctx.token is None:
        return None
    return ctx.token.remaining_s()


def clamp_sleep(delay_s: float) -> float:
    """A sleep duration that never outlives this thread's budget.

    Backoff sleeps between engine retries call this: a 100ms-budget query
    must not serve a 1.6s exponential backoff — it sleeps out its budget
    and the next attempt-start check aborts it with the typed error.
    """
    remaining = remaining_s()
    if remaining is None:
        return delay_s
    return max(min(delay_s, remaining), 0.0)


def enter_context(ctx: QueryContext) -> Optional[QueryContext]:
    """Install ``ctx`` on this thread; returns the displaced context (the
    gate restores it on exit so nested submits compose)."""
    global CONTEXT_ON
    previous = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    with _active_lock:
        global _active
        _active += 1
        CONTEXT_ON = True
    return previous


def exit_context(previous: Optional[QueryContext]) -> None:
    global CONTEXT_ON
    _tls.ctx = previous
    with _active_lock:
        global _active
        _active -= 1
        if _active <= 0:
            _active = 0
            CONTEXT_ON = False


def snapshot_context() -> Optional[QueryContext]:
    """This thread's context, for seeding a worker thread (watchdog)."""
    return getattr(_tls, "ctx", None)


def seed_thread_context(ctx: Optional[QueryContext]) -> None:
    """Adopt (or clear) a context snapshot on a worker thread.

    Always REPLACES: a pooled worker seeded for query A and later reused
    for query B (or for un-scoped work, ctx=None) must not retain A's
    deadline — the single-owner assumption the concurrency audit killed.
    The active-count bookkeeping is untouched: the owner's enter/exit pair
    owns the lifecycle; workers only route checks.
    """
    _tls.ctx = ctx
