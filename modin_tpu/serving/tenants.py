"""Per-tenant fairness state: weighted token buckets, cost EWMA, health.

Production traffic is many sessions sharing one device, and "fair" means
three different things the gate needs per tenant:

1. **Rate fairness** — a weighted token bucket
   (``MODIN_TPU_SERVING_TENANT_WEIGHTS``, e.g. ``"alice=3,bob=1"``;
   unlisted tenants weigh 1.0).  A tenant's bucket holds up to
   ``weight * max_concurrent`` tokens and refills at that many tokens per
   second; each admitted query spends one.  A tenant hammering past its
   weighted rate is *throttled* (typed :class:`~.errors.QueryRejected`
   with the token-refill time as the retry-after hint) while every other
   tenant's traffic flows untouched.

2. **Cost memory** — an EWMA of the device bytes each tenant's queries
   actually moved (``QueryStats.est_bytes`` from the graftcost capture,
   falling back to the HBM high-water sample for uncaptured runs).  The
   admission gate sizes its headroom reservation from this, so a tenant
   with a history of heavy queries reserves honestly and an unknown
   tenant gets the conservative default (budget / max_concurrent).

3. **Health** — one circuit breaker per tenant, reusing the PR-1
   machinery verbatim (``resilience.get_breaker``): a query whose run
   tripped device-path breakers (``QueryStats.breaker_trips``) strikes
   its tenant's breaker; ``ResilienceBreakerThreshold`` consecutive
   strikes trip it OPEN and that tenant's queries are rejected for the
   cooldown — the sick *workload* is quarantined, never the system.

All state lives behind one lock and is test-resettable.  The clock is the
module seam ``_now`` (resilience-style) so fairness scenarios run without
wall-clock sleeps.
"""

from __future__ import annotations

import re
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from modin_tpu.concurrency import named_lock

# test seam: patched to simulate refill time passing
_now = time.monotonic

#: EWMA smoothing for observed per-query cost (bytes); ~5 queries of memory
_EWMA_ALPHA = 0.3

#: Token-bucket burst factor: a tenant may burst this many times its
#: steady-state weighted rate (weight * max_concurrent per second) before
#: throttling engages — normal request trains never hit the limiter, a
#: sustained hammer drains the burst and then pays the rate.
_BURST = 4.0

#: Cardinality cap on retained tenant states (the metric stream has
#: MODIN_TPU_METERS_MAX_SERIES; per-user tenant ids need the same
#: protection here).  Past the cap, the LRU *idle* tenants — nothing in
#: flight, health breaker closed — are evicted together with their
#: breakers; active or quarantined tenants are never dropped, so the cap
#: may be transiently exceeded rather than ever losing live state.
_MAX_TENANTS = 1024

#: metric-name-safe tenant segment (emit_metric enforces [A-Za-z0-9._-])
_SANITIZE = re.compile(r"[^A-Za-z0-9_.-]+")


def sanitize(tenant: str) -> str:
    """Tenant id as a metric-name segment (never empty)."""
    return _SANITIZE.sub("_", str(tenant)) or "default"


def parse_weights(spec: str) -> Dict[str, float]:
    """``"alice=3,bob=1.5"`` -> {"alice": 3.0, "bob": 1.5}.

    Malformed entries are skipped (config must not crash admission);
    non-positive weights clamp to a minimal positive share.
    """
    weights: Dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, value = part.partition("=")
        try:
            weight = float(value)
        except ValueError:
            continue
        weights[name.strip()] = max(weight, 0.01)
    return weights


class TenantState:
    """One tenant's bucket / cost memory / health handle (lock in registry)."""

    __slots__ = (
        "name", "weight", "tokens", "capacity", "refill_per_s",
        "last_refill", "cost_ewma_bytes", "wall_ewma_s", "in_flight",
        "admitted", "shed", "gen",
    )

    def __init__(self, name: str, weight: float, max_concurrent: int):
        self.name = name
        self.cost_ewma_bytes: Optional[float] = None
        self.wall_ewma_s: Optional[float] = None
        self.in_flight = 0
        self.admitted = 0
        self.shed = 0
        self.gen = 0
        self.tokens = 0.0
        self.last_refill = _now()
        self.retune(weight, max_concurrent, 0)
        self.tokens = self.capacity  # new tenants start with a full burst

    def retune(self, weight: float, max_concurrent: int, gen: int) -> None:
        """Apply the CURRENT weight/concurrency config (the registry calls
        this when a knob changed since the tenant's last admission: runtime
        re-weighting must apply to already-seen tenants, not only new
        ones).  Tokens are clamped, never topped up, by a retune."""
        self.weight = weight
        self.refill_per_s = max(weight * max_concurrent, 1.0)
        self.capacity = self.refill_per_s * _BURST
        self.tokens = min(self.tokens, self.capacity)
        self.gen = gen

    # -- token bucket (caller holds the registry lock) ------------------ #

    def _refill(self) -> None:
        now = _now()
        elapsed = now - self.last_refill
        if elapsed > 0:
            self.tokens = min(
                self.capacity, self.tokens + elapsed * self.refill_per_s
            )
            self.last_refill = now

    def try_spend(self) -> Tuple[bool, float]:
        """(spent, retry_after_s): take one token, or how long until one."""
        self._refill()
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True, 0.0
        return False, (1.0 - self.tokens) / self.refill_per_s

    # -- cost / latency memory ------------------------------------------ #

    def observe(self, cost_bytes: float, wall_s: float) -> None:
        if cost_bytes > 0:
            self.cost_ewma_bytes = (
                cost_bytes
                if self.cost_ewma_bytes is None
                else (1 - _EWMA_ALPHA) * self.cost_ewma_bytes
                + _EWMA_ALPHA * cost_bytes
            )
        if wall_s > 0:
            self.wall_ewma_s = (
                wall_s
                if self.wall_ewma_s is None
                else (1 - _EWMA_ALPHA) * self.wall_ewma_s
                + _EWMA_ALPHA * wall_s
            )


class TenantRegistry:
    """Thread-safe name -> :class:`TenantState`: weights resolved lazily
    and RE-resolved when the knobs change (config generation), LRU-bounded
    at :data:`_MAX_TENANTS` idle tenants."""

    def __init__(self) -> None:
        self._lock = named_lock("serving.tenants")
        self._tenants: "OrderedDict[str, TenantState]" = OrderedDict()
        self._gen = 1  # any state created before wiring retunes on touch

    def _bump_gen(self, _param=None) -> None:
        """Config subscription: a weight/concurrency knob changed — every
        tenant re-applies it on its next touch."""
        with self._lock:
            self._gen += 1

    def _weights(self) -> Dict[str, float]:
        from modin_tpu.config import ServingTenantWeights

        return parse_weights(ServingTenantWeights.get())

    def _max_concurrent(self) -> int:
        from modin_tpu.config import ServingMaxConcurrent

        return max(int(ServingMaxConcurrent.get()), 1)

    def _get_locked(self, tenant: str) -> TenantState:
        state = self._tenants.get(tenant)
        if state is None:
            weight = self._weights().get(tenant, 1.0)
            state = TenantState(tenant, weight, self._max_concurrent())
            state.gen = self._gen
            self._tenants[tenant] = state
            self._evict_idle_locked()
        else:
            self._tenants.move_to_end(tenant)  # LRU touch
            if state.gen != self._gen:
                state.retune(
                    self._weights().get(tenant, 1.0),
                    self._max_concurrent(),
                    self._gen,
                )
        return state

    def _evict_idle_locked(self) -> None:
        """Cap the registry: drop the LRU tenants that are idle (nothing in
        flight) with a CLOSED health breaker, together with their breakers
        — per-user tenant ids must not grow process memory without bound.
        An open breaker is quarantine state and survives; its tenant stays."""
        if len(self._tenants) <= _MAX_TENANTS:
            return
        from modin_tpu.core.execution.resilience import drop_breaker

        for name in list(self._tenants):
            if len(self._tenants) <= _MAX_TENANTS:
                return
            state = self._tenants[name]
            if state.in_flight > 0 or breaker_for(name).state != "closed":
                continue
            del self._tenants[name]
            drop_breaker(f"tenant_{sanitize(name)}")

    def get(self, tenant: str) -> TenantState:
        with self._lock:
            return self._get_locked(tenant)

    def try_spend(self, tenant: str) -> Tuple[bool, float]:
        with self._lock:
            return self._get_locked(tenant).try_spend()

    def refund(self, tenant: str) -> None:
        """Return one rate token (the query was shed on CAPACITY grounds —
        queue full, or its deadline expired while queued — so it never ran;
        charging the tenant's rate for it would misattribute system
        overload to the tenant and quarantine a polite retrying client)."""
        with self._lock:
            state = self._get_locked(tenant)
            state.tokens = min(state.tokens + 1.0, state.capacity)

    def observe(self, tenant: str, cost_bytes: float, wall_s: float) -> None:
        with self._lock:
            self._get_locked(tenant).observe(cost_bytes, wall_s)

    # counter mutations all pass through the registry lock: the gate calls
    # note_admitted under ITS lock (order gate -> registry, consistent with
    # every other nesting) while note_release runs lock-free on the gate
    # side — unsynchronized read-modify-writes would drift in_flight, and
    # in_flight feeds the weighted-fair wake order, not just diagnostics

    def note_admitted(self, tenant: str) -> float:
        """Count an admission; returns the tenant's weight (for waiters)."""
        with self._lock:
            state = self._get_locked(tenant)
            state.in_flight += 1
            state.admitted += 1
            return state.weight

    def note_release(self, tenant: str) -> None:
        with self._lock:
            state = self._get_locked(tenant)
            state.in_flight = max(state.in_flight - 1, 0)

    def note_shed(self, tenant: str) -> None:
        with self._lock:
            self._get_locked(tenant).shed += 1

    def cost_estimate(self, tenant: str, default_bytes: float) -> float:
        """The tenant's EWMA cost, or the conservative default for a tenant
        with no history (unknown cost must reserve big, not small)."""
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None or state.cost_ewma_bytes is None:
                return default_bytes
            return state.cost_ewma_bytes

    def wall_hint(self, tenant: str, fallback_s: float) -> float:
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None or state.wall_ewma_s is None:
                return fallback_s
            return state.wall_ewma_s

    def snapshot(self) -> dict:
        with self._lock:
            return {
                name: {
                    "weight": s.weight,
                    "tokens": round(s.tokens, 3),
                    "in_flight": s.in_flight,
                    "admitted": s.admitted,
                    "shed": s.shed,
                    "cost_ewma_bytes": s.cost_ewma_bytes,
                    "wall_ewma_s": s.wall_ewma_s,
                    "breaker": breaker_for(name).state,
                }
                for name, s in sorted(self._tenants.items())
            }

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()


def breaker_for(tenant: str):
    """The tenant's health breaker — PR-1 circuit-breaker machinery,
    one ad-hoc family per tenant (``tenant_<name>``; the device-path
    family registry is for the query compiler's paths, and ad-hoc
    families are the documented escape hatch tests already use)."""
    from modin_tpu.core.execution.resilience import get_breaker

    return get_breaker(f"tenant_{sanitize(tenant)}")


registry = TenantRegistry()

# runtime re-weighting: the knobs fire the generation bump immediately on
# subscribe and on every later put(), so an operator raising a tenant's
# weight (or the gate's concurrency) retunes already-seen tenants on their
# next admission instead of freezing first-touch values forever
from modin_tpu.config import (  # noqa: E402
    ServingMaxConcurrent as _ServingMaxConcurrent,
    ServingTenantWeights as _ServingTenantWeights,
)

_ServingTenantWeights.subscribe(registry._bump_gen)
_ServingMaxConcurrent.subscribe(registry._bump_gen)
