"""graftgate — multi-tenant query serving: admission, deadlines, degradation.

Public surface::

    from modin_tpu import serving

    result = serving.submit(
        lambda: df.groupby("key").sum(),
        tenant="alice",
        deadline_ms=250,
    )

With ``MODIN_TPU_SERVING=0`` (the default) ``submit`` is a transparent
direct call — bit-for-bit today's single-query behavior, zero allocations.
With serving on, every submitted query is admitted (bounded concurrency +
device-byte headroom), queued (bounded depth, weighted-fair wake order),
or shed with a typed :class:`QueryRejected`; a latency budget rides the
query as a :class:`CancellationToken` checked at the engine-seam
boundaries and surfaces as a typed :class:`DeadlineExceeded`; and when the
device is sick (open breakers / ledger past high water) admitted queries
route to the host path instead of queueing behind it.

Import discipline: only :mod:`~modin_tpu.serving.errors` and
:mod:`~modin_tpu.serving.context` load eagerly — they are leaves, and the
resilience layer imports them at module scope.  The gate (which imports
resilience back) loads lazily on first use via PEP 562.
"""

from modin_tpu.serving import context, errors  # noqa: F401
from modin_tpu.serving.context import (  # noqa: F401
    CancellationToken,
    QueryContext,
    context_alloc_count,
)
from modin_tpu.serving.errors import (  # noqa: F401
    DeadlineExceeded,
    QueryRejected,
    ServingError,
)

# NOTE: "gate" and "tenants" are deliberately NOT lazy-mapped here —
# importing a submodule binds the MODULE object to the package attribute,
# so mapping `serving.gate` to the AdmissionGate instance would make the
# attribute's type depend on import order.  `serving.gate` is always the
# submodule; the instance lives at `serving.gate.gate`.
_LAZY = {
    "submit": "modin_tpu.serving.gate",
    "AdmissionGate": "modin_tpu.serving.gate",
    "Permit": "modin_tpu.serving.gate",
    "serving_snapshot": "modin_tpu.serving.gate",
}

__all__ = [
    "AdmissionGate",
    "CancellationToken",
    "DeadlineExceeded",
    "Permit",
    "QueryContext",
    "QueryRejected",
    "ServingError",
    "context",
    "context_alloc_count",
    "errors",
    "serving_snapshot",
    "submit",
]


def __getattr__(name: str):
    if name in ("gate", "tenants"):
        import importlib

        return importlib.import_module(f"modin_tpu.serving.{name}")
    module_name = _LAZY.get(name)
    if module_name is None:
        raise AttributeError(f"module 'modin_tpu.serving' has no attribute {name!r}")
    import importlib

    module = importlib.import_module(module_name)
    value = getattr(module, name)
    globals()[name] = value
    return value
