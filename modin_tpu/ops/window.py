"""Rolling/expanding-window device kernels (the reference's Fold operators).

Reference design: modin/core/dataframe/algebra/fold.py:28 + window.py — the
reference ships whole row blocks to workers and runs pandas.rolling per
partition.  Here every windowed aggregation is O(n) compiled work:

- sum/mean/count/var/std: cumulative sums and shifted differences (var uses
  windowed Σx and Σx² over a globally centered column, which removes the
  E[x²]−E[x]² cancellation);
- min/max: the van Herk/Gil-Werman two-pass — block prefix/suffix extrema
  give any window extremum as max(suffix[i−w+1], prefix[i]) in O(n),
  independent of window size;
- expanding_*: the same formulas with the prefix itself as the window.

pandas' min_periods/NaN semantics apply via the windowed non-NaN count.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np

ROLLING_DEVICE_OPS = ("sum", "mean", "count", "min", "max", "var", "std", "sem")
EXPANDING_DEVICE_OPS = ("sum", "mean", "count", "min", "max", "var", "std", "sem")
EWM_DEVICE_OPS = ("mean", "sum", "var", "std")


def _windowed(arr, window: int):
    """arr[i] - arr[i-window] (prefix-sum difference), pad-agnostic."""
    import jax.numpy as jnp

    if window > arr.shape[0]:
        return arr
    shifted = jnp.concatenate([jnp.zeros(window, arr.dtype), arr[:-window]])
    return arr - shifted


def _van_herk(x, window: int, op: str):
    """Windowed min/max in O(n): block prefix/suffix extrema.

    For window [s, i] (s = i-w+1) spanning blocks b-1 and b of width w,
    suffix[s] covers [s, end of b-1] and prefix[i] covers [start of b, i];
    their cum is exactly the window.  Leading incomplete windows (i < w-1)
    are prefix[i] alone — suffix[0] would leak future rows into them.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    P = x.shape[0]
    w = min(window, P)
    nb = (P + w - 1) // w
    pad = nb * w - P
    neutral = jnp.inf if op == "min" else -jnp.inf
    xp = jnp.concatenate([x, jnp.full(pad, neutral, x.dtype)]) if pad else x
    blocks = xp.reshape(nb, w)
    cum = jnp.minimum if op == "min" else jnp.maximum
    prefix = lax.associative_scan(cum, blocks, axis=1).reshape(-1)[:P]
    suffix = lax.associative_scan(cum, blocks, axis=1, reverse=True).reshape(-1)[:P]
    idx = jnp.arange(P)
    start = jnp.maximum(idx - w + 1, 0)
    out = cum(jnp.take(suffix, start), prefix)
    return jnp.where(idx < w - 1, prefix, out)


def _one_windowed(op: str, c, n: int, window: int, min_periods: int, ddof: int):
    import jax.numpy as jnp

    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    valid = jnp.arange(c.shape[0]) < n
    # pandas _prep_values treats +/-inf as missing in every window agg
    nanm = ((jnp.isnan(c) | jnp.isinf(c)) | ~valid) if is_f else ~valid
    cnt = (~nanm).astype(jnp.int64)
    wcnt = _windowed(jnp.cumsum(cnt), window)

    if op == "count":
        # pandas gates count on ROWS in the window (NaNs included)
        wrows = jnp.minimum(jnp.arange(c.shape[0]) + 1, window)
        return jnp.where(wrows >= min_periods, wcnt.astype(jnp.float64), jnp.nan)

    if op in ("min", "max"):
        neutral = jnp.inf if op == "min" else -jnp.inf
        x = jnp.where(nanm, neutral, c).astype(jnp.float64)
        r = _van_herk(x, window, op)
        return jnp.where(wcnt >= jnp.maximum(min_periods, 1), r, jnp.nan)

    x = jnp.where(nanm, 0, c).astype(jnp.float64)
    if op in ("var", "std", "sem"):
        # center globally first: windowed variance is shift-invariant and
        # Σx² − (Σx)²/n over centered values avoids catastrophic cancellation
        total_cnt = jnp.maximum(jnp.sum(cnt), 1)
        gmean = jnp.sum(x) / total_cnt
        x = jnp.where(nanm, 0.0, x - gmean)
    wsum = _windowed(jnp.cumsum(x), window)

    if op == "sum":
        return jnp.where(wcnt >= min_periods, wsum, jnp.nan)
    if op == "mean":
        res = wsum / jnp.maximum(wcnt, 1)
        return jnp.where((wcnt >= min_periods) & (wcnt > 0), res, jnp.nan)
    # var/std/sem
    wsum2 = _windowed(jnp.cumsum(x * x), window)
    cntf = jnp.maximum(wcnt, 1).astype(jnp.float64)
    var = (wsum2 - wsum * wsum / cntf) / jnp.maximum(wcnt - ddof, 1)
    var = jnp.maximum(var, 0.0)  # guard tiny negative rounding
    gate = (wcnt >= jnp.maximum(min_periods, 1)) & (wcnt - ddof > 0)
    var = jnp.where(gate, var, jnp.nan)
    if op == "var":
        return var
    if op == "std":
        return jnp.sqrt(var)
    return jnp.sqrt(var / cntf)  # sem


@functools.lru_cache(maxsize=None)
def _jit_rolling(op: str, n_cols: int, n: int, window: int, min_periods: int, ddof: int):
    import jax

    def fn(cols: Tuple):
        return tuple(
            _one_windowed(op, c, n, window, min_periods, ddof) for c in cols
        )

    return jax.jit(fn)


def rolling_reduce(
    op: str,
    cols: List[Any],
    n: int,
    window: int,
    min_periods: int,
    ddof: int = 1,
) -> List[Any]:
    """Rolling aggregation over padded columns; one jit for the frame."""
    fn = _jit_rolling(op, len(cols), int(n), int(window), int(min_periods), int(ddof))
    return list(fn(tuple(cols)))


def expanding_reduce(
    op: str, cols: List[Any], n: int, min_periods: int, ddof: int = 1
) -> List[Any]:
    """Expanding aggregation: exactly rolling with the full length as window
    (the prefix-sum differences, van Herk blocks, and gating all degenerate
    to the expanding forms when window >= n)."""
    return rolling_reduce(op, cols, int(n), max(int(n), 1), int(min_periods), int(ddof))


# --------------------------------------------------------------------- #
# Exponentially weighted windows
# --------------------------------------------------------------------- #
#
# The reference surface is modin/pandas/window.py (ExponentialMovingWindow
# defaulting per-block to pandas); pandas' own kernel is a sequential
# per-row update (core/window/online.py:38 mirrors the cython loop).  On
# device every ewm statistic is a composition of FIRST-ORDER LINEAR
# RECURRENCES y_t = a_t*y_{t-1} + b_t, which `lax.associative_scan` runs in
# O(log n) depth:
#
# - adjust=True: numerator / denominator / Σw² all decay by f = 1-alpha per
#   step (per OBSERVATION when ignore_na), each new observation entering
#   with weight 1; mean = num/den.
# - adjust=False: pandas renormalises at every observation (old_wt resets
#   to 1), so the mean itself is the recurrence:
#   y_t = (f^gap*y_{t-1} + alpha*x_t) / (f^gap + alpha), `gap` counting the
#   decay steps since the previous observation.  The bias-correction
#   weights renormalise by the same factor.
# - var: pandas' update
#   cov_t = (ow*(cov_{t-1} + (mu_{t-1}-mu_t)^2) + nw*(x_t-mu_t)^2)/(ow+nw)
#   is linear in cov once the mean sequence is known, so it is a second
#   scan over per-position coefficients; the debiasing factor is
#   Σw²/(Σw² - Σ(w²)).
#
# Exactness was established against the pandas oracle over a
# {clean,NaN-gapped,all-NaN,constant,alternating} x {adjust} x {ignore_na}
# x {min_periods} x {bias} grid (1920 checks, rtol 1e-9).


def _scan_combine(x, y):
    """Associative composition of first-order maps: ((a1,b1) then (a2,b2))
    -> (a1*a2, a2*b1 + b2)."""
    ax, bx = x
    ay, by = y
    return ax * ay, ay * bx + by


# Within-block scan length for the two-level formulation below.  jax's
# associative_scan does O(n log n) combine work; blocking caps the log factor
# at log(block) (12 for 4096 vs 27 at 1e8 rows) — the VERDICT-r4 concern
# about the ewm scan's work term at north-star scale.
_SCAN_BLOCK = 4096
# None -> auto (blocked on accelerators only).  Measured on the CPU
# substrate the flat scan WINS (3.7s vs 6.8s at 1e7x5: XLA:CPU lowers
# associative_scan to a sequential O(n) loop, and the blocked form only
# adds reshape traffic); the log-factor reduction targets accelerator
# backends where the flat scan's depth passes over HBM dominate.
_USE_BLOCKED_SCAN = None


def _blocked_scan_enabled() -> bool:
    if _USE_BLOCKED_SCAN is not None:
        return _USE_BLOCKED_SCAN
    import jax

    return jax.default_backend() != "cpu"


def _linear_scan(a, b):
    """y_t = a_t * y_{t-1} + b_t with y_{-1} = 0.

    Two-level blocked scan: (1) independent within-block scans over rows
    reshaped to (B, C); (2) one tiny scan over the B block summaries to get
    each block's incoming carry; (3) y[i,j] = A_prefix[i,j]*carry[i] + y_local.
    Work drops from O(n log n) to O(n log C + B log B + n) with identical
    results (map composition is exact, no reordering of the b terms).
    Short arrays and CPU backends use the flat scan."""
    import jax.lax as lax
    import jax.numpy as jnp

    P = a.shape[0]
    C = _SCAN_BLOCK
    if P <= 2 * C or not _blocked_scan_enabled():
        return lax.associative_scan(_scan_combine, (a, b))[1]
    B = -(-P // C)
    pad = B * C - P
    if pad:
        # identity elements (a=1, b=0) extend the tail without changing any
        # prefix value
        a = jnp.concatenate([a, jnp.ones(pad, a.dtype)])
        b = jnp.concatenate([b, jnp.zeros(pad, b.dtype)])
    a2 = a.reshape(B, C)
    b2 = b.reshape(B, C)
    aw, bw = lax.associative_scan(_scan_combine, (a2, b2), axis=1)
    _, carry_scan = lax.associative_scan(_scan_combine, (aw[:, -1], bw[:, -1]))
    carry = jnp.concatenate([jnp.zeros(1, b.dtype), carry_scan[:-1]])
    y = aw * carry[:, None] + bw
    return y.reshape(-1)[:P]


def _one_ewm(op: str, c, n: int, alpha, adjust: bool, ignore_na: bool,
             min_periods, bias: bool):
    import jax.lax as lax
    import jax.numpy as jnp

    P = c.shape[0]
    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    in_frame = jnp.arange(P) < n
    # pandas _prep_values treats +/-inf as missing, like the other windows
    nanm = ((jnp.isnan(c) | jnp.isinf(c)) | ~in_frame) if is_f else ~in_frame
    valid = ~nanm
    x = jnp.where(valid, c, 0).astype(jnp.float64)

    alpha = jnp.float64(alpha)
    f = 1.0 - alpha
    mp = jnp.maximum(jnp.int64(min_periods), 1)
    idx = jnp.arange(P, dtype=jnp.int64)
    cnt = jnp.cumsum(valid.astype(jnp.int64))
    is_first = valid & (cnt == 1)
    # decay steps applied on entering position t: every row counts unless
    # ignore_na, in which case only observations do
    lastv = lax.associative_scan(jnp.maximum, jnp.where(valid, idx, -1))
    lastv_excl = jnp.concatenate([jnp.full(1, -1, idx.dtype), lastv[:-1]])
    gap = (
        jnp.ones(P, jnp.float64)
        if ignore_na
        else (idx - lastv_excl).astype(jnp.float64)
    )
    fd = f ** gap  # old weight at an observation (adjust=False: reset to 1)

    if adjust or op == "sum":
        a_step = jnp.full(P, f) if not ignore_na else jnp.where(valid, f, 1.0)
        num = _linear_scan(a_step, jnp.where(valid, x, 0.0))
        if op == "sum":
            return jnp.where(cnt >= mp, num, jnp.nan)
        bv = valid.astype(jnp.float64)
        den = _linear_scan(a_step, bv)
        sum_wt2 = _linear_scan(a_step * a_step, bv)
        # den >= 1 at every observation; carry the LAST OBSERVATION's value
        # into NaN rows by gather rather than relying on the num/den ratio,
        # which 0/0-collapses when f**gap underflows (alpha -> 1)
        mean_raw = num / jnp.where(den == 0, 1.0, den)
        mean = jnp.where(
            lastv >= 0, jnp.take(mean_raw, jnp.clip(lastv, 0)), jnp.nan
        )
        sum_wt = den
        ow = a_step * jnp.concatenate([jnp.zeros(1), den[:-1]])
        nw = jnp.float64(1.0)
    else:
        cnorm = fd + alpha
        ay = jnp.where(
            valid, jnp.where(is_first, 0.0, fd / cnorm), 1.0
        )
        by = jnp.where(
            valid, jnp.where(is_first, x, alpha * x / cnorm), 0.0
        )
        mean = _linear_scan(ay, by)
        mean = jnp.where(cnt >= 1, mean, jnp.nan)
        mid = valid & ~is_first
        aw = jnp.where(mid, fd / cnorm, jnp.where(valid, 0.0, 1.0))
        sum_wt = _linear_scan(aw, jnp.where(mid, alpha / cnorm, jnp.where(valid, 1.0, 0.0)))
        aw2 = jnp.where(mid, (fd * fd) / (cnorm * cnorm), jnp.where(valid, 0.0, 1.0))
        sum_wt2 = _linear_scan(
            aw2,
            jnp.where(mid, (alpha * alpha) / (cnorm * cnorm), jnp.where(valid, 1.0, 0.0)),
        )
        ow = jnp.where(is_first, 0.0, fd)
        nw = jnp.float64(alpha)

    if op == "mean":
        return jnp.where(cnt >= mp, mean, jnp.nan)

    # var/std: linear scan for the debiased second moment
    mid = valid & ~is_first
    mean0 = jnp.where(jnp.isnan(mean), 0.0, mean)
    mprev = jnp.concatenate([jnp.zeros(1), mean0[:-1]])
    denom_t = jnp.where(mid, ow + nw, 1.0)
    ac = jnp.where(mid, ow / denom_t, jnp.where(valid, 0.0, 1.0))
    cc = jnp.where(
        mid,
        (ow * (mprev - mean0) ** 2 + nw * (x - mean0) ** 2) / denom_t,
        0.0,
    )
    cov = _linear_scan(ac, cc)
    if bias:
        v = cov
    else:
        numr = sum_wt * sum_wt
        denr = numr - sum_wt2
        v = jnp.where(denr > 0, cov * numr / jnp.where(denr == 0, 1.0, denr), jnp.nan)
    v = jnp.where(cnt >= mp, v, jnp.nan)
    return jnp.sqrt(v) if op == "std" else v


def _one_ewm_pair(op: str, cx, cy, n: int, alpha, adjust: bool,
                  ignore_na: bool, min_periods, bias: bool):
    """ewm cov/corr of one column pair under JOINT validity (a row counts
    as an observation only when BOTH sides are non-missing — the pandas
    ewmcov contract).  corr is the ratio of the three BIASED covariances
    over the same joint mask.  Same scan structure as _one_ewm; the three
    cov recurrences share coefficients, so they run as one stacked scan."""
    import jax.lax as lax
    import jax.numpy as jnp

    P = cx.shape[0]
    in_frame = jnp.arange(P) < n

    def missing(c):
        if jnp.issubdtype(c.dtype, jnp.floating):
            return jnp.isnan(c) | jnp.isinf(c)
        return jnp.zeros(c.shape, bool)

    valid = in_frame & ~missing(cx) & ~missing(cy)
    x = jnp.where(valid, cx, 0).astype(jnp.float64)
    y = jnp.where(valid, cy, 0).astype(jnp.float64)

    alpha = jnp.float64(alpha)
    f = 1.0 - alpha
    mp = jnp.maximum(jnp.int64(min_periods), 1)
    idx = jnp.arange(P, dtype=jnp.int64)
    cnt = jnp.cumsum(valid.astype(jnp.int64))
    is_first = valid & (cnt == 1)
    lastv = lax.associative_scan(jnp.maximum, jnp.where(valid, idx, -1))
    lastv_excl = jnp.concatenate([jnp.full(1, -1, idx.dtype), lastv[:-1]])
    gap = (
        jnp.ones(P, jnp.float64)
        if ignore_na
        else (idx - lastv_excl).astype(jnp.float64)
    )
    fd = f ** gap

    if adjust:
        a_step = jnp.full(P, f) if not ignore_na else jnp.where(valid, f, 1.0)
        bv = valid.astype(jnp.float64)
        a4 = jnp.stack(
            [a_step, a_step, a_step, a_step * a_step], axis=1
        )
        b4 = jnp.stack(
            [jnp.where(valid, x, 0.0), jnp.where(valid, y, 0.0), bv, bv],
            axis=1,
        )
        num_x, num_y, den, sum_wt2 = jnp.moveaxis(_linear_scan(a4, b4), 1, 0)
        den_safe = jnp.where(den == 0, 1.0, den)
        carried = lastv >= 0
        mx = jnp.where(
            carried, jnp.take(num_x / den_safe, jnp.clip(lastv, 0)), 0.0
        )
        my = jnp.where(
            carried, jnp.take(num_y / den_safe, jnp.clip(lastv, 0)), 0.0
        )
        sum_wt = den
        ow = a_step * jnp.concatenate([jnp.zeros(1), den[:-1]])
        nw = jnp.float64(1.0)
    else:
        cnorm = fd + alpha
        a_mean = jnp.where(valid, jnp.where(is_first, 0.0, fd / cnorm), 1.0)
        mid0 = valid & ~is_first
        a_w = jnp.where(mid0, fd / cnorm, jnp.where(valid, 0.0, 1.0))
        a_w2 = jnp.where(
            mid0, (fd * fd) / (cnorm * cnorm), jnp.where(valid, 0.0, 1.0)
        )
        a4 = jnp.stack([a_mean, a_mean, a_w, a_w2], axis=1)
        b4 = jnp.stack(
            [
                jnp.where(valid, jnp.where(is_first, x, alpha * x / cnorm), 0.0),
                jnp.where(valid, jnp.where(is_first, y, alpha * y / cnorm), 0.0),
                jnp.where(mid0, alpha / cnorm, jnp.where(valid, 1.0, 0.0)),
                jnp.where(
                    mid0,
                    (alpha * alpha) / (cnorm * cnorm),
                    jnp.where(valid, 1.0, 0.0),
                ),
            ],
            axis=1,
        )
        mx, my, sum_wt, sum_wt2 = jnp.moveaxis(_linear_scan(a4, b4), 1, 0)
        ow = jnp.where(is_first, 0.0, fd)
        nw = jnp.float64(alpha)

    mid = valid & ~is_first
    mxp = jnp.concatenate([jnp.zeros(1), mx[:-1]])
    myp = jnp.concatenate([jnp.zeros(1), my[:-1]])
    denom_t = jnp.where(mid, ow + nw, 1.0)
    ac = jnp.where(mid, ow / denom_t, jnp.where(valid, 0.0, 1.0))

    def cov_scan(u, v, up, vp, mu, mv):
        cc = jnp.where(
            mid,
            (ow * (up - mu) * (vp - mv) + nw * (u - mu) * (v - mv)) / denom_t,
            0.0,
        )
        return cc

    if op == "cov":
        cov = _linear_scan(ac, cov_scan(x, y, mxp, myp, mx, my))
        if not bias:
            numr = sum_wt * sum_wt
            denr = numr - sum_wt2
            cov = jnp.where(
                denr > 0, cov * numr / jnp.where(denr == 0, 1.0, denr), jnp.nan
            )
        return jnp.where(cnt >= mp, cov, jnp.nan)
    # corr: the three biased covariances share coefficients -> one scan
    a3 = jnp.stack([ac, ac, ac], axis=1)
    b3 = jnp.stack(
        [
            cov_scan(x, y, mxp, myp, mx, my),
            cov_scan(x, x, mxp, mxp, mx, mx),
            cov_scan(y, y, myp, myp, my, my),
        ],
        axis=1,
    )
    cxy, cxx, cyy = jnp.moveaxis(_linear_scan(a3, b3), 1, 0)
    denom = jnp.sqrt(cxx * cyy)
    r = jnp.where(denom > 0, cxy / jnp.where(denom == 0, 1.0, denom), jnp.nan)
    return jnp.where(cnt >= mp, r, jnp.nan)


@functools.lru_cache(maxsize=None)
def _jit_ewm_pair(op: str, n_cols: int, n: int, adjust: bool,
                  ignore_na: bool, bias: bool):
    import jax

    def fn(xs: Tuple, ys: Tuple, alpha, min_periods):
        return tuple(
            _one_ewm_pair(op, x, y, n, alpha, adjust, ignore_na, min_periods, bias)
            for x, y in zip(xs, ys)
        )

    return jax.jit(fn)


def ewm_pair_reduce(
    op: str,
    xs: List[Any],
    ys: List[Any],
    n: int,
    alpha: float,
    adjust: bool,
    ignore_na: bool,
    min_periods: int,
    bias: bool = False,
) -> List[Any]:
    """ewm cov/corr over matched column pairs (padded, logical length n)."""
    fn = _jit_ewm_pair(
        op, len(xs), int(n), bool(adjust), bool(ignore_na), bool(bias)
    )
    return list(fn(tuple(xs), tuple(ys), float(alpha), int(min_periods)))


@functools.lru_cache(maxsize=None)
def _jit_ewm(op: str, n_cols: int, n: int, adjust: bool, ignore_na: bool,
             bias: bool):
    # alpha/min_periods are TRACED (data-dependent sweeps must not recompile)
    import jax

    def fn(cols: Tuple, alpha, min_periods):
        return tuple(
            _one_ewm(op, c, n, alpha, adjust, ignore_na, min_periods, bias)
            for c in cols
        )

    return jax.jit(fn)


def ewm_reduce(
    op: str,
    cols: List[Any],
    n: int,
    alpha: float,
    adjust: bool,
    ignore_na: bool,
    min_periods: int,
    bias: bool = False,
) -> List[Any]:
    """Exponentially weighted aggregation over padded columns."""
    fn = _jit_ewm(op, len(cols), int(n), bool(adjust), bool(ignore_na), bool(bias))
    return list(fn(tuple(cols), float(alpha), int(min_periods)))
