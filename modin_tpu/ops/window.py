"""Rolling-window device kernels (the reference's Fold operator family).

Reference design: modin/core/dataframe/algebra/fold.py:28 + window.py — the
reference ships whole row blocks to workers and runs pandas.rolling per
partition.  Here a rolling sum/count is two cumulative sums and a shifted
difference — O(n) bandwidth-bound work that XLA fuses into one kernel, with
pandas' min_periods/NaN semantics applied via the non-NaN count.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


@functools.lru_cache(maxsize=None)
def _jit_rolling(op: str, n_cols: int, n: int, window: int, min_periods: int):
    import jax
    import jax.numpy as jnp

    def one(c):
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        valid = jnp.arange(c.shape[0]) < n
        nanm = (jnp.isnan(c) | ~valid) if is_f else ~valid
        x = jnp.where(nanm, 0, c).astype(jnp.float64)
        cnt = (~nanm).astype(jnp.int64)
        cs = jnp.cumsum(x)
        cc = jnp.cumsum(cnt)
        # windowed sums: cs[i] - cs[i-window]
        shifted = jnp.concatenate([jnp.zeros(window, cs.dtype), cs[:-window]]) if window <= cs.shape[0] else jnp.zeros_like(cs)
        shifted_c = jnp.concatenate([jnp.zeros(window, cc.dtype), cc[:-window]]) if window <= cc.shape[0] else jnp.zeros_like(cc)
        wsum = cs - shifted
        wcnt = cc - shifted_c
        if op == "count":
            # pandas gates count on the number of ROWS in the window (NaNs
            # included), unlike other aggs which gate on non-NaN observations.
            wrows = jnp.minimum(jnp.arange(c.shape[0]) + 1, window)
            return jnp.where(wrows >= min_periods, wcnt.astype(jnp.float64), jnp.nan)
        if op == "sum":
            # pandas: min_periods=0 makes an all-NaN/empty window sum 0.0
            return jnp.where(wcnt >= min_periods, wsum, jnp.nan)
        if op == "mean":
            res = wsum / jnp.maximum(wcnt, 1)
            return jnp.where((wcnt >= min_periods) & (wcnt > 0), res, jnp.nan)
        raise ValueError(op)

    def fn(cols: Tuple):
        return tuple(one(c) for c in cols)

    return jax.jit(fn)


def rolling_reduce(
    op: str,
    cols: List[Any],
    n: int,
    window: int,
    min_periods: int,
) -> List[Any]:
    """Rolling sum/mean/count over padded columns; one jit for the frame."""
    fn = _jit_rolling(op, len(cols), int(n), int(window), int(min_periods))
    return list(fn(tuple(cols)))
