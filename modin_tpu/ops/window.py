"""Rolling/expanding-window device kernels (the reference's Fold operators).

Reference design: modin/core/dataframe/algebra/fold.py:28 + window.py — the
reference ships whole row blocks to workers and runs pandas.rolling per
partition.  Here every windowed aggregation is O(n) compiled work:

- sum/mean/count/var/std: cumulative sums and shifted differences (var uses
  windowed Σx and Σx² over a globally centered column, which removes the
  E[x²]−E[x]² cancellation);
- min/max: the van Herk/Gil-Werman two-pass — block prefix/suffix extrema
  give any window extremum as max(suffix[i−w+1], prefix[i]) in O(n),
  independent of window size;
- expanding_*: the same formulas with the prefix itself as the window.

pandas' min_periods/NaN semantics apply via the windowed non-NaN count.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np

ROLLING_DEVICE_OPS = ("sum", "mean", "count", "min", "max", "var", "std", "sem")
EXPANDING_DEVICE_OPS = ("sum", "mean", "count", "min", "max", "var", "std", "sem")


def _windowed(arr, window: int):
    """arr[i] - arr[i-window] (prefix-sum difference), pad-agnostic."""
    import jax.numpy as jnp

    if window > arr.shape[0]:
        return arr
    shifted = jnp.concatenate([jnp.zeros(window, arr.dtype), arr[:-window]])
    return arr - shifted


def _van_herk(x, window: int, op: str):
    """Windowed min/max in O(n): block prefix/suffix extrema.

    For window [s, i] (s = i-w+1) spanning blocks b-1 and b of width w,
    suffix[s] covers [s, end of b-1] and prefix[i] covers [start of b, i];
    their cum is exactly the window.  Leading incomplete windows (i < w-1)
    are prefix[i] alone — suffix[0] would leak future rows into them.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    P = x.shape[0]
    w = min(window, P)
    nb = (P + w - 1) // w
    pad = nb * w - P
    neutral = jnp.inf if op == "min" else -jnp.inf
    xp = jnp.concatenate([x, jnp.full(pad, neutral, x.dtype)]) if pad else x
    blocks = xp.reshape(nb, w)
    cum = jnp.minimum if op == "min" else jnp.maximum
    prefix = lax.associative_scan(cum, blocks, axis=1).reshape(-1)[:P]
    suffix = lax.associative_scan(cum, blocks, axis=1, reverse=True).reshape(-1)[:P]
    idx = jnp.arange(P)
    start = jnp.maximum(idx - w + 1, 0)
    out = cum(jnp.take(suffix, start), prefix)
    return jnp.where(idx < w - 1, prefix, out)


def _one_windowed(op: str, c, n: int, window: int, min_periods: int, ddof: int):
    import jax.numpy as jnp

    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    valid = jnp.arange(c.shape[0]) < n
    # pandas _prep_values treats +/-inf as missing in every window agg
    nanm = ((jnp.isnan(c) | jnp.isinf(c)) | ~valid) if is_f else ~valid
    cnt = (~nanm).astype(jnp.int64)
    wcnt = _windowed(jnp.cumsum(cnt), window)

    if op == "count":
        # pandas gates count on ROWS in the window (NaNs included)
        wrows = jnp.minimum(jnp.arange(c.shape[0]) + 1, window)
        return jnp.where(wrows >= min_periods, wcnt.astype(jnp.float64), jnp.nan)

    if op in ("min", "max"):
        neutral = jnp.inf if op == "min" else -jnp.inf
        x = jnp.where(nanm, neutral, c).astype(jnp.float64)
        r = _van_herk(x, window, op)
        return jnp.where(wcnt >= jnp.maximum(min_periods, 1), r, jnp.nan)

    x = jnp.where(nanm, 0, c).astype(jnp.float64)
    if op in ("var", "std", "sem"):
        # center globally first: windowed variance is shift-invariant and
        # Σx² − (Σx)²/n over centered values avoids catastrophic cancellation
        total_cnt = jnp.maximum(jnp.sum(cnt), 1)
        gmean = jnp.sum(x) / total_cnt
        x = jnp.where(nanm, 0.0, x - gmean)
    wsum = _windowed(jnp.cumsum(x), window)

    if op == "sum":
        return jnp.where(wcnt >= min_periods, wsum, jnp.nan)
    if op == "mean":
        res = wsum / jnp.maximum(wcnt, 1)
        return jnp.where((wcnt >= min_periods) & (wcnt > 0), res, jnp.nan)
    # var/std/sem
    wsum2 = _windowed(jnp.cumsum(x * x), window)
    cntf = jnp.maximum(wcnt, 1).astype(jnp.float64)
    var = (wsum2 - wsum * wsum / cntf) / jnp.maximum(wcnt - ddof, 1)
    var = jnp.maximum(var, 0.0)  # guard tiny negative rounding
    gate = (wcnt >= jnp.maximum(min_periods, 1)) & (wcnt - ddof > 0)
    var = jnp.where(gate, var, jnp.nan)
    if op == "var":
        return var
    if op == "std":
        return jnp.sqrt(var)
    return jnp.sqrt(var / cntf)  # sem


@functools.lru_cache(maxsize=None)
def _jit_rolling(op: str, n_cols: int, n: int, window: int, min_periods: int, ddof: int):
    import jax

    def fn(cols: Tuple):
        return tuple(
            _one_windowed(op, c, n, window, min_periods, ddof) for c in cols
        )

    return jax.jit(fn)


def rolling_reduce(
    op: str,
    cols: List[Any],
    n: int,
    window: int,
    min_periods: int,
    ddof: int = 1,
) -> List[Any]:
    """Rolling aggregation over padded columns; one jit for the frame."""
    fn = _jit_rolling(op, len(cols), int(n), int(window), int(min_periods), int(ddof))
    return list(fn(tuple(cols)))


def expanding_reduce(
    op: str, cols: List[Any], n: int, min_periods: int, ddof: int = 1
) -> List[Any]:
    """Expanding aggregation: exactly rolling with the full length as window
    (the prefix-sum differences, van Herk blocks, and gating all degenerate
    to the expanding forms when window >= n)."""
    return rolling_reduce(op, cols, int(n), max(int(n), 1), int(min_periods), int(ddof))
