"""Pairwise statistics kernels: corr / cov as masked matmuls.

Reference design: modin/core/storage_formats/pandas/aggregations.py:31
(CorrCovBuilder) computes per-block sums-of-products then combines across
partitions.  On TPU the whole thing is three matmuls on the MXU: with
Z = values (NaN→0) and V = validity masks, every pairwise-complete sum the
Pearson formula needs is a (k x n) @ (n x k) product —

    N  = Vᵀ V         pairwise-complete counts
    S  = Zᵀ V         per-pair sums  (S[i,j] = Σ x_i over rows valid in both)
    P  = Zᵀ Z         per-pair product sums
    Q  = (Z∘Z)ᵀ V     per-pair square sums

— so the n-row scan is entirely MXU work and the k x k combine is elementwise.
pandas semantics: pairwise-complete observations, min_periods gating, NaN
where a pair has no (or insufficient) data.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


from modin_tpu.parallel.engine import materialize as _engine_materialize


@functools.lru_cache(maxsize=None)
def _jit_corr_cov(
    method: str, n_cols: int, n: int, ddof: int, min_periods: int
):
    import jax
    import jax.numpy as jnp

    def fn(cols: Tuple):
        X = jnp.stack([c.astype(jnp.float64) for c in cols], axis=1)  # (P, k)
        valid_rows = (jnp.arange(X.shape[0]) < n)[:, None]
        V = (~jnp.isnan(X)) & valid_rows
        Z = jnp.where(V, X, 0.0)
        Vf = V.astype(jnp.float64)

        N = Vf.T @ Vf                     # pairwise-complete counts
        S = Z.T @ Vf                      # S[i, j] = sum x_i over both-valid
        P = Z.T @ Z                       # sum x_i * x_j
        Q = (Z * Z).T @ Vf                # sum x_i^2 over both-valid

        Nsafe = jnp.maximum(N, 1.0)
        # pandas quirk: with any NaN present, DataFrame.cov takes the
        # pairwise-complete path which always divides by N-1, ignoring ddof
        has_nan = jnp.any(jnp.isnan(X) & valid_rows)
        eff_ddof = jnp.where(has_nan, 1.0, float(ddof))
        # pairwise covariance: E[xy] - E[x]E[y], scaled by (N - ddof)
        cov = (P - S * S.T / Nsafe) / jnp.maximum(N - eff_ddof, 1.0)
        if method == "cov":
            out = jnp.where(N - eff_ddof > 0, cov, jnp.nan)
        else:
            var_i = (Q - S * S / Nsafe) / jnp.maximum(N - ddof, 1.0)
            var_j = var_i.T
            denom = jnp.sqrt(var_i * var_j)
            out = jnp.where(denom > 0, cov / denom, jnp.nan)
            out = jnp.clip(out, -1.0, 1.0)
        out = jnp.where(N >= max(min_periods, 1), out, jnp.nan)
        return out, N

    return jax.jit(fn)


def corr_cov_matrix(
    cols: List[Any],
    n: int,
    method: str = "corr",
    ddof: int = 1,
    min_periods: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """(k x k matrix, pairwise counts) on host for the given device columns."""
    import jax

    fn = _jit_corr_cov(method, len(cols), int(n), int(ddof), int(min_periods))
    out, counts = fn(tuple(cols))
    out_h, counts_h = _engine_materialize((out, counts))
    return np.asarray(out_h), np.asarray(counts_h)
