"""modin_tpu subpackage."""
