"""Pallas TPU kernels for the groupby hot path.

``bincount``: the histogram that backs factorize's direct-range coding and the
``size``/``count`` aggregations.  XLA lowers ``zeros().at[codes].add(1)`` to a
scatter-add, which serializes badly on TPU (measured ~1s for 1e7 rows); this
kernel instead streams code blocks through VMEM and accumulates a one-hot
compare on the VPU — O(n*G) elementwise work with no scatter, exact int32
arithmetic.

Used on the TPU backend for group widths <= ``MAX_GROUPS``; everywhere else
the XLA scatter path stays (CPU scatters are fine).  Interpret mode makes the
kernel testable on CPU.
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

# block of codes processed per grid step: BR sublanes x 128 lanes
_BR = 32
_LANES = 128
MAX_GROUPS = 512  # one-hot block is BR*128*ceil(G/128)*128 ints in VMEM


@functools.lru_cache(maxsize=None)
def _build_bincount(n_blocks: int, g_padded: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu

        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    def kernel(codes_ref, out_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        codes_block = codes_ref[:]  # [_BR, _LANES] int32
        group_ids = jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, g_padded), dimension=2
        )
        onehot = (codes_block[:, :, None] == group_ids).astype(jnp.int32)
        # pin the accumulation dtype: with x64 enabled jnp.sum follows numpy
        # and widens int32 sums to int64, which TPU pallas cannot lower
        partial = jnp.sum(onehot, axis=(0, 1), dtype=jnp.int32)  # [g_padded]
        out_ref[0, :] += partial

    block_spec_kwargs = {"memory_space": vmem} if vmem is not None else {}
    # index maps must yield int32: with x64 enabled a literal 0 traces as a
    # weak int64 and Mosaic refuses the (i32, i64) index tuple
    zero = np.int32(0)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, g_padded), jnp.int32),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((_BR, _LANES), lambda i: (i, zero), **block_spec_kwargs)
        ],
        out_specs=pl.BlockSpec(
            (1, g_padded), lambda i: (zero, zero), **block_spec_kwargs
        ),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _jit_bincount_wrapper(p_len: int, num_groups: int, interpret: bool):
    import jax
    import jax.numpy as jnp

    # slots for every real group + the overflow bucket, padded to lanes
    g_padded = max(-(-(num_groups + 1) // _LANES) * _LANES, _LANES)
    block_elems = _BR * _LANES
    n_blocks = -(-p_len // block_elems)
    padded_len = n_blocks * block_elems
    call = _build_bincount(n_blocks, g_padded, interpret)

    def fn(codes):
        c = codes.astype(jnp.int32)
        if padded_len > p_len:
            # overflow bucket: padded tail must not count toward any group
            c = jnp.concatenate(
                [c, jnp.full(padded_len - p_len, num_groups, jnp.int32)]
            )
        counts = call(c.reshape(n_blocks * _BR, _LANES))
        return counts[0, :num_groups].astype(jnp.int64)

    return jax.jit(fn)


def pallas_bincount(codes: Any, num_groups: int, interpret: bool = False) -> Any:
    """Counts per group code; codes >= num_groups (pads/overflow) are dropped.

    Returns an int64 device array of length ``num_groups``.
    """
    if num_groups > MAX_GROUPS:
        raise ValueError(f"pallas_bincount supports <= {MAX_GROUPS} groups")
    return _jit_bincount_wrapper(int(codes.shape[0]), int(num_groups), bool(interpret))(
        codes
    )


def bincount_supported(codes: Any, num_groups: int) -> bool:
    """Whether the pallas histogram should be used for this input."""
    if num_groups > MAX_GROUPS or num_groups < 1:
        return False
    try:
        platform = next(iter(codes.devices())).platform
    except Exception:  # graftlint: disable=EXC-HYGIENE -- device-platform probe; any failure means 'no pallas path'
        return False
    return platform == "tpu"
