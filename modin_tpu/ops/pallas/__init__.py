"""modin_tpu subpackage."""
