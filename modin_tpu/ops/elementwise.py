"""Elementwise device kernels: maps and binary ops over column sets.

TPU-native replacement for the reference's Map/Binary operators over block
partitions (modin/core/dataframe/algebra/map.py:28, binary.py:293): instead of
one task per partition, ALL device columns go through ONE jit call as a
pytree, so XLA fuses the whole frame-wide expression and the dispatch cost is
paid once (the tunnel RTT floor dominates per-call cost on remote TPUs).

Pandas semantic deltas handled here:
- int / int true-division promotes to float64 and yields +/-inf on zero
  division (numpy raises/warns; jnp matches IEEE, which is what pandas does);
- int floordiv/mod with a zero divisor promotes to float64 (inf/nan) in
  pandas 3 — a data-dependent dtype, so the QC gates those cases to the
  pandas fallback; the kernels' zero-masking only backstops traced scalar
  divisors that are known nonzero at dispatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional, Tuple

import numpy as np


def _trim(x, p_out):
    """Slice a padded column down to a smaller padded size, keeping the
    rows axis sharded (a bare slice can come back replicated)."""
    import jax

    from modin_tpu.parallel.mesh import row_sharding

    return jax.lax.with_sharding_constraint(x[:p_out], row_sharding())


def _floordiv(x, y):
    import jax.numpy as jnp

    if jnp.issubdtype(jnp.result_type(x, y), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        return jnp.where(y == 0, 0, x // safe)
    return x // y


def _mod(x, y):
    import jax.numpy as jnp

    if jnp.issubdtype(jnp.result_type(x, y), jnp.integer):
        safe = jnp.where(y == 0, 1, y)
        return jnp.where(y == 0, 0, x % safe)
    return x % y


def _truediv(x, y):
    import jax.numpy as jnp

    res_dtype = jnp.result_type(x, y)
    if jnp.issubdtype(res_dtype, jnp.integer) or res_dtype == jnp.bool_:
        x = x.astype(jnp.float64) if hasattr(x, "astype") else jnp.float64(x)
    return x / y


def _build_ops() -> dict:
    import jax.numpy as jnp

    return {
        "add": lambda x, y: x + y,
        "radd": lambda x, y: y + x,
        "sub": lambda x, y: x - y,
        "rsub": lambda x, y: y - x,
        "mul": lambda x, y: x * y,
        "rmul": lambda x, y: y * x,
        "truediv": _truediv,
        "rtruediv": lambda x, y: _truediv(y, x) if not np.isscalar(y) else _truediv(jnp.asarray(y), x),
        "floordiv": _floordiv,
        "rfloordiv": lambda x, y: _floordiv(y, x),
        "mod": _mod,
        "rmod": lambda x, y: _mod(y, x),
        "pow": lambda x, y: x ** y,
        "rpow": lambda x, y: y ** x,
        "eq": lambda x, y: x == y,
        "ne": lambda x, y: x != y,
        "lt": lambda x, y: x < y,
        "le": lambda x, y: x <= y,
        "gt": lambda x, y: x > y,
        "ge": lambda x, y: x >= y,
        "__and__": lambda x, y: x & y,
        "__or__": lambda x, y: x | y,
        "__xor__": lambda x, y: x ^ y,
        "__rand__": lambda x, y: y & x,
        "__ror__": lambda x, y: y | x,
        "__rxor__": lambda x, y: y ^ x,
        # membership against a runtime value ARRAY (one compile per list
        # length, values stay jit arguments); the _nan variant adds pandas'
        # NaN-matches-NaN rule when the value list contains NaN
        "isin_vals": lambda x, v: jnp.isin(x, v),
        "isin_vals_nan": lambda x, v: jnp.isin(x, v) | jnp.isnan(x),
        # unary
        "abs": lambda x: abs(x),
        "negative": lambda x: -x,
        "invert": lambda x: ~x,
        "isna": lambda x: jnp.isnan(x) if jnp.issubdtype(x.dtype, jnp.floating) else jnp.zeros(x.shape, bool),
        "notna": lambda x: ~jnp.isnan(x) if jnp.issubdtype(x.dtype, jnp.floating) else jnp.ones(x.shape, bool),
        "sqrt": lambda x: jnp.sqrt(x),
        "exp": lambda x: jnp.exp(x),
        "log": lambda x: jnp.log(x),
        "log2": lambda x: jnp.log2(x),
        "log10": lambda x: jnp.log10(x),
        "sin": lambda x: jnp.sin(x),
        "cos": lambda x: jnp.cos(x),
        "tan": lambda x: jnp.tan(x),
        "tanh": lambda x: jnp.tanh(x),
        "floor": lambda x: jnp.floor(x),
        "ceil": lambda x: jnp.ceil(x),
        "sign": lambda x: jnp.sign(x),
        # cumulative ops with pandas skipna semantics: NaN keeps its position
        # but does not poison later entries
        "cumsum": lambda x: _nan_skipping_cum(x, jnp.cumsum, 0),
        "cumprod": lambda x: _nan_skipping_cum(x, jnp.cumprod, 1),
        "cummax": lambda x: _nan_skipping_cum(x, jax_lax_cummax, -jnp.inf),
        "cummin": lambda x: _nan_skipping_cum(x, jax_lax_cummin, jnp.inf),
        # physical resize to the padded-output invariant after a device
        # compaction (ops/structural.py); p_out is compiled into the program
        "trim": _trim,
        "round": lambda x, decimals: (
            jnp.round(x, decimals) if jnp.issubdtype(x.dtype, jnp.floating) else x
        ),
        "astype": lambda x, dtype: x.astype(dtype),
        "isna_nat": lambda x: x == _NAT_SENTINEL,
        "notna_nat": lambda x: x != _NAT_SENTINEL,
        "fillna": lambda x, v: (
            jnp.where(jnp.isnan(x), v, x) if jnp.issubdtype(x.dtype, jnp.floating) else x
        ),
        "clip_lower": lambda x, lo: jnp.where(x < lo, lo, x),
        "clip_upper": lambda x, hi: jnp.where(x > hi, hi, x),
    }


def _nan_skipping_cum(x, cum_fn, neutral):
    import jax.numpy as jnp

    if not jnp.issubdtype(x.dtype, jnp.floating):
        return cum_fn(x)
    nanm = jnp.isnan(x)
    filled = cum_fn(jnp.where(nanm, neutral, x))
    return jnp.where(nanm, jnp.nan, filled)


def jax_lax_cummax(x):
    import jax.lax as lax

    return lax.cummax(x, axis=0)


def jax_lax_cummin(x):
    import jax.lax as lax

    return lax.cummin(x, axis=0)


_OPS: dict = {}


def _ensure_ops() -> None:
    global _OPS
    if not _OPS:
        _OPS.update(_build_ops())


def get_op(op_name: str) -> Callable:
    """Elementwise op registry accessor (used by the lazy fusion layer)."""
    _ensure_ops()
    return _OPS[op_name]


def binary_op_columns(op_name: str, cols: List[Any], other: Any) -> List[Any]:
    """Deferred binary op on device columns vs a scalar or matching columns.

    Returns :class:`~modin_tpu.ops.lazy.LazyExpr` nodes: nothing dispatches
    until a consumer needs concrete data, at which point the whole
    accumulated chain compiles as one fused jit (ops/lazy.py).
    """
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    if isinstance(other, (list, tuple)):
        return [lazy_op(op_name, c, o) for c, o in zip(cols, other)]
    return [lazy_op(op_name, c, other) for c in cols]


def unary_op_columns(op_name: str, cols: List[Any]) -> List[Any]:
    """Deferred unary op on device columns (see binary_op_columns)."""
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    return [lazy_op(op_name, c) for c in cols]


_NAT_SENTINEL = np.iinfo(np.int64).min


def isna_columns(cols: List[Any], mM_flags: Tuple[bool, ...], negate: bool) -> List[Any]:
    """Deferred isna/notna, NaT-sentinel-aware for datetime-backed columns."""
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    out = []
    for c, is_dt in zip(cols, mM_flags):
        if is_dt:
            out.append(lazy_op("notna_nat" if negate else "isna_nat", c))
        else:
            out.append(lazy_op("notna" if negate else "isna", c))
    return out


def round_columns(cols: List[Any], decimals: int) -> List[Any]:
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    static = (("decimals", int(decimals)),)
    return [lazy_op("round", c, static=static) for c in cols]


def fillna_columns(cols: List[Any], value: Any) -> List[Any]:
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    return [lazy_op("fillna", c, value) for c in cols]


def clip_columns(cols: List[Any], lower: Any, upper: Any) -> List[Any]:
    from modin_tpu.ops.lazy import lazy_op

    _ensure_ops()
    out = []
    for c in cols:
        r = c
        if lower is not None:
            r = lazy_op("clip_lower", r, lower)
        if upper is not None:
            r = lazy_op("clip_upper", r, upper)
        out.append(r)
    return out


@functools.lru_cache(maxsize=None)
def _jit_shift(n_cols: int, n: int, periods: int, as_diff: bool):
    import jax
    import jax.numpy as jnp

    def one(c):
        k = abs(periods)
        if k == 0:
            if as_diff:
                # pandas diff(0) still promotes ints to float64
                return (c - c).astype(jnp.float64)
            return c  # shift(0) preserves the dtype
        if k >= n:
            # pandas: shifting past the frame is all-NaN (diff likewise)
            return jnp.full(c.shape, jnp.nan, jnp.float64)
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        x = c.astype(jnp.float64) if not is_f else c
        if periods >= 0:
            shifted = jnp.concatenate(
                [jnp.full(k, jnp.nan, x.dtype), x[: x.shape[0] - k]]
            )
        else:
            shifted = jnp.concatenate([x[k:], jnp.full(k, jnp.nan, x.dtype)])
            # mask the region beyond the logical length: rows shifted in from
            # pads must read as missing
            valid_src = jnp.arange(x.shape[0]) + k < n
            shifted = jnp.where(valid_src, shifted, jnp.nan)
        if as_diff:
            return x - shifted
        return shifted

    def fn(cols: Tuple) -> Tuple:
        return tuple(one(c) for c in cols)

    return jax.jit(fn)


def shift_columns(cols: List[Any], n: int, periods: int) -> List[Any]:
    """pandas shift: rows move by ``periods`` with NaN fill (float64 result)."""
    return list(_jit_shift(len(cols), int(n), int(periods), False)(tuple(cols)))


def diff_columns(cols: List[Any], n: int, periods: int) -> List[Any]:
    """pandas diff: x - x.shift(periods) (float64 result)."""
    return list(_jit_shift(len(cols), int(n), int(periods), True)(tuple(cols)))


def astype_column(col: Any, target: np.dtype) -> Any:
    import jax.numpy as jnp

    return col.astype(jnp.dtype(target))

