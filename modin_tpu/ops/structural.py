"""Structural device kernels: pad-aware gather, slice, and concat.

Device columns are padded to a multiple of the mesh row-shard count so
``device_put``/jit keep the rows axis sharded (XLA requires even shards for
explicitly laid-out arrays; uneven results fall back to replication).  Every
kernel here receives the **logical** lengths statically and never reads pad
rows.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from modin_tpu.observability import costs as _costs

#: graftfuse adaptive padding: while a quantizer is installed on this
#: thread, ``pad_host`` rounds its padded length up through it, so a scan
#: whose plan signature the compile ledger reports as a recompile storm
#: uploads at a shared bucket size instead of an exact one.  Scoped (the
#: fused lowering wraps ONLY its leaf-scan lowering) and thread-local, so
#: nothing else in the process ever sees a quantized pad.
_bucket_tls = threading.local()


@contextlib.contextmanager
def pad_bucket_scope(quantizer: Optional[Callable[[int], int]]):
    """Install ``quantizer`` (padded length -> bucketed padded length) for
    ``pad_host`` calls on this thread; ``None`` is a no-op scope."""
    if quantizer is None:
        yield
        return
    prev = getattr(_bucket_tls, "quantize", None)
    _bucket_tls.quantize = quantizer
    try:
        yield
    finally:
        _bucket_tls.quantize = prev


def float_total_order(x):
    """Monotone float -> int64 mapping with a strict IEEE total order.

    -0.0 == 0.0, every NaN maps to one key ABOVE +inf (so NaN sorts strictly
    after inf instead of tying with it), and ordering elsewhere matches <.
    Shared by the sort and join kernels.
    """
    import jax
    import jax.numpy as jnp

    # canonicalize: XLA folds x+0.0 to x, so -0.0 needs an explicit where
    x = jnp.where(x == 0, 0.0, x)
    x = jnp.where(jnp.isnan(x), jnp.nan, x)
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)
    return jnp.where(bits >= 0, bits, (~bits) ^ np.int64(-(2**63)))


def pad_len(n: int) -> int:
    """Smallest multiple of the mesh row-shard count >= n (and >= 1 shard)."""
    from modin_tpu.parallel.mesh import num_row_shards

    s = num_row_shards()
    return max(((n + s - 1) // s) * s, s)


def pad_host(values: np.ndarray, n: int | None = None) -> np.ndarray:
    """Pad a host array with zeros to the sharded length (quantized up to
    the active graftfuse pad bucket when one is installed)."""
    n = len(values) if n is None else n
    p = pad_len(n)
    quantize = getattr(_bucket_tls, "quantize", None)
    if quantize is not None:
        # re-run pad_len so a quantizer that answers off the shard grid
        # still lands on an even shard split
        p = pad_len(max(p, int(quantize(p))))
    if _costs.COST_ON:
        _costs.note_padding(
            "structural.pad_host",
            p * values.dtype.itemsize,
            len(values) * values.dtype.itemsize,
        )
    if len(values) == p:
        return values
    pad_block = np.zeros(p - len(values), dtype=values.dtype)
    return np.concatenate([values, pad_block])


@functools.lru_cache(maxsize=None)
def _jit_gather(n_cols: int):
    import jax
    import jax.numpy as jnp

    def fn(cols: Tuple, positions):
        return tuple(jnp.take(c, positions, axis=0) for c in cols)

    return jax.jit(fn)


def compact_rows(cols: List[Any], mask: Any, n: int) -> Tuple[List[Any], Any, Any]:
    """Device-side boolean-filter: kept rows compacted to the front.

    ``cols``/``mask`` may be deferred LazyExprs — the mask computation (e.g.
    ``df.a > 0``) fuses into the compaction program.  Returns (gathered
    columns, kept-count scalar, kept-positions array), all still on device:
    the only host sync a filter needs is the scalar count (one RTT over a
    remote tunnel, versus shipping an O(n) mask to host and positions back).
    Outputs keep the input padded size; pad rows land at the tail.
    """
    from modin_tpu.ops.lazy import run_fused

    def tail(arrs):
        import jax.numpy as jnp

        *col_arrs, m = arrs
        valid = jnp.arange(m.shape[0]) < n
        keep = m & valid
        # stable argsort of "dropped" puts kept rows first, original order
        perm = jnp.argsort(~keep, stable=True)
        count = jnp.sum(keep)
        return tuple(jnp.take(c, perm, axis=0) for c in col_arrs), count, perm

    return run_fused(
        [*cols, mask],
        tail_key=("compact_rows", len(cols), int(n)),
        tail_builder=tail,
    )


def gather_columns(cols: List[Any], positions: np.ndarray) -> Tuple[List[Any], int]:
    """Gather logical positions from padded columns.

    Returns (new padded device arrays, logical length).  The positions array
    is itself padded with 0 so the gather output stays evenly sharded.
    """
    from modin_tpu.parallel.engine import JaxWrapper

    n_out = len(positions)
    padded = pad_host(np.asarray(positions, dtype=np.int64), n_out)
    device_positions = JaxWrapper.put(padded)
    return (
        list(
            JaxWrapper.deploy(
                _jit_gather(len(cols)), (tuple(cols), device_positions)
            )
        ),
        n_out,
    )


def gather_columns_device(cols: List[Any], device_positions: Any) -> List[Any]:
    """Gather with an already-padded device positions array."""
    from modin_tpu.parallel.engine import JaxWrapper

    return list(
        JaxWrapper.deploy(_jit_gather(len(cols)), (tuple(cols), device_positions))
    )


@functools.lru_cache(maxsize=None)
def _jit_concat(n_parts: int, n_cols: int, lengths: Tuple[int, ...], p_out: int):
    import jax
    import jax.numpy as jnp

    def fn(parts: Tuple[Tuple, ...]):
        # parts[i] is the tuple of columns of part i (all padded)
        offsets = []
        off = 0
        for i in range(n_parts):
            offsets.append(off)
            off += parts[i][0].shape[0]
        # positions into the naive concatenation that skip the pads
        pos_list = [
            jnp.arange(lengths[i], dtype=jnp.int64) + offsets[i]
            for i in range(n_parts)
        ]
        total = sum(lengths)
        pos = jnp.concatenate(pos_list + [jnp.zeros(p_out - total, jnp.int64)])
        out = []
        for ci in range(n_cols):
            big = jnp.concatenate([parts[i][ci] for i in range(n_parts)])
            out.append(jnp.take(big, pos, axis=0))
        return tuple(out)

    return jax.jit(fn)


#: tail appends at least this many times smaller than the prefix take the
#: micro-batch fast path (graftfeed ingest: a 1k-row batch onto a 10M-row
#: feed must not re-gather all 10M rows).  Module-level so the ingest bench
#: can disable the fast path to measure the win honestly.
_APPEND_FASTPATH_RATIO = 8


@functools.lru_cache(maxsize=None)
def _jit_tail_append(n_cols: int, p_out: int):
    """Append a small tail onto a large prefix WITHOUT the gather re-layout
    of ``_jit_concat``: the prefix is copied once into the grown buffer
    (a contiguous memcpy XLA fuses, not an O(p_out) dynamic-index take) and
    the tail rows are placed at ``[start, start + tail_n)`` via roll+where.
    ``start``/``tail_n`` are dynamic scalars, so the compiled program is
    keyed only on the padded shapes — consecutive micro-batch appends that
    land inside the same pad bucket reuse it."""
    import jax
    import jax.numpy as jnp

    def fn(prefix: Tuple, tail: Tuple, start, tail_n):
        idx = jnp.arange(p_out, dtype=jnp.int64)
        in_tail = (idx >= start) & (idx < start + tail_n)
        out = []
        for ci in range(n_cols):
            big = prefix[ci]
            grown = jnp.zeros((p_out,), big.dtype).at[: big.shape[0]].set(big)
            t = tail[ci]
            tpad = jnp.zeros((p_out,), t.dtype).at[: t.shape[0]].set(t)
            # no wrap in the selected region: start + tail_n <= p_out
            rolled = jnp.roll(tpad, start, axis=0)
            out.append(jnp.where(in_tail, rolled, grown))
        return tuple(out)

    return jax.jit(fn)


def concat_columns(parts: List[List[Any]], lengths: List[int]) -> Tuple[List[Any], int]:
    """Row-concat column sets (each padded), producing padded outputs."""
    from modin_tpu.logging.metrics import emit_metric
    from modin_tpu.parallel.engine import JaxWrapper

    n_out = sum(lengths)
    p_out = pad_len(n_out)
    if (
        len(parts) == 2
        and lengths[1] > 0
        and lengths[1] * _APPEND_FASTPATH_RATIO <= lengths[0]
        and all(getattr(c, "ndim", 0) == 1 for p in parts for c in p)
        # physical sizes may exceed the minimal pad (graftfuse pad buckets)
        and all(c.shape[0] <= p_out for p in parts for c in p)
    ):
        fn = _jit_tail_append(len(parts[0]), p_out)
        out = list(
            JaxWrapper.deploy(
                fn,
                (
                    tuple(parts[0]),
                    tuple(parts[1]),
                    np.int64(lengths[0]),
                    np.int64(lengths[1]),
                ),
            )
        )
        emit_metric("structural.append_fastpath", 1)
        return out, n_out
    fn = _jit_concat(len(parts), len(parts[0]), tuple(lengths), p_out)
    return list(JaxWrapper.deploy(fn, (tuple(tuple(p) for p in parts),))), n_out


