"""Device datetime component extraction (``.dt.year`` & co).

Datetime columns live on device as int64 ticks of their unit (NaT = int64
min, pandas' own sentinel — core/dataframe/tpu/dataframe.py).  Every
calendar component is branchless integer arithmetic over those ticks:

- civil date from day number via the Gregorian-era decomposition
  (Howard Hinnant's public-domain ``civil_from_days`` algorithm —
  days-per-era constants 146097/36524/1460/365),
- time-of-day components from the tick remainder,
- predicates (is_month_start, ...) from the decomposed pieces.

The reference extracts these host-side through pandas' tslib per partition
(modin/core/dataframe/algebra/default2pandas/series.py DateTimeDefault);
here one jit per column handles 1e8 rows without leaving HBM.

Output dtype follows pandas: int32 for clean columns, float64 with NaN when
NaT is present (the caller decides from the returned NaT flag), bool for
predicates (NaT rows are False like pandas).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import numpy as np

_NAT = np.iinfo(np.int64).min

# ticks per second by numpy datetime unit
_TPS = {"s": 1, "ms": 10**3, "us": 10**6, "ns": 10**9}

# cumulative days before month m (1-indexed; non-leap)
_CUMDAYS = np.array(
    [0, 0, 31, 59, 90, 120, 151, 181, 212, 243, 273, 304, 334], np.int64
)
_DAYS_IN_MONTH = np.array(
    [0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], np.int64
)

COMPONENT_NAMES = (
    "year", "month", "day", "hour", "minute", "second", "microsecond",
    "nanosecond", "dayofweek", "weekday", "day_of_week", "dayofyear",
    "day_of_year", "quarter", "daysinmonth", "days_in_month",
    "is_leap_year", "is_month_start", "is_month_end", "is_quarter_start",
    "is_quarter_end", "is_year_start", "is_year_end",
)

_BOOL_COMPONENTS = frozenset(
    n for n in COMPONENT_NAMES if n.startswith("is_")
)

# timedelta64 columns: pandas Timedelta field semantics (days floors toward
# -inf; seconds/microseconds/nanoseconds are the NON-NEGATIVE remainders)
TIMEDELTA_COMPONENT_NAMES = (
    "days", "seconds", "microseconds", "nanoseconds", "total_seconds",
)


from modin_tpu.parallel.engine import materialize as _engine_materialize


@functools.lru_cache(maxsize=None)
def _jit_td_component(name: str, unit: str, n: int, want_float: bool = False):
    import jax
    import jax.numpy as jnp

    tps = _TPS[unit]
    day_ticks = 86400 * tps

    def fn(ticks):
        valid = (jnp.arange(ticks.shape[0]) < n) & (ticks != _NAT)
        t = jnp.where(valid, ticks, 0)
        days = jnp.floor_divide(t, day_ticks)
        rem = t - days * day_ticks  # [0, day_ticks)
        if name == "days":
            out = days
        elif name == "seconds":
            out = rem // tps
        elif name == "microseconds":
            out = ((rem % tps) * (10**9 // tps)) // 1000
        elif name == "nanoseconds":
            out = ((rem % tps) * (10**9 // tps)) % 1000
        elif name == "total_seconds":
            out = t.astype(jnp.float64) / tps
        else:  # pragma: no cover - gated by TIMEDELTA_COMPONENT_NAMES
            raise AssertionError(name)
        has_nat = jnp.any((jnp.arange(ticks.shape[0]) < n) & (ticks == _NAT))
        if name == "total_seconds" or want_float:
            return (
                jnp.where(valid, out.astype(jnp.float64), jnp.nan),
                has_nat,
            )
        dtype = jnp.int64 if name == "days" else jnp.int32
        return jnp.where(valid, out, 0).astype(dtype), has_nat

    return jax.jit(fn)


def td_component(name: str, ticks: Any, unit: str, n: int) -> Tuple[Any, Any]:
    """(device result, out_dtype) for one timedelta field; int64 days /
    int32 remainders upcast to float64+NaN exactly when NaT is present,
    total_seconds is float64 always."""
    import jax

    if name == "total_seconds":
        out, _ = _jit_td_component(name, unit, int(n))(ticks)
        return out, np.dtype(np.float64)
    out_i, has_nat = _jit_td_component(name, unit, int(n))(ticks)
    if bool(_engine_materialize(has_nat)):
        out_f, _ = _jit_td_component(name, unit, int(n), want_float=True)(ticks)
        return out_f, np.dtype(np.float64)
    return out_i, np.dtype(np.int64 if name == "days" else np.int32)


def is_bool_component(name: str) -> bool:
    return name in _BOOL_COMPONENTS


@functools.lru_cache(maxsize=None)
def _jit_component(name: str, unit: str, n: int, want_float: bool = False):
    import jax
    import jax.numpy as jnp

    tps = _TPS[unit]
    day_ticks = 86400 * tps

    def fn(ticks):
        valid = (jnp.arange(ticks.shape[0]) < n) & (ticks != _NAT)
        t = jnp.where(valid, ticks, 0)
        days = jnp.floor_divide(t, day_ticks)
        tod = t - days * day_ticks  # [0, day_ticks)

        # civil_from_days (Gregorian, proleptic)
        z = days + 719468
        era = jnp.floor_divide(z, 146097)
        doe = z - era * 146097
        yoe = jnp.floor_divide(
            doe - doe // 1460 + doe // 36524 - doe // 146096, 365
        )
        y = yoe + era * 400
        doy_mar = doe - (365 * yoe + yoe // 4 - yoe // 100)
        mp = jnp.floor_divide(5 * doy_mar + 2, 153)
        d = doy_mar - jnp.floor_divide(153 * mp + 2, 5) + 1
        m = mp + jnp.where(mp < 10, 3, -9)
        y = y + (m <= 2)

        leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
        dim = jnp.take(jnp.asarray(_DAYS_IN_MONTH), m, mode="clip") + (
            (m == 2) & leap
        )
        if name == "year":
            out = y
        elif name == "month":
            out = m
        elif name == "day":
            out = d
        elif name == "hour":
            out = tod // (3600 * tps)
        elif name == "minute":
            out = (tod // (60 * tps)) % 60
        elif name == "second":
            out = (tod // tps) % 60
        elif name == "microsecond":
            ns_of_sec = (tod % tps) * (10**9 // tps)
            out = ns_of_sec // 1000
        elif name == "nanosecond":
            ns_of_sec = (tod % tps) * (10**9 // tps)
            out = ns_of_sec % 1000
        elif name in ("dayofweek", "weekday", "day_of_week"):
            out = (days + 3) % 7  # 1970-01-01 is a Thursday (Monday=0 -> 3)
        elif name in ("dayofyear", "day_of_year"):
            out = (
                jnp.take(jnp.asarray(_CUMDAYS), m, mode="clip")
                + d
                + ((m > 2) & leap)
            )
        elif name == "quarter":
            out = (m + 2) // 3
        elif name in ("daysinmonth", "days_in_month"):
            out = dim
        elif name == "is_leap_year":
            out = leap
        elif name == "is_month_start":
            out = d == 1
        elif name == "is_month_end":
            out = d == dim
        elif name == "is_quarter_start":
            out = (d == 1) & (m % 3 == 1)
        elif name == "is_quarter_end":
            out = (d == dim) & (m % 3 == 0)
        elif name == "is_year_start":
            out = (m == 1) & (d == 1)
        elif name == "is_year_end":
            out = (m == 12) & (d == 31)
        else:  # pragma: no cover - gated by COMPONENT_NAMES
            raise AssertionError(name)

        has_nat = jnp.any((jnp.arange(ticks.shape[0]) < n) & (ticks == _NAT))
        if name in _BOOL_COMPONENTS:
            # pandas: NaT rows are False for the predicates
            return jnp.where(valid, out, False), has_nat
        if want_float:
            return jnp.where(valid, out.astype(jnp.float64), jnp.nan), has_nat
        return jnp.where(valid, out, 0).astype(jnp.int32), has_nat

    return jax.jit(fn)


def dt_component(name: str, ticks: Any, unit: str, n: int) -> Tuple[Any, Any]:
    """(device result, out_dtype) for one datetime component.

    One extra scalar fetch decides int32 vs float64 (pandas upcasts exactly
    when NaT is present)."""
    import jax

    fn = _jit_component(name, unit, int(n))
    if name in _BOOL_COMPONENTS:
        out, has_nat = fn(ticks)
        return out, np.dtype(bool)
    # the clean (no-NaT) path runs ONE int32 kernel; only a NaT column pays
    # for the float64 variant (pandas upcasts exactly then)
    out_i, has_nat = fn(ticks)
    if bool(_engine_materialize(has_nat)):
        out_f, _ = _jit_component(name, unit, int(n), want_float=True)(ticks)
        return out_f, np.dtype(np.float64)
    return out_i, np.dtype(np.int32)
