"""Per-column sorted-representation cache (graftsort).

The sort-shaped reductions (median, quantile, nunique, mode) all begin with
the same prefix: sort the column with NaN/pad rows collapsed to the tail and
count the valid prefix (``ops/sort.py sorted_valid_columns``).  Before this
cache, that prefix was recomputed inside every op's own jit — four ops on
one column paid four O(n log n) sorts.  Now the first op attaches the
``(sorted values, n_valid)`` pair to its ``DeviceColumn`` as a
:class:`SortedRep` and every later op consumes it with an O(n) pass.

Correctness contract:

- **Identity**: a rep is valid only while the column still holds the exact
  buffer it was computed from (``source_id == id(col._data)``) in the
  current device epoch.  Every mutation of the column's buffer — spill,
  spill-restore, lineage re-seat, lazy materialization — additionally drops
  the rep eagerly (``DeviceColumn._invalidate_sorted``), so the identity
  check is belt-and-braces, not the only line of defense.
- **Memory**: the rep's device buffer is registered in the
  ``_DeviceLedger`` (core/memory.py) like any column buffer, so admission
  control and the OOM evict-then-retry leg can reclaim it.  "Spilling" a
  rep just drops it — derived data needs no host copy; the next sort-shaped
  op rebuilds it.
- **Recovery**: after a device loss the graftguard reseat pass walks the
  same ledger; a rep is recognized (``is_derived_cache``) and dropped
  instead of replayed — it is disposable, never unrecoverable.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from modin_tpu.logging.metrics import emit_metric


class SortedRep:
    """One column's cached sorted representation, device-ledger-tracked."""

    __slots__ = ("_data", "n_valid", "source_id", "epoch", "_dev_key", "__weakref__")

    #: recovery marker: reseat passes drop derived caches instead of
    #: replaying lineage for them (core/execution/recovery.py)
    is_derived_cache = True
    is_lazy = False

    def __init__(self, data: Any, n_valid: Any, source_id: int, epoch: int):
        self._data = data
        self.n_valid = n_valid
        self.source_id = source_id
        self.epoch = epoch
        self._dev_key = None

    @property
    def raw(self) -> Any:
        return self._data

    def drop(self) -> int:
        """Release the device buffer; returns bytes freed."""
        if self._data is None:
            return 0
        from modin_tpu.core.memory import device_ledger

        freed = device_ledger.deregister(self)
        self._data = None
        self.n_valid = None
        return freed

    def spill(self) -> int:
        """Ledger spill protocol: derived data is dropped, not copied out."""
        freed = self.drop()
        if freed:
            emit_metric("sortcache.spill", 1)
        return freed


def _live_rep(col: Any) -> Optional[SortedRep]:
    rep = getattr(col, "_sorted_rep", None)
    if rep is None or rep._data is None:
        return None
    from modin_tpu.core.execution import recovery

    if rep.epoch != recovery.current_epoch() or rep.source_id != id(col._data):
        invalidate(col)
        return None
    return rep


def peek(col: Any) -> bool:
    """Whether ``col`` has a live, current rep (no metrics, no LRU touch —
    the router's planning probe)."""
    return _live_rep(col) is not None


def get(col: Any) -> Optional[Tuple[Any, Any]]:
    """``(sorted values, n_valid)`` if ``col`` has a live, current rep."""
    rep = _live_rep(col)
    if rep is None:
        return None
    from modin_tpu.core.memory import device_ledger

    device_ledger.touch(rep)
    emit_metric("sortcache.hit", 1)
    return rep._data, rep.n_valid


def attach(col: Any, xs: Any, n_valid: Any) -> None:
    """Cache ``(xs, n_valid)`` as ``col``'s sorted representation."""
    from modin_tpu.core.execution import recovery
    from modin_tpu.core.memory import device_ledger

    invalidate(col)
    rep = SortedRep(xs, n_valid, id(col._data), recovery.current_epoch())
    device_ledger.register(rep)
    col._sorted_rep = rep
    emit_metric("sortcache.build", 1)


def invalidate(col: Any) -> None:
    """Drop ``col``'s cached rep (buffer mutation, spill, re-seat)."""
    rep = getattr(col, "_sorted_rep", None)
    if rep is None:
        return
    col._sorted_rep = None
    if rep.drop():
        emit_metric("sortcache.invalidate", 1)
