"""Per-column sorted-representation cache (graftsort).

The sort-shaped reductions (median, quantile, nunique, mode) all begin with
the same prefix: sort the column with NaN/pad rows collapsed to the tail and
count the valid prefix (``ops/sort.py sorted_valid_columns``).  Before this
cache, that prefix was recomputed inside every op's own jit — four ops on
one column paid four O(n log n) sorts.  Now the first op attaches the
``(sorted values, n_valid)`` pair to its ``DeviceColumn`` as a
:class:`SortedRep` and every later op consumes it with an O(n) pass.

Correctness contract:

- **Identity**: a rep is valid only while the column still holds the exact
  buffer it was computed from (``source_id == id(col._data)``) in the
  current device epoch.  Every mutation of the column's buffer — spill,
  spill-restore, lineage re-seat, lazy materialization — additionally drops
  the rep eagerly (``DeviceColumn._invalidate_sorted``), so the identity
  check is belt-and-braces, not the only line of defense.
- **Memory**: the rep's device buffer is registered in the
  ``_DeviceLedger`` (core/memory.py) like any column buffer, so admission
  control and the OOM evict-then-retry leg can reclaim it.  "Spilling" a
  rep just drops it — derived data needs no host copy; the next sort-shaped
  op rebuilds it.
- **Recovery**: after a device loss the graftguard reseat pass walks the
  same ledger; a rep is recognized (``is_derived_cache``) and dropped
  instead of replayed — it is disposable, never unrecoverable.
- **Concurrency**: attach / get / invalidate are serialized by one module
  lock (graftgate: concurrent queries legitimately share frames, so two
  threads may race a sort-shaped op against a mutation of the same
  column).  Without it, a reader could pass the identity check and then
  observe ``rep._data = None`` torn in by a concurrent invalidate.  The
  lock is module-wide, not per-column: the guarded sections are a few
  attribute reads, and a per-column lock would have to live on
  ``DeviceColumn`` (one more slot on every column for a cache only
  sort-shaped ops touch).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Tuple

from modin_tpu.logging.metrics import emit_metric

# reentrant: invalidate() drops the rep while already holding the lock,
# and the ledger spill / recovery paths call SortedRep.drop() directly
_CACHE_LOCK = threading.RLock()


class SortedRep:
    """One column's cached sorted representation, device-ledger-tracked."""

    __slots__ = (
        "_data", "n_valid", "source_id", "epoch", "mesh_key", "_dev_key",
        "__weakref__",
    )

    #: recovery marker: reseat passes drop derived caches instead of
    #: replaying lineage for them (core/execution/recovery.py)
    is_derived_cache = True
    is_lazy = False

    def __init__(
        self,
        data: Any,
        n_valid: Any,
        source_id: int,
        epoch: int,
        mesh_key: str = "",
    ):
        self._data = data
        self.n_valid = n_valid
        self.source_id = source_id
        self.epoch = epoch
        # graftmesh: the rep is keyed on the shard layout it was built
        # under — a mesh reshape changes the padded physical layout and
        # which collectives later consumers compile against, so a rep from
        # another topology is stale even if the source buffer survived
        self.mesh_key = mesh_key
        self._dev_key = None

    @property
    def raw(self) -> Any:
        return self._data

    def drop(self) -> int:
        """Release the device buffer; returns bytes freed.

        Serialized under the module cache lock: ``_data`` only ever
        transitions under it, so a reader holding the lock can never see
        the pair torn by a concurrent ledger spill or recovery drop.
        """
        with _CACHE_LOCK:
            if self._data is None:
                return 0
            from modin_tpu.core.memory import device_ledger

            freed = device_ledger.deregister(self)
            self._data = None
            self.n_valid = None
            return freed

    def spill(self) -> int:
        """Ledger spill protocol: derived data is dropped, not copied out."""
        freed = self.drop()
        if freed:
            emit_metric("sortcache.spill", 1)
        return freed


def _invalidate_locked(col: Any) -> int:
    """Detach + drop ``col``'s rep; returns bytes freed (lock held)."""
    rep = getattr(col, "_sorted_rep", None)
    if rep is None:
        return 0
    col._sorted_rep = None
    return rep.drop()


def _live_rep_locked(col: Any) -> Optional[SortedRep]:
    """``col``'s rep if live and current, invalidating a stale one
    (lock held: the identity check and any use of the returned rep's
    buffer must be one atomic step against a concurrent invalidate)."""
    rep = getattr(col, "_sorted_rep", None)
    if rep is None or rep._data is None:
        return None
    from modin_tpu.core.execution import recovery
    from modin_tpu.parallel.mesh import mesh_shape_key

    if (
        rep.epoch != recovery.current_epoch()
        or rep.source_id != id(col._data)
        or rep.mesh_key != mesh_shape_key()
    ):
        if _invalidate_locked(col):
            emit_metric("sortcache.invalidate", 1)
        return None
    return rep


def peek(col: Any) -> bool:
    """Whether ``col`` has a live, current rep (no metrics, no LRU touch —
    the router's planning probe)."""
    with _CACHE_LOCK:
        return _live_rep_locked(col) is not None


def get(col: Any) -> Optional[Tuple[Any, Any]]:
    """``(sorted values, n_valid)`` if ``col`` has a live, current rep."""
    with _CACHE_LOCK:
        rep = _live_rep_locked(col)
        if rep is None:
            return None
        # copy the pair out under the lock: a concurrent invalidate after
        # release only drops the ledger entry, never the arrays we hold
        data, n_valid = rep._data, rep.n_valid
    from modin_tpu.core.memory import device_ledger

    device_ledger.touch(rep)
    emit_metric("sortcache.hit", 1)
    return data, n_valid


def attach(col: Any, xs: Any, n_valid: Any) -> None:
    """Cache ``(xs, n_valid)`` as ``col``'s sorted representation."""
    from modin_tpu.core.execution import recovery
    from modin_tpu.core.memory import device_ledger
    from modin_tpu.parallel.mesh import mesh_shape_key

    rep = SortedRep(
        xs, n_valid, id(col._data), recovery.current_epoch(), mesh_shape_key()
    )
    with _CACHE_LOCK:
        invalidated = _invalidate_locked(col)
        device_ledger.register(rep)
        col._sorted_rep = rep
    if invalidated:
        emit_metric("sortcache.invalidate", 1)
    emit_metric("sortcache.build", 1)


def invalidate(col: Any) -> None:
    """Drop ``col``'s cached rep (buffer mutation, spill, re-seat)."""
    with _CACHE_LOCK:
        freed = _invalidate_locked(col)
    if freed:
        emit_metric("sortcache.invalidate", 1)
