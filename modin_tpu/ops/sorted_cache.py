"""Per-column sorted-representation cache (graftsort) — graftview shim.

The sort-shaped reductions (median, quantile, nunique, mode) all begin with
the same prefix: sort the column with NaN/pad rows collapsed to the tail and
count the valid prefix (``ops/sort.py sorted_valid_columns``).  The first op
attaches the ``(sorted values, n_valid)`` pair to its ``DeviceColumn`` as a
:class:`SortedRep` and every later op consumes it with an O(n) pass.

Since graftview (modin_tpu/views/) this module is a **compatibility shim**:
``SortedRep`` is a :class:`~modin_tpu.views.registry.DerivedArtifact`
subclass and the lock, validity stamps (buffer identity / device epoch /
mesh-shape key), ledger registration, and recovery protocol all live in the
shared registry — the invalidation bookkeeping that used to be duplicated
here is gone.  What stays local:

- the per-column attachment slot (``DeviceColumn._sorted_rep``) — the rep
  is consulted on sort-shaped hot paths and a slot read beats a keyed
  lookup;
- the ``sortcache.*`` metric names (stable observability surface; the
  generic artifacts emit ``view.*``).

The correctness contract is unchanged: a rep is valid only for the exact
buffer it was computed from in the current device epoch under the current
mesh shape, every buffer mutation drops it eagerly, ledger "spill" = drop
(derived data rebuilds on demand), and graftguard reseat passes drop it
instead of replaying lineage — never counting it unrecoverable.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from modin_tpu.logging.metrics import emit_metric
from modin_tpu.views import registry as _registry

#: the ONE derived-cache lock, shared with every graftview artifact
#: (graftgate: concurrent queries legitimately share frames, so readers
#: and invalidators of the same column serialize here)
_CACHE_LOCK = _registry.LOCK


class SortedRep(_registry.DerivedArtifact):
    """One column's cached sorted representation, device-ledger-tracked.

    The device payload is the sorted array; ``n_valid`` rides in the
    artifact state.  ``_data`` keeps its historical name (tests and the
    recovery path read it)."""

    __slots__ = ("col_ref",)

    def __init__(self, data: Any, n_valid: Any, source_id: int, col: Any = None):
        super().__init__(
            kind="sorted_rep",
            params=(),
            token=0,
            length=0,
            source_id=source_id,
            state={"n_valid": n_valid},
            can_fold=False,
            payload=data,
        )
        import weakref

        self.col_ref = weakref.ref(col) if col is not None else None

    @property
    def _data(self) -> Any:
        return self._payload

    @property
    def n_valid(self) -> Any:
        state = self.state
        return state["n_valid"] if state is not None else None

    def spill(self) -> int:
        """Ledger spill protocol: derived data is dropped, not copied out.

        A pressure drop also clears the owning column's graftview
        artifacts: the ledger chose this column as cold, and every derived
        cache answering for it shares the drop-under-pressure contract —
        the next query rebuilds from the (still resident) source buffer.
        """
        freed = self.drop()
        if freed:
            emit_metric("sortcache.spill", 1)
            # the rep IS a graftview device-payload artifact: its pressure
            # drop counts in the registry's family too
            emit_metric("view.spill", 1)
            col = self.col_ref() if self.col_ref is not None else None
            if col is not None and col._view_token is not None:
                _registry.invalidate_column(col, reason="pressure")
        return freed


def _invalidate_locked(col: Any) -> int:
    """Detach + drop ``col``'s rep; returns bytes freed (lock held)."""
    rep = getattr(col, "_sorted_rep", None)
    if rep is None:
        return 0
    col._sorted_rep = None
    return rep.drop()


def _live_rep_locked(col: Any) -> Optional[SortedRep]:
    """``col``'s rep if live and current, invalidating a stale one
    (lock held: the identity check and any use of the returned rep's
    buffer must be one atomic step against a concurrent invalidate)."""
    rep = getattr(col, "_sorted_rep", None)
    if rep is None or rep._payload is None:
        return None
    if (
        rep.epoch != _registry._current_epoch()
        or rep.source_id != id(col._data)
        or rep.mesh_key != _registry._mesh_key()
    ):
        if _invalidate_locked(col):
            emit_metric("sortcache.invalidate", 1)
        return None
    return rep


def peek(col: Any) -> bool:
    """Whether ``col`` has a live, current rep (no metrics, no LRU touch —
    the router's planning probe)."""
    with _CACHE_LOCK:
        return _live_rep_locked(col) is not None


def get(col: Any) -> Optional[Tuple[Any, Any]]:
    """``(sorted values, n_valid)`` if ``col`` has a live, current rep."""
    with _CACHE_LOCK:
        rep = _live_rep_locked(col)
        if rep is None:
            return None
        # copy the pair out under the lock: a concurrent invalidate after
        # release only drops the ledger entry, never the arrays we hold
        data, n_valid = rep._payload, rep.n_valid
    from modin_tpu.core.memory import device_ledger

    device_ledger.touch(rep)
    emit_metric("sortcache.hit", 1)
    return data, n_valid


def attach(col: Any, xs: Any, n_valid: Any) -> None:
    """Cache ``(xs, n_valid)`` as ``col``'s sorted representation."""
    from modin_tpu.core.memory import device_ledger

    rep = SortedRep(xs, n_valid, id(col._data), col)
    with _CACHE_LOCK:
        invalidated = _invalidate_locked(col)
        device_ledger.register(rep)
        col._sorted_rep = rep
    if invalidated:
        emit_metric("sortcache.invalidate", 1)
    emit_metric("sortcache.build", 1)


def invalidate(col: Any) -> None:
    """Drop ``col``'s cached rep (buffer mutation, spill, re-seat)."""
    with _CACHE_LOCK:
        freed = _invalidate_locked(col)
    if freed:
        emit_metric("sortcache.invalidate", 1)
