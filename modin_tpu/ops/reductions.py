"""Column reductions with pandas NaN semantics, pad-aware.

TPU-native replacement for the reference's Reduce/TreeReduce operators
(modin/core/dataframe/algebra/tree_reduce.py:29): on a sharded jax.Array a
``jnp.sum`` lowers to per-shard partial reduction + an XLA ``psum`` over ICI —
the map/axis-reduce task pair of the reference collapses into one compiled
collective program.

All per-column reductions of a frame run in ONE jit so a ``df.sum()`` costs
one dispatch + one small fetch regardless of column count.  Columns are
padded to the shard count; every kernel masks rows >= n (the logical length,
passed statically).
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


def _masked(c, n, neutral):
    import jax.numpy as jnp

    if c.shape[0] == n:
        return c
    valid = jnp.arange(c.shape[0]) < n
    return jnp.where(valid, c, neutral)


def _valid_mask(c, n):
    import jax.numpy as jnp

    return jnp.arange(c.shape[0]) < n


def _reduce_one(op: str, c, n: int, skipna: bool, ddof: int):
    """Reduce one padded column with logical length n."""
    import jax.numpy as jnp

    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    valid = _valid_mask(c, n)
    nan_mask = jnp.isnan(c) & valid if is_f else jnp.zeros(c.shape, bool)
    use = valid & ~nan_mask if (skipna and is_f) else valid
    n_use = jnp.sum(use)

    if op == "count":
        return jnp.sum(valid & ~nan_mask).astype(jnp.int64)
    if op == "sum":
        return jnp.sum(jnp.where(use, c, 0))
    if op == "prod":
        return jnp.prod(jnp.where(use, c, 1))
    if op == "min":
        if is_f:
            r = jnp.min(jnp.where(use, c, jnp.inf))
            any_nan = jnp.any(nan_mask & valid) & (not skipna)
            return jnp.where(jnp.isinf(r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.min(jnp.where(use, c, _int_max(c.dtype)))
    if op == "max":
        if is_f:
            r = jnp.max(jnp.where(use, c, -jnp.inf))
            any_nan = jnp.any(nan_mask & valid) & (not skipna)
            return jnp.where(jnp.isinf(-r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.max(jnp.where(use, c, _int_min(c.dtype)))
    if op in ("mean", "var", "std", "sem", "skew", "kurt"):
        x = jnp.where(use, c, 0).astype(jnp.float64)
        s = jnp.sum(x)
        mean = s / n_use
        if op == "mean":
            if is_f and not skipna:
                return jnp.where(jnp.any(nan_mask), jnp.nan, mean)
            return jnp.where(n_use == 0, jnp.nan, mean)
        d = jnp.where(use, x - mean, 0.0)
        m2s = jnp.sum(d**2)
        if op in ("var", "std", "sem"):
            var = m2s / jnp.maximum(n_use - ddof, 1)
            var = jnp.where(n_use - ddof > 0, var, jnp.nan)
            if is_f and not skipna:
                var = jnp.where(jnp.any(nan_mask), jnp.nan, var)
            if op == "var":
                return var
            if op == "std":
                return jnp.sqrt(var)
            return jnp.sqrt(var / n_use)
        nf = n_use.astype(jnp.float64)
        m2 = m2s / nf
        if op == "skew":
            m3 = jnp.sum(d**3) / nf
            g1 = m3 / jnp.where(m2 > 0, m2, 1.0) ** 1.5
            res = jnp.sqrt(nf * (nf - 1.0)) / (nf - 2.0) * g1
            res = jnp.where((nf < 3) | (m2 == 0), jnp.nan, res)
        else:  # kurt — sample excess kurtosis G2, pandas' nankurt
            m4 = jnp.sum(d**4) / nf
            g2 = m4 / jnp.where(m2 > 0, m2, 1.0) ** 2 - 3.0
            res = ((nf + 1.0) * g2 + 6.0) * (nf - 1.0) / ((nf - 2.0) * (nf - 3.0))
            res = jnp.where((nf < 4) | (m2 == 0), jnp.nan, res)
        if is_f and not skipna:
            res = jnp.where(jnp.any(nan_mask), jnp.nan, res)
        return res
    if op == "median":
        x = jnp.where(use, c, jnp.nan).astype(jnp.float64)
        return jnp.nanmedian(x)
    if op == "any":
        truthy = jnp.where(nan_mask, not skipna, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.any(truthy & valid)
    if op == "all":
        truthy = jnp.where(nan_mask, True, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.all(truthy | ~valid)
    raise ValueError(op)


def _int_max(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return True
    return np.iinfo(np.dtype(str(dtype))).max


def _int_min(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return False
    return np.iinfo(np.dtype(str(dtype))).min


def reduce_columns(
    op_name: str,
    cols: List[Any],
    n: int,
    skipna: bool = True,
    ddof: int = 1,
    cast_bool: bool = False,
) -> list:
    """Reduce each padded column (logical length n) to a scalar; one fetch.

    ``cols`` may mix concrete arrays and deferred LazyExprs — the reduction
    traces as a *tail* of the fused program (ops/lazy.py), so a chain like
    ``(a * b + c).sum()`` compiles to one kernel.  ``cast_bool`` applies the
    pandas bool->int promotion for arithmetic aggregations inside the fusion.
    """
    import jax

    from modin_tpu.ops.lazy import run_fused

    n, skipna, ddof = int(n), bool(skipna), int(ddof)

    def tail(arrs):
        import jax.numpy as jnp

        if cast_bool:
            arrs = [a.astype(jnp.int64) if a.dtype == jnp.bool_ else a for a in arrs]
        return tuple(_reduce_one(op_name, c, n, skipna, ddof) for c in arrs)

    results = run_fused(
        cols,
        tail_key=("reduce", op_name, n, skipna, ddof, bool(cast_bool)),
        tail_builder=tail,
    )
    return [np.asarray(r) for r in jax.device_get(results)]


@functools.lru_cache(maxsize=None)
def _make_axis1_fn(op_name: str, n_cols: int, skipna: bool, ddof: int):
    import jax.numpy as jnp

    def fn(cols: Tuple):
        # pad rows produce garbage values that are sliced off logically
        common = jnp.result_type(*[c.dtype for c in cols])
        x = jnp.stack([c.astype(common) for c in cols], axis=0)
        is_f = jnp.issubdtype(x.dtype, jnp.floating)
        if op_name == "count":
            if is_f:
                return jnp.sum(~jnp.isnan(x), axis=0).astype(jnp.int64)
            return jnp.full((x.shape[1],), n_cols, jnp.int64)
        if not is_f or not skipna:
            reducer = {
                "sum": jnp.sum, "mean": jnp.mean, "min": jnp.min, "max": jnp.max,
                "median": jnp.median,
            }.get(op_name)
            if reducer is not None:
                return reducer(x, axis=0)
            if op_name == "var":
                return jnp.var(x, axis=0, ddof=ddof)
            if op_name == "std":
                return jnp.std(x, axis=0, ddof=ddof)
        reducer = {
            "sum": jnp.nansum, "mean": jnp.nanmean, "min": jnp.nanmin,
            "max": jnp.nanmax, "median": jnp.nanmedian,
        }.get(op_name)
        if reducer is not None:
            return reducer(x, axis=0)
        if op_name == "var":
            return jnp.nanvar(x, axis=0, ddof=ddof)
        if op_name == "std":
            return jnp.nanstd(x, axis=0, ddof=ddof)
        raise ValueError(op_name)

    return fn


def reduce_axis1(
    op_name: str,
    cols: List[Any],
    skipna: bool = True,
    ddof: int = 1,
    cast_bool: bool = False,
) -> Any:
    """Row-wise reduction across columns; returns a padded device 1-D array.

    Accepts deferred LazyExprs like :func:`reduce_columns` (fused tail).
    """
    from modin_tpu.ops.lazy import run_fused

    skipna, ddof = bool(skipna), int(ddof)
    inner = _make_axis1_fn(op_name, len(cols), skipna, ddof)

    def tail(arrs):
        import jax.numpy as jnp

        if cast_bool:
            arrs = [a.astype(jnp.int64) if a.dtype == jnp.bool_ else a for a in arrs]
        return inner(tuple(arrs))

    return run_fused(
        cols,
        tail_key=("reduce_axis1", op_name, skipna, ddof, bool(cast_bool)),
        tail_builder=tail,
    )


@functools.lru_cache(maxsize=None)
def _jit_idx_minmax(op_name: str, n_cols: int, n: int):
    import jax
    import jax.numpy as jnp

    def fn(cs: Tuple) -> Tuple:
        out = []
        counts = []
        for c in cs:
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            valid = _valid_mask(c, n)
            if is_f:
                n_valid = jnp.sum(valid & ~jnp.isnan(c))
            else:
                n_valid = jnp.sum(valid)
            counts.append(n_valid)
            if op_name == "idxmin":
                neutral = jnp.inf if is_f else _int_max(c.dtype)
                x = _masked(c, n, neutral)
                x = jnp.where(jnp.isnan(x), jnp.inf, x) if is_f else x
                out.append(jnp.argmin(x))
            else:
                neutral = -jnp.inf if is_f else _int_min(c.dtype)
                x = _masked(c, n, neutral)
                x = jnp.where(jnp.isnan(x), -jnp.inf, x) if is_f else x
                out.append(jnp.argmax(x))
        return tuple(out), tuple(counts)

    return jax.jit(fn)


def idx_minmax(op_name: str, cols: List[Any], n: int, skipna: bool = True):
    """(positions, valid_counts) per padded column, NaN-skipping; one fetch."""
    import jax

    positions, counts = _jit_idx_minmax(op_name, len(cols), int(n))(tuple(cols))
    fetched = jax.device_get((positions, counts))
    return [int(r) for r in fetched[0]], [int(c) for c in fetched[1]]
