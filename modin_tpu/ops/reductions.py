"""Column reductions with pandas NaN semantics, pad-aware.

TPU-native replacement for the reference's Reduce/TreeReduce operators
(modin/core/dataframe/algebra/tree_reduce.py:29): on a sharded jax.Array a
``jnp.sum`` lowers to per-shard partial reduction + an XLA ``psum`` over ICI —
the map/axis-reduce task pair of the reference collapses into one compiled
collective program.

All per-column reductions of a frame run in ONE jit so a ``df.sum()`` costs
one dispatch + one small fetch regardless of column count.  Columns are
padded to the shard count; every kernel masks rows >= n (the logical length,
passed statically).
"""

from __future__ import annotations

import functools
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np


from modin_tpu.parallel.engine import materialize as _engine_materialize


def _masked(c, n, neutral):
    import jax.numpy as jnp

    if c.shape[0] == n:
        return c
    valid = jnp.arange(c.shape[0]) < n
    return jnp.where(valid, c, neutral)


def _valid_mask(c, n):
    import jax.numpy as jnp

    return jnp.arange(c.shape[0]) < n


def _reduce_one(
    op: str,
    c,
    n: int,
    skipna: bool,
    ddof: int,
    adaptive: bool = False,
    adaptive_sharded: bool = False,
):
    """Reduce one padded column with logical length n.

    When the column is unpadded (shape == n, the common case: lengths that
    divide the shard count evenly), the validity iota-mask is skipped — on
    clean data that leaves a single fused pass over the column.

    ``adaptive`` additionally enables the NaN-adaptive lax.cond fast path on
    single-shard meshes (a GLOBAL lax.cond over sharded operands miscompiles
    under SPMD partitioning — observed on the virtual CPU mesh).
    ``adaptive_sharded`` is the multi-shard formulation: the cond runs PER
    SHARD inside shard_map, where its operands are local, and scalar
    partials combine outside (see _reduce_adaptive_sharded).
    """
    import jax.numpy as jnp

    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    unpadded = c.shape[0] == n
    if adaptive and unpadded and is_f and skipna and n > 0:
        fast = _reduce_clean_adaptive(op, c, n, ddof)
        if fast is not None:
            return fast
    if adaptive_sharded and unpadded and is_f and skipna and n > 0:
        fast = _reduce_adaptive_sharded(op, c, n)
        if fast is not None:
            return fast
    # unpadded columns (lengths dividing the shard count) elide the iota
    # validity mask — clean int/float reductions become a single fused pass
    cnt_dtype = jnp.int32 if n < 2**31 else jnp.int64
    if unpadded:
        valid = None
        nan_mask = jnp.isnan(c) if is_f else None
        use = ~nan_mask if (skipna and is_f) else None
        n_use = (
            jnp.sum(use, dtype=cnt_dtype).astype(jnp.int64)
            if use is not None
            else jnp.asarray(n, jnp.int64)
        )
    else:
        valid = _valid_mask(c, n)
        nan_mask = jnp.isnan(c) & valid if is_f else None
        use = valid & ~nan_mask if (skipna and is_f) else valid
        n_use = jnp.sum(use, dtype=cnt_dtype).astype(jnp.int64)

    def sel(x, neutral):
        return x if use is None else jnp.where(use, x, neutral)

    def sel_valid(x, neutral):
        return x if valid is None else jnp.where(valid, x, neutral)

    if op == "count":
        if nan_mask is None:
            return jnp.asarray(n, jnp.int64)
        return jnp.sum(sel_valid(~nan_mask, False), dtype=cnt_dtype).astype(jnp.int64)
    if op == "sum":
        return jnp.sum(sel(c, 0))
    if op == "prod":
        return jnp.prod(sel(c, 1))
    if op == "min":
        if is_f:
            r = jnp.min(sel(c, jnp.inf))
            any_nan = jnp.any(nan_mask) & (not skipna)
            return jnp.where(jnp.isinf(r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.min(sel(c, _int_max(c.dtype)))
    if op == "max":
        if is_f:
            r = jnp.max(sel(c, -jnp.inf))
            any_nan = jnp.any(nan_mask) & (not skipna)
            return jnp.where(jnp.isinf(-r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.max(sel(c, _int_min(c.dtype)))
    if op in ("mean", "var", "std", "sem", "skew", "kurt"):
        x = sel(c, 0).astype(jnp.float64)
        s = jnp.sum(x)
        mean = s / n_use
        if op == "mean":
            if is_f and not skipna:
                return jnp.where(jnp.any(nan_mask), jnp.nan, mean)
            return jnp.where(n_use == 0, jnp.nan, mean)
        d = sel(x - mean, 0.0)
        m2s = jnp.sum(d**2)
        if op in ("var", "std", "sem"):
            var = m2s / jnp.maximum(n_use - ddof, 1)
            var = jnp.where(n_use - ddof > 0, var, jnp.nan)
            if is_f and not skipna:
                var = jnp.where(jnp.any(nan_mask), jnp.nan, var)
            if op == "var":
                return var
            if op == "std":
                return jnp.sqrt(var)
            return jnp.sqrt(var / n_use)
        nf = n_use.astype(jnp.float64)
        m2 = m2s / nf
        if op == "skew":
            m3 = jnp.sum(d**3) / nf
            g1 = m3 / jnp.where(m2 > 0, m2, 1.0) ** 1.5
            res = jnp.sqrt(nf * (nf - 1.0)) / (nf - 2.0) * g1
            res = jnp.where((nf < 3) | (m2 == 0), jnp.nan, res)
        else:  # kurt — sample excess kurtosis G2, pandas' nankurt
            m4 = jnp.sum(d**4) / nf
            g2 = m4 / jnp.where(m2 > 0, m2, 1.0) ** 2 - 3.0
            res = ((nf + 1.0) * g2 + 6.0) * (nf - 1.0) / ((nf - 2.0) * (nf - 3.0))
            res = jnp.where((nf < 4) | (m2 == 0), jnp.nan, res)
        if is_f and not skipna:
            res = jnp.where(jnp.any(nan_mask), jnp.nan, res)
        return res
    if op == "median":
        x = sel(c, jnp.nan).astype(jnp.float64)
        return jnp.nanmedian(x)
    if op == "any":
        truthy = jnp.where(nan_mask, not skipna, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.any(sel_valid(truthy, False))
    if op == "all":
        truthy = jnp.where(nan_mask, True, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.all(sel_valid(truthy, True))
    raise ValueError(op)


def _reduce_clean_adaptive(op: str, c, n: int, ddof: int):
    """NaN-adaptive float reduction: run the unmasked single-pass kernel and
    fall into the masked path (via lax.cond) only when the result shows a NaN
    actually occurred.  On clean data — the common case — the select/masking
    passes are skipped entirely (measured ~4x on XLA CPU, where jnp.sum
    beats pandas but where+sum does not).  Returns None for ops without an
    adaptive form.
    """
    import jax.lax as lax
    import jax.numpy as jnp

    def masked(neutral):
        return jnp.where(jnp.isnan(c), neutral, c)

    # int32 accumulation of the bool mask is ~3x faster on XLA CPU than the
    # default int64 widening (n < 2^31 always holds for per-shard lengths)
    cnt_dtype = jnp.int32 if n < 2**31 else jnp.int64

    def n_use():
        return (n - jnp.sum(jnp.isnan(c), dtype=cnt_dtype)).astype(jnp.int64)

    if op == "sum":
        s = jnp.sum(c)
        return lax.cond(jnp.isnan(s), lambda: jnp.sum(masked(0.0)), lambda: s)
    if op == "prod":
        p = jnp.prod(c)
        return lax.cond(jnp.isnan(p), lambda: jnp.prod(masked(1.0)), lambda: p)
    if op == "count":
        # clean data: one plain sum proves there are no NaNs and count is n;
        # inf+-inf false-positives only cost the slow path, never correctness
        s = jnp.sum(c)
        return lax.cond(
            jnp.isnan(s), n_use, lambda: jnp.asarray(n, jnp.int64)
        )
    if op in ("min", "max"):
        reducer = jnp.min if op == "min" else jnp.max
        r = reducer(c)

        def dirty():
            neutral = jnp.inf if op == "min" else -jnp.inf
            m = reducer(masked(neutral))
            # all-NaN group: masked reduce returns the neutral infinity
            return jnp.where(n_use() == 0, jnp.nan, m)

        return lax.cond(jnp.isnan(r), dirty, lambda: r)
    # mean/var family accumulates in float64, matching the masked path
    x64 = c.astype(jnp.float64)
    if op == "mean":
        s = jnp.sum(x64)

        def dirty():
            k = n_use()
            return jnp.where(
                k == 0, jnp.nan, jnp.sum(jnp.where(jnp.isnan(x64), 0.0, x64)) / k
            )

        return lax.cond(jnp.isnan(s), dirty, lambda: s / n)
    if op in ("var", "std", "sem"):
        s = jnp.sum(x64)

        def clean():
            mean = s / n
            d = x64 - mean
            var = jnp.sum(d * d) / max(n - ddof, 1)
            return var if n - ddof > 0 else jnp.full((), jnp.nan)

        def dirty():
            nanm = jnp.isnan(x64)
            k = n_use()
            x = jnp.where(nanm, 0.0, x64)
            mean = jnp.sum(x) / k
            d = jnp.where(nanm, 0.0, x - mean)
            var = jnp.sum(d * d) / jnp.maximum(k - ddof, 1)
            return jnp.where(k - ddof > 0, var, jnp.nan)

        var = lax.cond(jnp.isnan(s), dirty, clean)
        if op == "var":
            return var
        if op == "std":
            return jnp.sqrt(var)
        k = lax.cond(
            jnp.isnan(s), lambda: n_use().astype(jnp.int64),
            lambda: jnp.asarray(n, jnp.int64),
        )
        return jnp.sqrt(var / k)
    return None


_SHARDED_ADAPTIVE_OPS = ("sum", "prod", "count", "min", "max", "mean")


def _reduce_adaptive_sharded(op: str, c, n: int):
    """NaN-adaptive reduction on a row-sharded column.

    The single-shard form's global ``lax.cond`` cannot be SPMD-partitioned
    over sharded operands, so here the cond runs PER SHARD inside
    ``shard_map`` — each branch sees only the shard's local block — and the
    shards return (partial, nan_count) scalars that combine outside the
    map.  Clean shards skip the isnan/where passes entirely; a NaN only
    slows the shard that contains it.  The var/skew family keeps the masked
    path when sharded: its two global passes (mean, then centered moments)
    leave little for the adaptive branch to save.
    """
    import jax.lax as lax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from modin_tpu.parallel.jax_compat import shard_map

    from modin_tpu.parallel.mesh import get_mesh

    if op not in _SHARDED_ADAPTIVE_OPS:
        return None
    mesh = get_mesh()
    cnt_dtype = jnp.int32 if n < 2**31 else jnp.int64

    def local(x):
        def nan_count():
            return jnp.sum(jnp.isnan(x), dtype=cnt_dtype).astype(jnp.int64)

        def no_nans():
            return jnp.zeros((), jnp.int64)

        if op in ("sum", "prod"):
            reducer = jnp.sum if op == "sum" else jnp.prod
            neutral = jnp.asarray(0 if op == "sum" else 1, x.dtype)
            s = reducer(x)
            ms = lax.cond(
                jnp.isnan(s),
                lambda: reducer(jnp.where(jnp.isnan(x), neutral, x)),
                lambda: s,
            )
            return ms[None], jnp.zeros((1,), jnp.int64)
        if op == "count":
            # one plain sum proves the shard is clean; inf-inf false
            # positives only cost the slow branch, never correctness
            s = jnp.sum(x)
            nc = lax.cond(jnp.isnan(s), nan_count, no_nans)
            return jnp.zeros((1,), x.dtype), nc[None]
        if op in ("min", "max"):
            reducer = jnp.min if op == "min" else jnp.max
            neutral = jnp.asarray(jnp.inf if op == "min" else -jnp.inf, x.dtype)
            r = reducer(x)
            m, nc = lax.cond(
                jnp.isnan(r),
                lambda: (reducer(jnp.where(jnp.isnan(x), neutral, x)), nan_count()),
                lambda: (r, jnp.zeros((), jnp.int64)),
            )
            return m[None], nc[None]
        # mean: float64 accumulation, matching the masked path
        x64 = x.astype(jnp.float64)
        s = jnp.sum(x64)
        ms, nc = lax.cond(
            jnp.isnan(s),
            lambda: (jnp.sum(jnp.where(jnp.isnan(x64), 0.0, x64)), nan_count()),
            lambda: (s, jnp.zeros((), jnp.int64)),
        )
        return ms[None], nc[None]

    partials, ncs = shard_map(
        local,
        mesh=mesh,
        in_specs=P("rows"),
        out_specs=(P("rows"), P("rows")),
        check_vma=False,
    )(c)
    n_use = n - jnp.sum(ncs)
    if op == "count":
        return n_use.astype(jnp.int64)
    if op == "sum":
        return jnp.sum(partials)
    if op == "prod":
        return jnp.prod(partials)
    if op == "mean":
        return jnp.where(n_use == 0, jnp.nan, jnp.sum(partials) / n_use)
    reducer = jnp.min if op == "min" else jnp.max
    return jnp.where(n_use == 0, jnp.nan, reducer(partials))


def _int_max(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return True
    return np.iinfo(np.dtype(str(dtype))).max


def _int_min(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return False
    return np.iinfo(np.dtype(str(dtype))).min


def reduce_columns(
    op_name: str,
    cols: List[Any],
    n: int,
    skipna: bool = True,
    ddof: int = 1,
    cast_bool: bool = False,
    donate_cols: Optional[List[Any]] = None,
) -> list:
    """Reduce each padded column (logical length n) to a scalar; one fetch.

    ``cols`` may mix concrete arrays and deferred LazyExprs — the reduction
    traces as a *tail* of the fused program (ops/lazy.py), so a chain like
    ``(a * b + c).sum()`` compiles to one kernel.  ``cast_bool`` applies the
    pandas bool->int promotion for arithmetic aggregations inside the fusion.
    """
    import jax

    from modin_tpu.parallel.mesh import num_row_shards

    n, skipna, ddof = int(n), bool(skipna), int(ddof)
    n_shards = num_row_shards()
    adaptive = n_shards == 1
    # shard-local adaptive form needs evenly-divided (unpadded) rows
    adaptive_sharded = n_shards > 1 and n > 0 and n % n_shards == 0

    def tail(arrs):
        import jax.numpy as jnp

        if cast_bool:
            arrs = [a.astype(jnp.int64) if a.dtype == jnp.bool_ else a for a in arrs]
        return tuple(
            _reduce_one(op_name, c, n, skipna, ddof, adaptive, adaptive_sharded)
            for c in arrs
        )

    results = _mark_and_run(
        cols,
        # adaptive/adaptive_sharded are derived from (n, n_shards), so the
        # shard count alone completes the cache key
        ("reduce", op_name, n, skipna, ddof, bool(cast_bool), n_shards),
        tail,
        donate_cols,
    )
    return [np.asarray(r) for r in _engine_materialize(results)]


def _reduce_one_masked(op: str, c, valid, skipna: bool, ddof: int):
    """Reduce one padded column restricted to the ``valid`` row mask.

    The graftfuse whole-plan form of :func:`_reduce_one`: ``valid`` is the
    filter's keep mask already AND-ed with the logical-length iota mask (n
    rides as a *traced* scalar in the fused program, so one executable
    serves every logical length at a physical size).  Semantics mirror
    ``_reduce_one``'s masked branch exactly — the compacted rows a staged
    filter would have gathered are the same values this mask selects, in
    the same order — with the NaN-adaptive fast paths skipped (the mask
    forces the select form anyway).
    """
    import jax.numpy as jnp

    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    cnt_dtype = jnp.int32 if c.shape[0] < 2**31 else jnp.int64
    nan_mask = jnp.isnan(c) & valid if is_f else None
    use = valid & ~nan_mask if (skipna and is_f) else valid
    n_use = jnp.sum(use, dtype=cnt_dtype).astype(jnp.int64)

    def sel(x, neutral):
        return jnp.where(use, x, neutral)

    def sel_valid(x, neutral):
        return jnp.where(valid, x, neutral)

    if op == "count":
        if nan_mask is None:
            return jnp.sum(valid, dtype=cnt_dtype).astype(jnp.int64)
        return jnp.sum(sel_valid(~nan_mask, False), dtype=cnt_dtype).astype(jnp.int64)
    if op == "sum":
        return jnp.sum(sel(c, 0))
    if op == "prod":
        return jnp.prod(sel(c, 1))
    if op == "min":
        if is_f:
            r = jnp.min(sel(c, jnp.inf))
            any_nan = jnp.any(nan_mask) & (not skipna)
            return jnp.where(jnp.isinf(r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.min(sel(c, _int_max(c.dtype)))
    if op == "max":
        if is_f:
            r = jnp.max(sel(c, -jnp.inf))
            any_nan = jnp.any(nan_mask) & (not skipna)
            return jnp.where(jnp.isinf(-r) & (n_use == 0), jnp.nan, jnp.where(any_nan, jnp.nan, r))
        return jnp.max(sel(c, _int_min(c.dtype)))
    if op in ("mean", "var", "std", "sem", "skew", "kurt"):
        x = sel(c, 0).astype(jnp.float64)
        s = jnp.sum(x)
        mean = s / n_use
        if op == "mean":
            if is_f and not skipna:
                return jnp.where(jnp.any(nan_mask), jnp.nan, mean)
            return jnp.where(n_use == 0, jnp.nan, mean)
        d = sel(x - mean, 0.0)
        m2s = jnp.sum(d**2)
        if op in ("var", "std", "sem"):
            var = m2s / jnp.maximum(n_use - ddof, 1)
            var = jnp.where(n_use - ddof > 0, var, jnp.nan)
            if is_f and not skipna:
                var = jnp.where(jnp.any(nan_mask), jnp.nan, var)
            if op == "var":
                return var
            if op == "std":
                return jnp.sqrt(var)
            return jnp.sqrt(var / n_use)
        nf = n_use.astype(jnp.float64)
        m2 = m2s / nf
        if op == "skew":
            m3 = jnp.sum(d**3) / nf
            g1 = m3 / jnp.where(m2 > 0, m2, 1.0) ** 1.5
            res = jnp.sqrt(nf * (nf - 1.0)) / (nf - 2.0) * g1
            res = jnp.where((nf < 3) | (m2 == 0), jnp.nan, res)
        else:  # kurt
            m4 = jnp.sum(d**4) / nf
            g2 = m4 / jnp.where(m2 > 0, m2, 1.0) ** 2 - 3.0
            res = ((nf + 1.0) * g2 + 6.0) * (nf - 1.0) / ((nf - 2.0) * (nf - 3.0))
            res = jnp.where((nf < 4) | (m2 == 0), jnp.nan, res)
        if is_f and not skipna:
            res = jnp.where(jnp.any(nan_mask), jnp.nan, res)
        return res
    if op == "median":
        # a masked median needs a data-dependent selection; the fused leg
        # declines it to the staged path before getting here
        raise ValueError("median has no masked fused form")
    if op == "any":
        truthy = jnp.where(nan_mask, not skipna, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.any(sel_valid(truthy, False))
    if op == "all":
        truthy = jnp.where(nan_mask, True, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
        return jnp.all(sel_valid(truthy, True))
    raise ValueError(op)


def _mark_and_run(roots, tail_key, tail, donate_cols):
    """Dispatch ``run_fused`` with buffer donation (graftfuse).

    ``donate_cols`` are DeviceColumns the caller proved donation-safe; only
    those whose buffer the forest actually consumes are donated.  Columns
    are marked consumed (spilled-with-exact-host-copy semantics) BEFORE the
    dispatch — the argument tree pins the buffers for the program itself,
    and any failure path that re-dispatches (the engine's rebind retry)
    then rebuilds over lineage-restored buffers instead of the consumed
    ones.  The finally re-mark covers exactly that rebind: its restore
    hands the column a fresh buffer that the retried donated program
    consumes too.
    """
    from modin_tpu.logging.metrics import emit_metric
    from modin_tpu.ops.lazy import leaf_buffer_ids, run_fused

    donate_map = {}
    if donate_cols:
        consumed = leaf_buffer_ids(roots)
        for col in donate_cols:
            buf = col._data
            if buf is not None and not col.is_lazy and id(buf) in consumed:
                donate_map[id(buf)] = col
    if not donate_map:
        return run_fused(roots, tail_key=tail_key, tail_builder=tail)
    # emit BEFORE marking: QueryStats samples HBM residency on this metric,
    # and the pre-donation sample is the honest peak (the consumed buffers
    # are still resident right up to the dispatch)
    emit_metric("fuse.donated", len(donate_map))
    freed = 0
    for col in donate_map.values():
        freed += col.mark_donated()
    emit_metric("fuse.donated_bytes", freed)
    try:
        return run_fused(
            roots, tail_key=tail_key, tail_builder=tail,
            donate=frozenset(donate_map),
        )
    finally:
        for col in donate_map.values():
            if col._data is not None:
                col.mark_donated()


def reduce_columns_masked(
    op_name: str,
    cols: List[Any],
    keep: Any,
    n: int,
    skipna: bool = True,
    ddof: int = 1,
    cast_bool: bool = False,
    donate_cols: Optional[List[Any]] = None,
) -> Tuple[list, int]:
    """graftfuse whole-plan tail: reduce each column over ``keep`` rows.

    ``keep`` is the (possibly deferred) boolean filter mask over the
    UNCOMPACTED padded rows — the filter/map chain fuses into this one
    program instead of paying a separate compaction dispatch.  ``n`` (the
    pre-filter logical length) rides as a runtime scalar so the compiled
    program is shared across logical lengths at one physical size.
    Returns ``(values, kept_rows)``; the caller declines to the staged
    path when ``kept_rows == 0`` (pandas empty-frame semantics live there).
    """
    n, skipna, ddof = int(n), bool(skipna), int(ddof)

    def tail(arrs):
        import jax.numpy as jnp

        *col_arrs, m, n_t = arrs
        if cast_bool:
            col_arrs = [
                a.astype(jnp.int64) if a.dtype == jnp.bool_ else a
                for a in col_arrs
            ]
        valid = m & (jnp.arange(m.shape[0]) < n_t)
        kept = jnp.sum(valid, dtype=jnp.int64)
        outs = tuple(
            _reduce_one_masked(op_name, c, valid, skipna, ddof)
            for c in col_arrs
        )
        return outs + (kept,)

    results = _mark_and_run(
        [*cols, keep, n],
        ("fuse_reduce", op_name, skipna, ddof, bool(cast_bool), len(cols)),
        tail,
        donate_cols,
    )
    fetched = [np.asarray(r) for r in _engine_materialize(results)]
    return fetched[:-1], int(fetched[-1])


@functools.lru_cache(maxsize=None)
def _make_axis1_fn(op_name: str, n_cols: int, skipna: bool, ddof: int):
    import jax.numpy as jnp

    def fn(cols: Tuple):
        # pad rows produce garbage values that are sliced off logically
        common = jnp.result_type(*[c.dtype for c in cols])
        x = jnp.stack([c.astype(common) for c in cols], axis=0)
        is_f = jnp.issubdtype(x.dtype, jnp.floating)
        if op_name == "count":
            if is_f:
                return jnp.sum(~jnp.isnan(x), axis=0).astype(jnp.int64)
            return jnp.full((x.shape[1],), n_cols, jnp.int64)
        if not is_f or not skipna:
            reducer = {
                "sum": jnp.sum, "mean": jnp.mean, "min": jnp.min, "max": jnp.max,
                "median": jnp.median,
            }.get(op_name)
            if reducer is not None:
                return reducer(x, axis=0)
            if op_name == "var":
                return jnp.var(x, axis=0, ddof=ddof)
            if op_name == "std":
                return jnp.std(x, axis=0, ddof=ddof)
        reducer = {
            "sum": jnp.nansum, "mean": jnp.nanmean, "min": jnp.nanmin,
            "max": jnp.nanmax, "median": jnp.nanmedian,
        }.get(op_name)
        if reducer is not None:
            return reducer(x, axis=0)
        if op_name == "var":
            return jnp.nanvar(x, axis=0, ddof=ddof)
        if op_name == "std":
            return jnp.nanstd(x, axis=0, ddof=ddof)
        raise ValueError(op_name)

    return fn


def reduce_axis1(
    op_name: str,
    cols: List[Any],
    skipna: bool = True,
    ddof: int = 1,
    cast_bool: bool = False,
) -> Any:
    """Row-wise reduction across columns; returns a padded device 1-D array.

    Accepts deferred LazyExprs like :func:`reduce_columns` (fused tail).
    """
    from modin_tpu.ops.lazy import run_fused

    skipna, ddof = bool(skipna), int(ddof)
    inner = _make_axis1_fn(op_name, len(cols), skipna, ddof)

    def tail(arrs):
        import jax.numpy as jnp

        if cast_bool:
            arrs = [a.astype(jnp.int64) if a.dtype == jnp.bool_ else a for a in arrs]
        return inner(tuple(arrs))

    return run_fused(
        cols,
        tail_key=("reduce_axis1", op_name, skipna, ddof, bool(cast_bool)),
        tail_builder=tail,
    )


@functools.lru_cache(maxsize=None)
def _jit_idx_minmax(op_name: str, n_cols: int, n: int):
    import jax
    import jax.numpy as jnp

    def fn(cs: Tuple) -> Tuple:
        out = []
        counts = []
        for c in cs:
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            valid = _valid_mask(c, n)
            if is_f:
                n_valid = jnp.sum(valid & ~jnp.isnan(c))
            else:
                n_valid = jnp.sum(valid)
            counts.append(n_valid)
            if op_name == "idxmin":
                neutral = jnp.inf if is_f else _int_max(c.dtype)
                x = _masked(c, n, neutral)
                x = jnp.where(jnp.isnan(x), jnp.inf, x) if is_f else x
                out.append(jnp.argmin(x))
            else:
                neutral = -jnp.inf if is_f else _int_min(c.dtype)
                x = _masked(c, n, neutral)
                x = jnp.where(jnp.isnan(x), -jnp.inf, x) if is_f else x
                out.append(jnp.argmax(x))
        return tuple(out), tuple(counts)

    return jax.jit(fn)


def idx_minmax(op_name: str, cols: List[Any], n: int, skipna: bool = True):
    """(positions, valid_counts) per padded column, NaN-skipping; one fetch."""
    import jax

    positions, counts = _jit_idx_minmax(op_name, len(cols), int(n))(tuple(cols))
    fetched = _engine_materialize((positions, counts))
    return [int(r) for r in fetched[0]], [int(c) for c in fetched[1]]


# --------------------------------------------------------------------- #
# graftsort: sort-shaped reductions (median / quantile / nunique / mode)
# over shared sorted representations and O(n) histogram fast paths
# --------------------------------------------------------------------- #
#
# Three execution strategies per column, planned before dispatch:
#
# - "dict":   the answer is already on the host (dictionary-encoding
#             categories; ops/dictionary.py) — zero device work;
# - "hist":   bounded-range ints and dictionary codes count occurrences
#             with one O(n) scatter-add histogram — no sort, and mode's
#             k_bound cap is dead code here (every modal value falls out
#             of the bin mask);
# - "cached"/"sort": the classic sorted path, but the (sorted, n_valid)
#             prefix is built once per column via ops/sort.sorted_valid
#             and cached on the DeviceColumn (ops/sorted_cache.py), so
#             median + quantile + nunique + mode on one column pay ONE
#             O(n log n) sort, not four.
#
# The substrate-aware choice between running any of this on device and
# declining to the pandas fallback belongs to ops/router.py; the query
# compiler consults it with the planned strategies before calling the
# executors below.


class ColumnPlan(NamedTuple):
    col: Any  # DeviceColumn carrying the values (dictionary codes included)
    strategy: str  # ops/router.py STRATEGIES member
    span: int  # histogram value-bin count (hist strategy only)
    base: int  # histogram base value: bin = value - base (0 for codes)
    n_categories: int  # dict strategy: distinct non-missing count
    has_nan: bool  # dict/code columns: encoding has missing rows


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 1).bit_length()


@functools.lru_cache(maxsize=None)
def _jit_minmax(n_cols: int, n: int):
    """Per-column (min, max) over valid rows — the O(n) histogram
    eligibility probe for bounded-range int columns."""
    import jax

    def fn(cols: Tuple):
        import jax.numpy as jnp

        out = []
        for c in cols:
            if c.dtype == jnp.bool_:
                c = c.astype(jnp.int8)
            if c.shape[0] == n:
                out.append((jnp.min(c), jnp.max(c)))
            else:
                valid = _valid_mask(c, n)
                out.append(
                    (
                        jnp.min(jnp.where(valid, c, _int_max(c.dtype))),
                        jnp.max(jnp.where(valid, c, _int_min(c.dtype))),
                    )
                )
        return tuple(out)

    return jax.jit(fn)


def plan_sort_reduce(op: str, specs: List[dict], n: int) -> List[ColumnPlan]:
    """One :class:`ColumnPlan` per column spec for a sort-shaped ``op``.

    ``specs`` entries are ``{"col": DeviceColumn}`` for numeric columns or
    ``{"col": codes, "n_categories": k, "has_nan": b}`` for
    dictionary-encoded ones.  Bounded-range int columns are probed (one
    fused min/max jit + one scalar fetch) for histogram eligibility under
    ``MODIN_TPU_KERNEL_ROUTER_HIST_BOUND``; columns with a live sorted
    representation plan as "cached".
    """
    from modin_tpu.config import KernelRouterHistBound
    from modin_tpu.ops import sorted_cache

    hist_bound = int(KernelRouterHistBound.get())
    hist_ok = op in ("nunique", "mode")
    plans: List[Any] = [None] * len(specs)
    probe: List[int] = []
    for i, spec in enumerate(specs):
        col = spec["col"]
        if "n_categories" in spec:
            k = int(spec["n_categories"])
            has_nan = bool(spec["has_nan"])
            if op == "nunique":
                plans[i] = ColumnPlan(col, "dict", 0, 0, k, has_nan)
            elif hist_ok and k + 2 <= hist_bound:
                # span floor 1: an all-missing column factorizes to empty
                # categories (k=0), and a zero-size value-bin slice would
                # make the kernel's max reduction trace-fail
                plans[i] = ColumnPlan(col, "hist", max(k, 1), 0, k, has_nan)
            elif sorted_cache.peek(col):
                plans[i] = ColumnPlan(col, "cached", 0, 0, k, has_nan)
            else:
                plans[i] = ColumnPlan(col, "sort", 0, 0, k, has_nan)
            continue
        if sorted_cache.peek(col):
            plans[i] = ColumnPlan(col, "cached", 0, 0, 0, False)
        elif hist_ok and col.pandas_dtype.kind in "biu":
            probe.append(i)
        else:
            plans[i] = ColumnPlan(col, "sort", 0, 0, 0, False)
    if probe:
        ranges = _engine_materialize(
            _jit_minmax(len(probe), int(n))(
                tuple(specs[i]["col"].data for i in probe)
            )
        )
        for i, (cmin, cmax) in zip(probe, ranges):
            cmin, cmax = int(cmin), int(cmax)
            span = cmax - cmin + 1
            if 0 < span <= hist_bound:
                plans[i] = ColumnPlan(
                    specs[i]["col"], "hist", span, cmin, 0, False
                )
            else:
                plans[i] = ColumnPlan(specs[i]["col"], "sort", 0, 0, 0, False)
    return plans


def _sorted_inputs(plans: List[ColumnPlan], n: int) -> dict:
    """{plan index: (sorted values, n_valid)} for every sorted-strategy
    plan; missing representations are built in ONE batched jit and cached
    on their columns."""
    from modin_tpu.observability import spans as graftscope
    from modin_tpu.ops import sorted_cache
    from modin_tpu.ops.sort import sorted_valid_columns

    reps: dict = {}
    missing: List[Tuple[int, Any]] = []
    for i, p in enumerate(plans):
        if p.strategy not in ("cached", "sort"):
            continue
        got = sorted_cache.get(p.col)
        if got is None:
            missing.append((i, p.col))
        else:
            reps[i] = got
    if missing:
        with graftscope.span(
            "sortcache.build", layer="QUERY-COMPILER", cols=len(missing)
        ):
            built = None
            from modin_tpu.ops import router

            if router.decide_layout("sort", int(n)) == "sharded":
                # graftmesh: build the reps through the all_to_all shuffle
                # (bit-identical representation); any decline (skew,
                # single shard) falls back to the one-jit local build
                from modin_tpu.ops import spmd

                built = spmd.sharded_sorted_valid_columns(
                    [c.data for _, c in missing], int(n)
                )
            if built is None:
                built = sorted_valid_columns(
                    [c.data for _, c in missing], int(n)
                )
        for (i, col), pair in zip(missing, built):
            sorted_cache.attach(col, pair[0], pair[1])
            reps[i] = pair
    return reps


@functools.lru_cache(maxsize=None)
def _jit_nunique_sorted(n_pairs: int, n: int, dropna: bool):
    import jax

    def fn(pairs: Tuple):
        import jax.numpy as jnp

        out = []
        for xs, n_valid in pairs:
            is_f = jnp.issubdtype(xs.dtype, jnp.floating)
            idx = jnp.arange(xs.shape[0])
            firsts = jnp.concatenate([jnp.ones(1, bool), xs[1:] != xs[:-1]])
            count = jnp.sum(firsts & (idx < n_valid))
            if is_f and not dropna:
                count = count + (n_valid < n).astype(count.dtype)
            out.append(count)
        return tuple(out)

    return jax.jit(fn)


def _quantile_from_sorted(xs, n_valid, qs, interpolation: str):
    """Quantiles of one column's (sorted, n_valid) representation — the
    single interpolation implementation behind both the quantile and the
    median kernels."""
    import jax.numpy as jnp

    is_f = jnp.issubdtype(xs.dtype, jnp.floating)
    # fractional position of each q over the valid prefix
    pos = qs * jnp.maximum(n_valid - 1, 0).astype(jnp.float64)
    lo = jnp.floor(pos).astype(jnp.int64)
    hi = jnp.ceil(pos).astype(jnp.int64)
    if interpolation in ("lower", "higher", "nearest"):
        # pandas keeps the ORIGINAL dtype value exactly (int64 results
        # stay int64) — select without a float cast
        if interpolation == "lower":
            idx = lo
        elif interpolation == "higher":
            idx = hi
        else:  # nearest: numpy half-to-even
            idx = jnp.round(pos).astype(jnp.int64)
        v = jnp.take(xs, idx)
        if is_f:
            v = jnp.where(n_valid > 0, v, jnp.nan)
        return v
    xs64 = xs.astype(jnp.float64)
    vlo = jnp.take(xs64, lo)
    vhi = jnp.take(xs64, hi)
    if interpolation == "linear":
        v = vlo + (vhi - vlo) * (pos - lo)
    else:  # midpoint
        v = (vlo + vhi) / 2.0
    return jnp.where(n_valid > 0, v, jnp.nan)


@functools.lru_cache(maxsize=None)
def _jit_quantile_sorted(n_pairs: int, n_q: int, interpolation: str):
    import jax

    def fn(pairs: Tuple, qs):
        return tuple(
            _quantile_from_sorted(xs, n_valid, qs, interpolation)
            for xs, n_valid in pairs
        )

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_median_sorted(n_pairs: int, n: int, skipna: bool):
    import jax

    def fn(pairs: Tuple):
        import jax.numpy as jnp

        qs = jnp.asarray([0.5], jnp.float64)
        out = []
        for xs, n_valid in pairs:
            v = _quantile_from_sorted(xs, n_valid, qs, "linear")[0]
            v = v.astype(jnp.float64)
            if not skipna:
                # pandas: median(skipna=False) is NaN when any NaN present
                v = jnp.where(n_valid < n, jnp.nan, v)
            out.append(v)
        return tuple(out)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_mode_sorted(n_pairs: int, k_bound: int):
    import jax

    def fn(pairs: Tuple):
        import jax.numpy as jnp

        outs = []
        for xs, n_valid in pairs:
            idx = jnp.arange(xs.shape[0])
            valid = idx < n_valid
            firsts = (
                jnp.concatenate([jnp.ones(1, bool), xs[1:] != xs[:-1]]) & valid
            )
            # run id per element; counts via scatter-add of run starts' spans
            rid = jnp.cumsum(firsts) - 1
            ones = valid.astype(jnp.int64)
            run_counts = jnp.zeros(xs.shape[0], jnp.int64).at[rid].add(ones)
            count_of = run_counts[rid]
            max_count = jnp.max(jnp.where(valid, count_of, 0))
            is_modal = firsts & (count_of == max_count)
            m = jnp.sum(is_modal)
            # gather the modal values (already ascending) into k_bound slots
            pos = jnp.cumsum(is_modal) - 1
            slot = jnp.where(is_modal, pos, k_bound)
            vals = jnp.zeros(k_bound, xs.dtype).at[slot].set(xs, mode="drop")
            outs.append((vals, m))
        return tuple(outs)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_hist(n_cols: int, span_pad: int, n: int, want_mode: bool, dropna: bool):
    """O(n) histogram kernel over ``span_pad`` bins (a shared power of two,
    so data-dependent value ranges cause at most log2(HIST_BOUND)
    recompiles).  Bin layout: [0, span_pad-2) value bins, span_pad-2 the
    NaN bin (dictionary codes / float code columns), span_pad-1 the
    dead-row bin (pads)."""
    import jax

    nan_slot = span_pad - 2
    dead_slot = span_pad - 1

    def fn(cols: Tuple, bases: Tuple):
        import jax.numpy as jnp

        outs = []
        for c, base in zip(cols, bases):
            if c.dtype == jnp.bool_:
                c = c.astype(jnp.int8)
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            if is_f:
                # dictionary codes: float64 in [0, k) with NaN for missing
                nanm = jnp.isnan(c)
                bins = jnp.where(
                    nanm, nan_slot, jnp.where(nanm, 0.0, c).astype(jnp.int32)
                )
            else:
                bins = (c - base).astype(jnp.int32)
            if c.shape[0] != n:
                bins = jnp.where(_valid_mask(c, n), bins, dead_slot)
            counts = jnp.zeros(span_pad, jnp.int64).at[bins].add(1)
            value_counts = counts[:nan_slot]
            nan_count = counts[nan_slot]
            if not want_mode:
                cnt = jnp.sum(value_counts > 0)
                if is_f and not dropna:
                    cnt = cnt + (nan_count > 0).astype(cnt.dtype)
                outs.append(cnt)
                continue
            max_val = jnp.max(value_counts)
            max_all = (
                max_val if dropna else jnp.maximum(max_val, nan_count)
            )
            mask = (value_counts == max_all) & (value_counts > 0)
            nan_modal = (
                jnp.zeros((), bool)
                if dropna
                else (nan_count == max_all) & (nan_count > 0)
            )
            outs.append((mask, max_all, nan_modal))
        return tuple(outs)

    return jax.jit(fn)


def _hist_groups(plans: List[ColumnPlan]):
    """(indices, span_pad, cols, bases) for the histogram-strategy plans."""
    import jax.numpy as jnp

    idxs = [i for i, p in enumerate(plans) if p.strategy == "hist"]
    if not idxs:
        return idxs, 0, (), ()
    span_pad = _next_pow2(max(plans[i].span for i in idxs) + 2)
    from modin_tpu.observability import costs as _costs

    if _costs.COST_ON:
        # pow2-padded histogram bins: span_pad slots per column vs the
        # span + NaN + dead slots actually addressed (int64 counts)
        valid = sum(int(plans[i].span) + 2 for i in idxs)
        _costs.note_padding(
            "reductions.hist_bins", len(idxs) * span_pad * 8, valid * 8
        )
    cols = tuple(plans[i].col.data for i in idxs)
    bases = tuple(jnp.asarray(int(plans[i].base)) for i in idxs)
    return idxs, span_pad, cols, bases


def nunique_planned(
    plans: List[ColumnPlan], n: int, dropna: bool = True
) -> List[int]:
    """Distinct-count per planned column: O(1) for dict columns, one O(n)
    histogram for bounded-range ints, sorted adjacent-difference (shared
    sorted rep) for the rest."""
    n, dropna = int(n), bool(dropna)
    results: List[Any] = [None] * len(plans)
    for i, p in enumerate(plans):
        if p.strategy == "dict":
            results[i] = p.n_categories + (0 if dropna else int(p.has_nan))
    sorted_is = [
        i for i, p in enumerate(plans) if p.strategy in ("cached", "sort")
    ]
    if sorted_is:
        reps = _sorted_inputs(plans, n)
        vals = _jit_nunique_sorted(len(sorted_is), n, dropna)(
            tuple(reps[i] for i in sorted_is)
        )
        for i, v in zip(sorted_is, _engine_materialize(vals)):
            results[i] = int(v)
    hist_is, span_pad, cols, bases = _hist_groups(plans)
    if hist_is:
        vals = _jit_hist(len(hist_is), span_pad, n, False, dropna)(cols, bases)
        for i, v in zip(hist_is, _engine_materialize(vals)):
            results[i] = int(v)
    return results


def mode_planned(
    plans: List[ColumnPlan], n: int, dropna: bool = True, k_bound: int = 1024
) -> List[Any]:
    """Per-column modal values, ascending (pandas' order).

    Returns per column either ``(values, nan_modal)`` — a host array of the
    modal values (code indices for dictionary columns; the caller decodes)
    plus whether NaN ties the max count (dropna=False histogram path only)
    — or ``None`` when the column's mode is unrepresentable on device (the
    sorted path's empty/over-``k_bound`` mode set); the caller falls back.
    The histogram path has no such cap: modal values fall out of the bin
    mask, so ``k_bound`` is dead code there.
    """
    n, dropna = int(n), bool(dropna)
    results: List[Any] = [None] * len(plans)
    sorted_is = [
        i for i, p in enumerate(plans) if p.strategy in ("cached", "sort")
    ]
    if sorted_is:
        reps = _sorted_inputs(plans, n)
        fetched = _engine_materialize(
            _jit_mode_sorted(len(sorted_is), int(k_bound))(
                tuple(reps[i] for i in sorted_is)
            )
        )
        for i, (vals, m) in zip(sorted_is, fetched):
            m = int(m)
            if 0 < m <= int(k_bound):
                results[i] = (np.asarray(vals[:m]), False)
    hist_is, span_pad, cols, bases = _hist_groups(plans)
    if hist_is:
        fetched = _engine_materialize(
            _jit_hist(len(hist_is), span_pad, n, True, dropna)(cols, bases)
        )
        for i, (mask, max_all, nan_modal) in zip(hist_is, fetched):
            nan_modal = bool(nan_modal)
            if int(max_all) <= 0 and not nan_modal:
                # all-missing under dropna: empty mode set, like the
                # sorted path — the caller falls back to pandas
                continue
            values = np.nonzero(np.asarray(mask))[0].astype(np.int64) + int(
                plans[i].base
            )
            results[i] = (values, nan_modal)
    return results


def quantile_columns(
    cols: List[Any], n: int, qs: List[float], interpolation: str = "linear"
) -> list:
    """Quantiles per device COLUMN (not raw array: the shared sorted
    representation caches on the column) -> list of (n_q,) host arrays,
    each in its pandas result dtype: float64 for 'linear'/'midpoint', the
    column's own dtype for the element-selecting interpolations
    ('lower'/'higher'/'nearest' — pandas keeps int64 exact there).  An
    all-NaN/empty int column cannot carry NaN; the QC gate guarantees n>0
    and int columns are never NaN."""
    import jax.numpy as jnp

    plans = [ColumnPlan(c, "sort", 0, 0, 0, False) for c in cols]
    reps = _sorted_inputs(plans, int(n))
    fn = _jit_quantile_sorted(len(cols), len(qs), str(interpolation))
    results = fn(
        tuple(reps[i] for i in range(len(cols))), jnp.asarray(qs, jnp.float64)
    )
    return [np.asarray(r) for r in _engine_materialize(results)]


def median_columns(cols: List[Any], n: int, skipna: bool = True) -> list:
    """Median per device column over the shared sorted representation;
    pandas semantics including ``skipna=False`` (any NaN -> NaN)."""
    plans = [ColumnPlan(c, "sort", 0, 0, 0, False) for c in cols]
    reps = _sorted_inputs(plans, int(n))
    results = _jit_median_sorted(len(cols), int(n), bool(skipna))(
        tuple(reps[i] for i in range(len(cols)))
    )
    return [np.asarray(r) for r in _engine_materialize(results)]


def _axis1_matrix(cols, n):
    """Stack padded columns into an (n_pad, k) matrix in their numpy common
    dtype (pandas' axis-1 upcast rule)."""
    import jax.numpy as jnp

    common = np.result_type(*[np.dtype(str(c.dtype)) for c in cols])
    if common.kind == "b":
        common = np.dtype(np.int8)
    return jnp.stack([c.astype(common.name) for c in cols], axis=1)


@functools.lru_cache(maxsize=None)
def _jit_nunique_axis1(n_cols: int, n: int, dropna: bool):
    import jax

    def fn(cols: Tuple):
        import jax.numpy as jnp

        x = _axis1_matrix(cols, n)
        xs = jnp.sort(x, axis=1)  # NaN sort to the row tail
        k = xs.shape[1]
        if jnp.issubdtype(xs.dtype, jnp.floating):
            nv = jnp.sum(~jnp.isnan(xs), axis=1)
        else:
            nv = jnp.full(xs.shape[0], k, jnp.int64)
        j = jnp.arange(1, k)
        news = (xs[:, 1:] != xs[:, :-1]) & (j[None, :] < nv[:, None])
        distinct = jnp.where(nv > 0, 1 + jnp.sum(news, axis=1), 0)
        if not dropna and jnp.issubdtype(xs.dtype, jnp.floating):
            distinct = distinct + (nv < k).astype(distinct.dtype)
        return distinct.astype(jnp.int64)

    return jax.jit(fn)


def nunique_axis1(cols: List[Any], n: int, dropna: bool = True) -> Any:
    """Row-wise distinct count across columns -> padded device int64 array.

    Sorted-row adjacent-difference: one jit, no per-row Python.  Parity
    target: pandas ``DataFrame.nunique(axis=1)`` (reference routes it
    through a full-axis fold, modin/core/storage_formats/pandas/
    query_compiler.py)."""
    return _jit_nunique_axis1(len(cols), int(n), bool(dropna))(tuple(cols))


@functools.lru_cache(maxsize=None)
def _jit_mode_axis1(n_cols: int, n: int):
    import jax

    def fn(cols: Tuple):
        import jax.numpy as jnp

        x = _axis1_matrix(cols, n)
        nrow, k = x.shape
        is_f = jnp.issubdtype(x.dtype, jnp.floating)
        xs = jnp.sort(x, axis=1)  # NaN to the row tail
        if is_f:
            nv = jnp.sum(~jnp.isnan(xs), axis=1)  # valid count per row
        else:
            nv = jnp.full(nrow, k, jnp.int64)
        j = jnp.arange(k)
        valid = j[None, :] < nv[:, None]
        firsts = (
            jnp.concatenate(
                [jnp.ones((nrow, 1), bool), xs[:, 1:] != xs[:, :-1]], axis=1
            )
            & valid
        )
        rid = jnp.cumsum(firsts, axis=1) - 1
        # run counts without 2-D scatter: O(k) unrolled equality folds
        run_counts = jnp.stack(
            [jnp.sum((rid == q) & valid, axis=1) for q in range(k)], axis=1
        )
        count_of = jnp.take_along_axis(run_counts, jnp.maximum(rid, 0), axis=1)
        max_count = jnp.max(jnp.where(valid, count_of, 0), axis=1)
        is_modal = firsts & (count_of == max_count[:, None])
        m = jnp.sum(is_modal, axis=1)
        pos = jnp.cumsum(is_modal, axis=1) - 1
        slot = jnp.where(is_modal, pos, k)
        rows = jnp.arange(nrow)[:, None]
        # native-dtype output (zero-padded; exact for int64) + a float64
        # NaN-padded view for the ragged case (pandas' upcast)
        vals = jnp.zeros((nrow, k + 1), xs.dtype).at[rows, slot].set(xs)[:, :k]
        placed = jnp.zeros((nrow, k + 1), bool).at[rows, slot].set(True)[:, :k]
        vals_f = jnp.where(placed, vals.astype(jnp.float64), jnp.nan)
        row_ok = jnp.arange(nrow) < n
        m = jnp.where(row_ok, m, 0)
        m_max = jnp.max(m)
        uniform = jnp.all(jnp.where(row_ok, m == m_max, True))
        return vals, vals_f, m_max, uniform

    return jax.jit(fn)


def mode_axis1(cols: List[Any], n: int) -> Tuple[Any, Any, int, bool]:
    """Row-wise modes (``dropna=True``): (native-dtype zero-padded matrix,
    float64 NaN-padded matrix, max mode count over valid rows, whether every
    valid row has exactly max_count modes).  The caller takes the native
    matrix when uniform (no padding -> pandas keeps the source dtype) and
    the float64 one otherwise."""
    import jax

    vals, vals_f, m_max, uniform = _jit_mode_axis1(len(cols), int(n))(
        tuple(cols)
    )
    m_max, uniform = _engine_materialize((m_max, uniform))
    return vals, vals_f, int(m_max), bool(uniform)
