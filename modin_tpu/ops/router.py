"""graftsort kernel router: substrate-aware device/host dispatch for the
sort-shaped reduction families (median / quantile / nunique / mode).

VERDICT r5 measured the device sort-shaped kernels losing 13-23x to pandas
on the CPU substrate (an XLA:CPU single-core sort against pandas' optimized
selection/hash kernels) while the framework happily ran them anyway: device
paths were gated on dtype/shape, never on *where the kernel would run*.
This module is the repo's per-op analogue of the reference's backend cost
calculator (QCCoercionCost, reference
modin/core/storage_formats/base/query_compiler.py:116) and the cost-aware
rewriting Dias argues for (PAPERS.md): each sort-shaped ``_try_*`` family
asks ``decide()`` whether the device kernel or the pandas host kernel is
predicted faster at the observed (rows, per-column strategy, substrate),
and declines to the existing ``device_path`` fallback seam when the host
wins.

The model is seeded by a **one-shot calibration**: four device micro-kernels
(sort, sorted-consume, histogram) and four host kernels (pandas median /
quantile / nunique / mode) are timed at ``KernelRouterCalibrationRows`` and
the per-row coefficients cached to ``CacheDir`` per substrate, so the cost
is paid once per machine.  Scaling: sorts grow n·log n, everything else
linearly.  Decisions are observable: every ``decide()`` emits a
``router.<op>.<choice>`` metric and a ``router.decide`` span carrying the
predicted costs, so a graftscope trace shows *why* a path was chosen.

Knobs (config/envvars.py): ``MODIN_TPU_KERNEL_ROUTER`` (auto|device|host),
``MODIN_TPU_KERNEL_ROUTER_MIN_ROWS`` (below it, auto == device and the
calibration never runs — unit-test frames stay on device, deterministic),
``MODIN_TPU_KERNEL_ROUTER_HIST_BOUND``,
``MODIN_TPU_KERNEL_ROUTER_CALIBRATION_ROWS``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope
from modin_tpu.ops import calibration as calstore

#: column strategies a sort-shaped plan may carry (see plan_strategies in
#: ops/reductions.py): "dict" costs ~0 (host categories already known),
#: "view" costs 0 on device (a graftview whole-result artifact already
#: holds the answer — flipping the crossover exactly like the sorted-rep
#: amortization leg, one stage further), "cached" consumes an existing
#: sorted representation, "hist" is the O(n) segment-sum path, "sort" pays
#: the full O(n log n) device sort
STRATEGIES = ("dict", "view", "cached", "hist", "sort")

#: predicted device-minus-host savings (seconds) the host side must clear
#: before auto routing declines a device path: below this the decision is
#: noise and device residency wins ties
MIN_SAVINGS_S = 0.05

_CAL_VERSION = 3

#: graftopt consult hook.  ``plan/optimizer.py`` installs a callable here
#: while ``MODIN_TPU_OPT=Auto`` (and clears it for Off): each ``decide_*``
#: offers its live verdict — ``(leg, choice, reason, **ctx)`` — and the
#: optimizer answers a replacement ``(choice, reason)`` from the current
#: node's plan-time strategy annotation, or None to keep the router's own.
#: A module attribute rather than an import so the Off mode costs exactly
#: one ``is not None`` check per decision and allocates nothing.
_opt_consult = None

#: baseline reasons the optimizer may override: forced modes and the
#: deterministic row floors stay authoritative (tests and bench legs pin
#: sides; tiny frames never consult plan-time state), as do the
#: degenerate single_shard / no_budget / uncalibrated outcomes.
_OPT_REASONS = frozenset({"auto", "cost_model", "fits", "over_headroom"})

_lock = named_lock("ops.router_calibration")
#: None = not yet resolved; False = calibration failed (route device);
#: dict = live table
_calibration: Any = None
#: the mesh shape the lazy resolution (success OR failure) belongs to —
#: an in-process reshape re-resolves both outcomes, not just tables
_calibration_mesh: Optional[str] = None
#: a table installed by set_calibration is honored verbatim (tests force
#: crossovers); a lazily-resolved one is re-resolved when the mesh reshapes
_calibration_forced = False


def set_calibration(table: Optional[Dict[str, float]]) -> None:
    """Force the calibration table (tests) or reset to lazy (None)."""
    global _calibration, _calibration_forced, _calibration_mesh
    with _lock:
        _calibration = table if table is not None else None
        _calibration_forced = table is not None
        _calibration_mesh = None


def _platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except Exception:  # graftlint: disable=EXC-HYGIENE -- no backend at all: calibration is meaningless, the caller records a failed table and routes device
        return "unknown"


def _mesh_key() -> str:
    from modin_tpu.parallel.mesh import mesh_shape_key

    try:
        return mesh_shape_key()
    except Exception:  # graftlint: disable=EXC-HYGIENE -- no backend/mesh at all: calibration is keyed 'unknown' and the sharded entries are simply absent
        return "unknown"


def _cache_path(platform: str, mesh_key: str) -> Optional[str]:
    return calstore.table_path(
        "kernel_router", platform, mesh_key=mesh_key, version=_CAL_VERSION
    )


def _time_best(fn, reps: int = 2) -> float:
    """Best-of wall time of ``fn()`` after one untimed warmup (compile)."""
    fn()
    best = float("inf")
    for _ in range(max(reps, 1)):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _measure() -> Dict[str, float]:
    """Time the per-family micro-kernels at the calibration size.

    Host kernels are timed in BOTH cardinality regimes: pandas'
    hash-based nunique/mode are up to ~40x faster per row on
    low-cardinality data (exactly the columns the device answers with a
    histogram) than on all-distinct data (the columns that need a sort),
    so one coefficient per op would systematically mis-predict one regime.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pandas

    from modin_tpu.config import KernelRouterCalibrationRows

    rows = int(KernelRouterCalibrationRows.get())
    rng = np.random.default_rng(0)
    wide = rng.integers(0, 1 << 40, rows)  # ~all-distinct: the sort case
    narrow = rng.integers(0, 1024, rows)  # low-cardinality: the hist case

    dev_wide = jnp.asarray(wide)
    dev_narrow_idx = jnp.asarray(narrow.astype(np.int32))

    sort_fn = jax.jit(jnp.sort)
    consume_fn = jax.jit(
        lambda xs: jnp.sum(
            jnp.concatenate([jnp.ones(1, bool), xs[1:] != xs[:-1]])
        )
    )
    hist_fn = jax.jit(
        lambda idx: jnp.zeros(1025, jnp.int64).at[idx].add(1)
    )

    sorted_dev = sort_fn(dev_wide)
    table = {
        "version": _CAL_VERSION,
        "platform": _platform(),
        "mesh": _mesh_key(),
        "rows": rows,
        "device_sort_s": _time_best(
            lambda: np.asarray(sort_fn(dev_wide))
        ),
        "device_consume_s": _time_best(
            lambda: np.asarray(consume_fn(sorted_dev))
        ),
        "device_hist_s": _time_best(
            lambda: np.asarray(hist_fn(dev_narrow_idx))
        ),
    }
    for regime, values in (("high", wide), ("low", narrow)):
        host = pandas.Series(values)
        table[f"host_median_{regime}_s"] = _time_best(lambda: host.median())
        table[f"host_quantile_{regime}_s"] = _time_best(
            lambda: host.quantile(0.5)
        )
        table[f"host_nunique_{regime}_s"] = _time_best(lambda: host.nunique())
        table[f"host_mode_{regime}_s"] = _time_best(lambda: host.mode())
    _measure_sharded(table, rows, wide)
    return table


def _measure_sharded(table: Dict[str, Any], rows: int, wide: Any) -> None:
    """graftmesh calibration entries, only meaningful on a >= 2-shard mesh:

    - ``device_shuffle_s``: the full sharded sort (sample -> pivots ->
      all_to_all -> per-shard local sort -> compaction) at the calibration
      size with one payload column — the end-to-end cost ``decide_layout``
      scales by n log n against the local ``device_sort_s``;
    - ``collective_bytes_per_s``: a bare tiled ``all_to_all`` round over
      the same volume, giving the interconnect term extra payload columns
      are billed at (the ``engine.cost.collective_bytes`` coefficient).

    Any failure leaves the entries absent: ``decide_layout`` then answers
    "local"/uncalibrated, never crashes.
    """
    from modin_tpu.parallel.mesh import get_mesh, num_row_shards

    try:
        S = num_row_shards()
        if S < 2:
            return
        import jax
        import numpy as np

        from jax.sharding import PartitionSpec as P

        from modin_tpu.ops.structural import pad_host
        from modin_tpu.parallel import shuffle as _shuffle
        from modin_tpu.parallel.engine import JaxWrapper
        from modin_tpu.parallel.jax_compat import shard_map

        key_dev = JaxWrapper.put(pad_host(wide))
        payload = JaxWrapper.put(pad_host(wide))

        def run_shuffle() -> None:
            out = _shuffle.range_shuffle(
                key_dev, [payload], rows, local_sort=True
            )
            np.asarray(out[0])

        table["device_shuffle_s"] = _time_best(run_shuffle)

        mesh = get_mesh()
        cap = max(rows // max(S * S, 1), 8)

        def local_roundtrip(x):
            block = x.reshape(S, cap)
            recv = jax.lax.all_to_all(
                block, "rows", split_axis=0, concat_axis=0, tiled=True
            )
            return recv.reshape(-1)

        fn = jax.jit(
            shard_map(
                local_roundtrip,
                mesh=mesh,
                in_specs=(P("rows"),),
                out_specs=P("rows"),
                check_vma=False,
            )
        )
        data = JaxWrapper.put(np.zeros(S * S * cap, dtype=np.int64))
        wall = _time_best(lambda: np.asarray(fn(data)))
        moved_bytes = S * S * cap * 8
        if wall > 0:
            table["collective_bytes_per_s"] = moved_bytes / wall
    except Exception:  # graftlint: disable=EXC-HYGIENE -- sharded calibration is an optimization probe; absence of its entries keeps layout routing on the local default
        pass


def calibration_peek() -> Optional[Dict[str, float]]:
    """The calibration table if ALREADY resolved, never measuring.

    graftopt's plan-time cost model reads coefficients through this —
    planning must never trigger the one-shot device measurement (a
    dispatch storm inside someone's measured region); the runtime
    ``decide()`` keeps paying for resolution at its existing points.
    """
    with _lock:
        table = _calibration
    return table if isinstance(table, dict) else None


def get_calibration() -> Optional[Dict[str, float]]:
    """The calibration table: memory -> CacheDir -> one-shot measurement.

    Returns None when calibration is impossible (the caller routes device,
    the pre-router behavior); the failure is remembered so a broken
    substrate is probed once, not per decision.
    """
    global _calibration, _calibration_mesh
    with _lock:
        if _calibration is not None:
            if _calibration_forced or _calibration_mesh == _mesh_key():
                return _calibration if _calibration is not False else None
            # mesh reshaped: the resolution — a table's sharded entries,
            # their absence, or a FAILURE — belongs to another topology
            _calibration = None
        platform = _platform()
        mesh_key = _mesh_key()
        path = _cache_path(platform, mesh_key)
        table = calstore.load_table(
            path, version=_CAL_VERSION, platform=platform, mesh_key=mesh_key
        )
        if table is not None:
            _calibration = table
            _calibration_mesh = mesh_key
            return table
        try:
            with graftscope.span(
                "router.calibrate", layer="QUERY-COMPILER", platform=platform
            ):
                table = _measure()
            emit_metric("router.calibrate", 1)
        except Exception:  # graftlint: disable=EXC-HYGIENE -- calibration is an optimization probe; ANY failure (no backend, OOM at micro size) must leave routing on the pre-router device default
            _calibration = False
            _calibration_mesh = mesh_key
            return None
        _calibration = table
        _calibration_mesh = mesh_key
        calstore.store_table(path, table)
        return table


def predicted_costs(
    op: str, n: int, strategies: List[str], table: Dict[str, float]
) -> Dict[str, float]:
    """Predicted {device_s, host_s} for ``op`` over ``n`` rows with the
    given per-column strategies.  Linear scaling for everything except the
    sort term, which grows n*log2(n)."""
    cal_rows = max(int(table["rows"]), 2)
    scale = calstore.linear_scale(n, cal_rows)
    logscale = calstore.nlogn_scale(n, cal_rows)
    consume = table["device_consume_s"] * scale
    per_strategy = {
        "dict": 0.0,
        "view": 0.0,  # graftview result artifact: the answer is cached
        "cached": consume,
        "hist": table["device_hist_s"] * scale,
        "sort": table["device_sort_s"] * logscale + consume,
    }
    device_s = sum(per_strategy[s] for s in strategies)
    # host cost is cardinality-sensitive: hist/dict columns are the
    # low-cardinality regime pandas hashes fast, sort columns the slow one
    # (a view-cached column bills host at the slow regime: the host side
    # would have to recompute it from scratch)
    host_s = sum(
        table[
            f"host_{op}_{'low' if s in ('hist', 'dict') else 'high'}_s"
        ]
        for s in strategies
    ) * scale
    return {"device_s": device_s, "host_s": host_s}


def decide_layout(
    op: str, n: int, payload_cols: int = 0, itemsize: int = 8
) -> str:
    """"local" or "sharded" for one collective-eligible op over ``n`` rows.

    ``op`` names the kernel family (``sort`` for sort_values and the
    sorted-representation build, ``merge`` for the join's right-side sort);
    ``payload_cols`` counts the non-key columns the sharded path would move
    through the all_to_all (each is pure collective traffic the local path
    never pays).  The model: both sides scale n log n from their calibrated
    walls (``device_sort_s`` vs ``device_shuffle_s``), and payload columns
    beyond the calibration's single one are billed at the measured
    ``collective_bytes_per_s``.  Forced modes (``MODIN_TPU_SPMD``) and a
    single-shard mesh skip the model entirely — the router, not a flag, is
    the default decider, but tests and bench legs pin each side.

    Emitted as ``router.spmd_<op>.<choice>`` metrics and a
    ``router.decide`` span with the predicted costs.
    """
    from modin_tpu.config import SpmdMinRows, SpmdMode
    from modin_tpu.parallel.mesh import num_row_shards

    try:
        S = num_row_shards()
    except Exception:  # graftlint: disable=EXC-HYGIENE -- no backend: there is no mesh to shard over, the local path is the only path
        S = 1
    mode = SpmdMode.get().lower()
    costs: Dict[str, float] = {}
    if S < 2:
        choice, reason = "local", "single_shard"
    elif mode == "sharded":
        choice, reason = "sharded", "forced"
    elif mode == "local":
        choice, reason = "local", "forced"
    elif n < int(SpmdMinRows.get()):
        choice, reason = "local", "below_min_rows"
    else:
        table = get_calibration()
        if table is None or "device_shuffle_s" not in table:
            choice, reason = "local", "uncalibrated"
        else:
            logscale = calstore.nlogn_scale(n, int(table["rows"]))
            local_s = table["device_sort_s"] * logscale
            sharded_s = table["device_shuffle_s"] * logscale
            bw = float(table.get("collective_bytes_per_s") or 0.0)
            if bw > 0 and payload_cols > 1:
                # the calibration shuffled one payload column; each extra
                # one is (n rows + slack) of pure interconnect traffic
                sharded_s += (payload_cols - 1) * n * itemsize / bw
            costs = {"local_s": local_s, "sharded_s": sharded_s}
            choice = "sharded" if sharded_s < local_s else "local"
            reason = "cost_model"
    if _opt_consult is not None and reason in _OPT_REASONS:
        planned = _opt_consult("layout", choice, reason, op=op, n=n)
        if planned is not None:
            choice, reason = planned
    emit_metric(f"router.spmd_{op}.{choice}", 1)
    if graftscope.TRACE_ON:
        graftscope.finish_span(
            graftscope.start_span(
                "router.decide",
                layer="QUERY-COMPILER",
                attrs={
                    "op": f"spmd_{op}",
                    "n": n,
                    "choice": choice,
                    "reason": reason,
                    "payload_cols": payload_cols,
                    **{k: round(v, 6) for k, v in costs.items()},
                },
            )
        )
    return choice


def decide_residency(op: str, est_bytes: int, self_bytes: int = 0) -> str:
    """"resident" or "windowed" for one streaming-eligible op (graftstream).

    ``op`` names the family (``scan_reduce`` / ``scan_groupby`` for the
    windowed plan lowering, ``sort`` / ``merge`` for the external kernels);
    ``est_bytes`` is the op's estimated working-set (sniffed source size or
    frame bytes) and ``self_bytes`` the share of the device ledger the op's
    own inputs already occupy (subtracted so a frame is not counted against
    its own headroom).  Model: with ``MODIN_TPU_STREAM=Auto`` the op
    streams exactly when its estimate exceeds the ledger headroom —
    ``budget - other residents`` — under the configured device budget; no
    budget means resident always.  ``Resident``/``Windowed`` pin a side
    (tests, bench legs).

    Emitted as ``router.residency_<op>.<choice>`` metrics and a
    ``router.decide`` span with the estimate and headroom.
    """
    from modin_tpu.config import StreamMode
    from modin_tpu.core.memory import device_ledger

    mode = StreamMode.get().lower()
    headroom = None
    if mode == "resident":
        choice, reason = "resident", "forced"
    elif mode == "windowed":
        choice, reason = "windowed", "forced"
    else:
        budget = device_ledger.budget()
        if budget is None:
            choice, reason = "resident", "no_budget"
        else:
            headroom = budget - max(
                device_ledger.total_bytes() - max(int(self_bytes), 0), 0
            )
            if int(est_bytes) > headroom:
                choice, reason = "windowed", "over_headroom"
            else:
                choice, reason = "resident", "fits"
    if _opt_consult is not None and reason in _OPT_REASONS:
        planned = _opt_consult(
            "residency", choice, reason, op=op, est_bytes=int(est_bytes)
        )
        if planned is not None:
            choice, reason = planned
    emit_metric(f"router.residency_{op}.{choice}", 1)
    if graftscope.TRACE_ON:
        graftscope.finish_span(
            graftscope.start_span(
                "router.decide",
                layer="QUERY-COMPILER",
                attrs={
                    "op": f"residency_{op}",
                    "est_bytes": int(est_bytes),
                    "choice": choice,
                    "reason": reason,
                    **(
                        {"headroom_bytes": int(headroom)}
                        if headroom is not None
                        else {}
                    ),
                },
            )
        )
    return choice


def decide_compile(plan_sig: Any, n: int) -> str:
    """"fused" or "staged" for one whole-plan materialization (graftfuse).

    ``plan_sig`` is the stable segment signature (plan/fuse.py), carried
    into the decision span so a trace shows WHICH plan chose which leg;
    ``n`` is the leaf frame's logical row count.  The model is a floor,
    not a calibration: tracing + compiling a whole-plan XLA program costs
    milliseconds regardless of data size, so below
    ``MODIN_TPU_FUSE_MIN_ROWS`` the staged path's already-compiled per-op
    kernels win outright.  ``MODIN_TPU_FUSE`` pins a side (tests, bench
    legs).  Emitted as ``router.fuse.<choice>`` metrics and a
    ``router.decide`` span.
    """
    from modin_tpu.config import FuseMinRows, FuseMode

    mode = FuseMode.get().lower()
    if mode == "fused":
        choice, reason = "fused", "forced"
    elif mode == "staged":
        choice, reason = "staged", "forced"
    elif n < int(FuseMinRows.get()):
        choice, reason = "staged", "below_min_rows"
    else:
        choice, reason = "fused", "auto"
    if _opt_consult is not None and reason in _OPT_REASONS:
        planned = _opt_consult("compile", choice, reason, sig=plan_sig, n=n)
        if planned is not None:
            choice, reason = planned
    emit_metric(f"router.fuse.{choice}", 1)
    if graftscope.TRACE_ON:
        graftscope.finish_span(
            graftscope.start_span(
                "router.decide",
                layer="QUERY-COMPILER",
                attrs={
                    "op": "fuse",
                    "n": n,
                    "choice": choice,
                    "reason": reason,
                    "plan_sig": str(plan_sig),
                },
            )
        )
    return choice


def forced_host(op: str, n: int) -> bool:
    """True when routing is forced to Host: callers check this BEFORE any
    planning work (device materialization, the min/max histogram probe) so
    a substrate the operator declared device-bad pays zero device
    dispatches on the way to the pandas fallback.  Records the decision
    like any other (empty strategy list)."""
    from modin_tpu.config import KernelRouterMode

    if KernelRouterMode.get().lower() != "host":
        return False
    decide(op, n, [])
    return True


def decide(op: str, n: int, strategies: List[str]) -> str:
    """"device" or "host" for one sort-shaped op over ``n`` rows.

    ``op`` is the host-kernel family (median / quantile / nunique / mode);
    ``strategies`` carries one STRATEGIES entry per participating column.
    The decision is emitted as a ``router.<op>.<choice>`` metric and a
    ``router.decide`` span with the predicted costs.
    """
    from modin_tpu.config import KernelRouterMinRows, KernelRouterMode

    mode = KernelRouterMode.get().lower()
    costs: Dict[str, float] = {}
    if mode in ("device", "host"):
        choice, reason = mode, "forced"
    elif n < int(KernelRouterMinRows.get()):
        choice, reason = "device", "below_min_rows"
    else:
        table = get_calibration()
        if table is None:
            choice, reason = "device", "uncalibrated"
        else:
            costs = predicted_costs(op, n, strategies, table)
            if costs["device_s"] - costs["host_s"] > MIN_SAVINGS_S:
                choice, reason = "host", "cost_model"
            else:
                choice, reason = "device", "cost_model"
    if _opt_consult is not None and reason in _OPT_REASONS:
        planned = _opt_consult(
            "kernel", choice, reason, op=op, n=n, strategies=strategies
        )
        if planned is not None:
            choice, reason = planned
    emit_metric(f"router.{op}.{choice}", 1)
    if graftscope.TRACE_ON:
        graftscope.finish_span(
            graftscope.start_span(
                "router.decide",
                layer="QUERY-COMPILER",
                attrs={
                    "op": op,
                    "n": n,
                    "choice": choice,
                    "reason": reason,
                    "strategies": ",".join(strategies),
                    **{k: round(v, 6) for k, v in costs.items()},
                },
            )
        )
    return choice
