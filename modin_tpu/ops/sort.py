"""Device sort kernels: stable multi-key argsort + permutation apply (pad-aware).

TPU-native replacement for the reference's range-partitioning sort
(modin/core/dataframe/pandas/dataframe/dataframe.py:2565 sample->pivot->
shuffle->local-sort): on a device mesh a global ``jnp.argsort`` over a sharded
array already lowers to XLA's distributed sort (bitonic/radix over ICI), so
the four-stage shuffle collapses into one compiled op.

Pad rows are forced to sort after every valid row (stability keeps valid rows,
whose positions are < n, ahead on ties), so sorted frames keep their trailing
pads.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


from modin_tpu.parallel.engine import materialize as _engine_materialize


def _pad_sentinel(dtype, ascending: bool):
    import jax.numpy as jnp

    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if ascending else -jnp.inf
    if dtype == jnp.bool_:
        return True if ascending else False
    info = np.iinfo(np.dtype(str(dtype)))
    return info.max if ascending else info.min


@functools.lru_cache(maxsize=None)
def _jit_lexsort(n_keys: int, n: int, n_asc: Tuple[bool, ...], na_last: bool):
    import jax
    import jax.numpy as jnp

    def order_one(k_masked, ascending, perm):
        from modin_tpu.ops.structural import float_total_order

        kk = jnp.take(k_masked, perm)
        if jnp.issubdtype(kk.dtype, jnp.floating):
            # total-order int keys: NaN sorts STRICTLY beyond +inf instead of
            # tying with it (a where(nan, inf) mapping misorders inf vs NaN),
            # and pads sort strictly beyond NaN (perm values are original
            # positions, so padness survives earlier rounds)
            t = float_total_order(kk)
            i64 = np.iinfo(np.int64)
            nanm = jnp.isnan(kk)
            is_pad = perm >= n
            if ascending:
                nan_key = np.int64(i64.min + 1) if not na_last else None
                key = t if na_last else jnp.where(nanm, nan_key, t)
                key = jnp.where(is_pad, np.int64(i64.max), key)
                o = jnp.argsort(key, stable=True)
            else:
                key = jnp.where(nanm, np.int64(i64.min + 1), t) if na_last else t
                key = jnp.where(is_pad, np.int64(i64.min), key)
                o = jnp.argsort(key, stable=True, descending=True)
        else:
            o = jnp.argsort(kk, stable=True, descending=not ascending)
        return jnp.take(perm, o)

    def fn(keys: Tuple):
        p = keys[0].shape[0]
        valid = jnp.arange(p) < n
        masked = [
            jnp.where(valid, k, _pad_sentinel(k.dtype, asc))
            for k, asc in zip(keys, n_asc)
        ]
        perm = jnp.arange(p, dtype=jnp.int64)
        # least-significant key first; stable sorts preserve prior order
        for i in range(n_keys - 1, -1, -1):
            perm = order_one(masked[i], n_asc[i], perm)
        return perm

    return jax.jit(fn)


def lexsort_permutation(
    keys: List[Any], n: int, ascending: List[bool], na_position: str = "last"
) -> Any:
    """Stable permutation ordering rows by the given padded keys."""
    from modin_tpu.observability import costs as _costs

    if _costs.COST_ON:
        _costs.note_padding(
            "sort.lexsort",
            sum(int(k.shape[0]) * k.dtype.itemsize for k in keys),
            sum(int(n) * k.dtype.itemsize for k in keys),
        )
    fn = _jit_lexsort(
        len(keys), int(n), tuple(bool(a) for a in ascending), na_position == "last"
    )
    return fn(tuple(keys))


def sorted_valid(c, n):
    """(sorted values, n_valid): NaN/pad rows sort to the tail as +inf/max
    surrogates so the first ``n_valid`` entries are exactly the clean data.

    The shared prefix of every sort-shaped reduction (median, quantile,
    nunique, mode) — graftsort caches its output per column
    (ops/sorted_cache.py) so consecutive ops on one column pay one sort.
    """
    import jax.numpy as jnp

    from modin_tpu.ops.reductions import _int_max, _valid_mask

    if c.dtype == jnp.bool_:
        c = c.astype(jnp.int8)  # XLA sort keys; 0/1 round-trips any caller
    is_f = jnp.issubdtype(c.dtype, jnp.floating)
    valid = _valid_mask(c, n) if c.shape[0] != n else None
    if is_f:
        nanm = jnp.isnan(c) if valid is None else (jnp.isnan(c) | ~valid)
        x = jnp.where(nanm, jnp.inf, c)
        n_valid = (n if valid is None else jnp.sum(valid)) - jnp.sum(
            jnp.isnan(c) if valid is None else (jnp.isnan(c) & valid)
        )
    else:
        x = c if valid is None else jnp.where(valid, c, _int_max(c.dtype))
        n_valid = jnp.asarray(n, jnp.int64)
    return jnp.sort(x), n_valid


@functools.lru_cache(maxsize=None)
def _jit_sorted_valid_multi(n_cols: int, n: int):
    import jax

    def fn(cols: Tuple):
        return tuple(sorted_valid(c, n) for c in cols)

    return jax.jit(fn)


def sorted_valid_columns(arrays: List[Any], n: int) -> List[Tuple[Any, Any]]:
    """Batched sorted-representation build: one jit sorting every column.

    Returns one (sorted values, n_valid) pair per input column; callers
    cache the pairs on their columns via ops/sorted_cache.attach.
    """
    if not arrays:
        return []
    from modin_tpu.observability import costs as _costs

    if _costs.COST_ON:
        _costs.note_padding(
            "sort.sorted_valid",
            sum(int(c.shape[0]) * c.dtype.itemsize for c in arrays),
            sum(int(n) * c.dtype.itemsize for c in arrays),
        )
    return list(_jit_sorted_valid_multi(len(arrays), int(n))(tuple(arrays)))


@functools.lru_cache(maxsize=None)
def _jit_top_k(n: int, k: int, largest: bool, is_float: bool, is_int64: bool, is_signed: bool):
    import jax

    def fn(c):
        import jax.lax as lax
        import jax.numpy as jnp

        P = c.shape[0]
        idx = jnp.arange(P)
        valid = idx < n
        if is_float:
            # IEEE total-order bits: real +/-inf stay DISTINCT from the
            # excluded (NaN/pad) rows, which get the absolute-minimum key
            x = c.astype(jnp.float64)
            nan_row = jnp.isnan(x) & valid
            bad = jnp.isnan(x) | ~valid
            bits = lax.bitcast_convert_type(x, jnp.uint64)
            sign = (bits >> jnp.uint64(63)) == 1
            u = jnp.where(sign, ~bits, bits | jnp.uint64(1 << 63))
            key = u if largest else ~u
            key = jnp.where(bad, jnp.uint64(0), key)
            n_valid = jnp.sum(~bad)
        elif is_int64:
            # signed: order-preserving sign-bit bias to uint64; unsigned:
            # already ordered. Complement flips for smallest-first without
            # the INT_MIN negation overflow.
            if is_signed:
                u = c.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
            else:
                u = c.astype(jnp.uint64)
            key = u if largest else ~u
            key = jnp.where(valid, key, jnp.uint64(0))
            nan_row = jnp.zeros(P, bool)
            n_valid = jnp.sum(valid)
        else:
            x = c.astype(jnp.int64)
            pad = np.iinfo(np.int64).min if largest else np.iinfo(np.int64).max
            x = jnp.where(valid, x, pad)
            key = x if largest else -x
            nan_row = jnp.zeros(P, bool)
            n_valid = jnp.sum(valid)
        _, positions = lax.top_k(key, k)
        # earliest NaN rows, in original order (pandas pads the result with
        # them when k exceeds the valid count)
        nan_key = jnp.where(nan_row, jnp.int64(P) - idx, jnp.int64(-1))
        _, nan_positions = lax.top_k(nan_key, k)
        return positions, nan_positions, n_valid

    return jax.jit(fn)


def top_k_positions(col, n: int, k: int, largest: bool):
    """Row positions for pandas nlargest/nsmallest keep='first': the k
    best valid values (ties keep the earlier row — XLA top_k is stable),
    then earliest NaN rows as filler when k exceeds the valid count.
    Returns (positions ndarray of length min(k, n), n_valid)."""
    import jax
    import jax.numpy as jnp

    k = max(min(int(k), int(n)), 0)
    if k == 0:
        return np.empty(0, np.int64), 0
    is_float = jnp.issubdtype(col.dtype, jnp.floating)
    is_int64 = col.dtype in (jnp.int64, jnp.uint64)
    is_signed = col.dtype != jnp.uint64
    fn = _jit_top_k(
        int(n), k, bool(largest), bool(is_float), bool(is_int64), bool(is_signed)
    )
    positions, nan_positions, n_valid = _engine_materialize(fn(col))
    n_valid = int(n_valid)
    if k <= n_valid:
        return np.asarray(positions[:k], np.int64), n_valid
    filler = np.asarray(nan_positions[: k - n_valid], np.int64)
    return (
        np.concatenate([np.asarray(positions[:n_valid], np.int64), filler]),
        n_valid,
    )



@functools.lru_cache(maxsize=None)
def _jit_rank(n_cols: int, float_flags: Tuple[bool, ...], n: int, method: str,
              ascending: bool, na_option: str, pct: bool):
    """Column rank with full pandas tie/NaN semantics.

    Sort once per column (order-preserving uint64 keys; NaNs collapse to
    one tied key and zone-sort to the top/bottom/tail per na_option, pads
    strictly last), then every method is a per-group statistic over the
    sorted run: first/last indexes of each tie group give min/max/average,
    the running group ordinal gives dense, and the sorted position itself
    gives 'first'.  Ranks scatter back through the sort permutation."""
    import jax
    import jax.numpy as jnp

    from modin_tpu.ops.structural import float_total_order

    def one(c):
        P = c.shape[0]
        idx = jnp.arange(P)
        valid = idx < n
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        nanm = (jnp.isnan(c) & valid) if is_f else jnp.zeros(P, bool)
        if jnp.issubdtype(c.dtype, jnp.unsignedinteger):
            ku = c.astype(jnp.uint64)  # already in key order, no sign bias
        else:
            t = float_total_order(c) if is_f else c.astype(jnp.int64)
            ku = t.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
        if not ascending:
            ku = ~ku
        ku = jnp.where(nanm, jnp.uint64(0), ku)  # NaNs tie with each other
        nan_zone = 0 if na_option == "top" else 2
        zone = jnp.where(valid, jnp.where(nanm, nan_zone, 1), 3).astype(jnp.uint8)
        order = jnp.lexsort((ku, zone))  # primary zone, then key, stable
        sku = jnp.take(ku, order)
        szone = jnp.take(zone, order)
        change = (szone[1:] != szone[:-1]) | (sku[1:] != sku[:-1])
        first = jnp.concatenate([jnp.ones(1, bool), change])
        pos = idx  # position within the sorted order
        f_idx = jax.lax.associative_scan(jnp.maximum, jnp.where(first, pos, 0))
        last = jnp.concatenate([change, jnp.ones(1, bool)])
        l_idx = (P - 1) - jax.lax.associative_scan(
            jnp.maximum, jnp.where(last[::-1], pos, 0)
        )[::-1]
        if method == "average":
            ranks = (f_idx + l_idx).astype(jnp.float64) / 2.0 + 1.0
        elif method == "min":
            ranks = f_idx.astype(jnp.float64) + 1.0
        elif method == "max":
            ranks = l_idx.astype(jnp.float64) + 1.0
        elif method == "first":
            ranks = pos.astype(jnp.float64) + 1.0
        else:  # dense
            ranks = jnp.cumsum(first.astype(jnp.int64)).astype(jnp.float64)
        out = jnp.zeros(P, jnp.float64).at[order].set(ranks)
        counted = valid if na_option in ("top", "bottom") else (valid & ~nanm)
        if pct:
            if method == "dense":
                denom = jnp.max(jnp.where(counted, out, 0.0))
            else:
                denom = jnp.sum(counted).astype(jnp.float64)
            out = out / jnp.maximum(denom, 1.0)
        if na_option == "keep":
            out = jnp.where(nanm, jnp.nan, out)
        return out

    def fn(cols: Tuple):
        return tuple(one(c) for c in cols)

    return jax.jit(fn)


def rank_columns(
    cols: List[Any], n: int, method: str, ascending: bool, na_option: str,
    pct: bool,
) -> List[Any]:
    import jax.numpy as jnp

    float_flags = tuple(
        bool(jnp.issubdtype(c.dtype, jnp.floating)) for c in cols
    )
    fn = _jit_rank(
        len(cols), float_flags, int(n), str(method), bool(ascending),
        str(na_option), bool(pct),
    )
    return list(fn(tuple(cols)))
