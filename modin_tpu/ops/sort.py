"""Device sort kernels: stable multi-key argsort + permutation apply (pad-aware).

TPU-native replacement for the reference's range-partitioning sort
(modin/core/dataframe/pandas/dataframe/dataframe.py:2565 sample->pivot->
shuffle->local-sort): on a device mesh a global ``jnp.argsort`` over a sharded
array already lowers to XLA's distributed sort (bitonic/radix over ICI), so
the four-stage shuffle collapses into one compiled op.

Pad rows are forced to sort after every valid row (stability keeps valid rows,
whose positions are < n, ahead on ties), so sorted frames keep their trailing
pads.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


def _pad_sentinel(dtype, ascending: bool):
    import jax.numpy as jnp

    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf if ascending else -jnp.inf
    if dtype == jnp.bool_:
        return True if ascending else False
    info = np.iinfo(np.dtype(str(dtype)))
    return info.max if ascending else info.min


@functools.lru_cache(maxsize=None)
def _jit_lexsort(n_keys: int, n: int, n_asc: Tuple[bool, ...], na_last: bool):
    import jax
    import jax.numpy as jnp

    def order_one(k_masked, ascending, perm):
        from modin_tpu.ops.structural import float_total_order

        kk = jnp.take(k_masked, perm)
        if jnp.issubdtype(kk.dtype, jnp.floating):
            # total-order int keys: NaN sorts STRICTLY beyond +inf instead of
            # tying with it (a where(nan, inf) mapping misorders inf vs NaN),
            # and pads sort strictly beyond NaN (perm values are original
            # positions, so padness survives earlier rounds)
            t = float_total_order(kk)
            i64 = np.iinfo(np.int64)
            nanm = jnp.isnan(kk)
            is_pad = perm >= n
            if ascending:
                nan_key = np.int64(i64.min + 1) if not na_last else None
                key = t if na_last else jnp.where(nanm, nan_key, t)
                key = jnp.where(is_pad, np.int64(i64.max), key)
                o = jnp.argsort(key, stable=True)
            else:
                key = jnp.where(nanm, np.int64(i64.min + 1), t) if na_last else t
                key = jnp.where(is_pad, np.int64(i64.min), key)
                o = jnp.argsort(key, stable=True, descending=True)
        else:
            o = jnp.argsort(kk, stable=True, descending=not ascending)
        return jnp.take(perm, o)

    def fn(keys: Tuple):
        p = keys[0].shape[0]
        valid = jnp.arange(p) < n
        masked = [
            jnp.where(valid, k, _pad_sentinel(k.dtype, asc))
            for k, asc in zip(keys, n_asc)
        ]
        perm = jnp.arange(p, dtype=jnp.int64)
        # least-significant key first; stable sorts preserve prior order
        for i in range(n_keys - 1, -1, -1):
            perm = order_one(masked[i], n_asc[i], perm)
        return perm

    return jax.jit(fn)


def lexsort_permutation(
    keys: List[Any], n: int, ascending: List[bool], na_position: str = "last"
) -> Any:
    """Stable permutation ordering rows by the given padded keys."""
    fn = _jit_lexsort(
        len(keys), int(n), tuple(bool(a) for a in ascending), na_position == "last"
    )
    return fn(tuple(keys))
