"""Device sort-merge join.

TPU-native replacement for the reference's merge implementations
(modin/core/storage_formats/pandas/merge.py:39 range_partitioning_merge /
:104 row_axis_merge): instead of broadcasting the right frame to every left
partition or shuffling both frames through the object store, the join runs as
one device program family:

1. stable-sort the right keys (keeps pandas' original-order-within-ties);
2. binary-search every left key against the sorted right keys (lo/hi bounds);
3. one host sync for the output row count (data-dependent shape);
4. expand matches with a searchsorted-over-offsets trick and gather both
   sides' columns by position.

Matches pandas ``merge`` row order for ``sort=False``: left order, and
right-side ties in right's original order.  Float keys use an IEEE
total-order int mapping so pandas' merge equality holds exactly
(-0.0 == 0.0; every NaN key matches every other NaN key).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import numpy as np


@functools.lru_cache(maxsize=None)
def _jit_match_bounds(n_left: int, n_right: int):
    import jax
    import jax.numpy as jnp

    def _total_order(x):
        """Monotone float64 -> int64 mapping: pandas merge equality semantics
        (-0.0 == 0.0, every NaN matches every NaN, NaN sorts last)."""
        # canonicalize: XLA folds x+0.0 to x, so -0.0 needs an explicit where
        x = jnp.where(x == 0, 0.0, x)
        x = jnp.where(jnp.isnan(x), jnp.nan, x)
        bits = jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)
        return jnp.where(bits >= 0, bits, (~bits) ^ np.int64(-(2**63)))

    def fn(left_key, right_key):
        if jnp.issubdtype(right_key.dtype, jnp.floating):
            left_key = _total_order(left_key)
            right_key = _total_order(right_key)
        # pads must sort to the tail and never match
        r_bad = jnp.arange(right_key.shape[0]) >= n_right
        perm0 = jnp.argsort(right_key, stable=True)
        bad_sorted = jnp.take(r_bad, perm0)
        perm = jnp.take(perm0, jnp.argsort(bad_sorted, stable=True))
        n_valid = jnp.sum(~r_bad)
        # the search array must stay monotone through the tail: pads get the
        # dtype's maximum (clipping hi/lo to n_valid excludes boundary ties)
        tail = jnp.arange(right_key.shape[0]) >= n_valid
        if right_key.dtype == jnp.bool_:
            tail_value = True
        else:
            tail_value = np.iinfo(np.dtype(str(right_key.dtype))).max
        rs = jnp.where(tail, tail_value, jnp.take(right_key, perm))

        lo = jnp.searchsorted(rs, left_key, side="left")
        hi = jnp.searchsorted(rs, left_key, side="right")
        lo = jnp.minimum(lo, n_valid)
        hi = jnp.minimum(hi, n_valid)
        counts = hi - lo
        l_valid = jnp.arange(left_key.shape[0]) < n_left
        counts = jnp.where(l_valid, counts, 0)
        total_inner = jnp.sum(counts)
        total_left = jnp.sum(jnp.where(l_valid, jnp.maximum(counts, 1), 0))
        return perm, lo, counts, total_inner, total_left

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_expand(p_out: int, n_left: int, how_left: bool):
    import jax
    import jax.numpy as jnp

    def fn(perm, lo, counts):
        l_valid = jnp.arange(counts.shape[0]) < n_left
        if how_left:
            emit = jnp.where(l_valid, jnp.maximum(counts, 1), 0)
        else:
            emit = counts
        ends = jnp.cumsum(emit)
        out_pos = jnp.arange(p_out, dtype=jnp.int64)
        # which left row produced output row j (output pads land on the last
        # left row and are sliced off logically)
        left_pos = jnp.searchsorted(ends, out_pos, side="right")
        left_pos = jnp.minimum(left_pos, counts.shape[0] - 1)
        starts = ends - emit
        within = out_pos - jnp.take(starts, left_pos)
        sorted_right_pos = jnp.take(lo, left_pos) + within
        sorted_right_pos = jnp.clip(sorted_right_pos, 0, perm.shape[0] - 1)
        right_pos = jnp.take(perm, sorted_right_pos)
        if how_left:
            has_match = jnp.take(counts, left_pos) > 0
            right_pos = jnp.where(has_match, right_pos, -1)
        return left_pos, right_pos

    return jax.jit(fn)


def sort_merge_positions(
    left_key: Any,
    right_key: Any,
    n_left: int,
    n_right: int,
    how: str = "inner",
) -> Tuple[Any, Any, int]:
    """(left_positions, right_positions, n_out, has_miss) for the joined rows.

    Positions are padded device arrays; ``right_positions == -1`` marks a
    left-join miss.  Exactly one host sync (the inner/left output counts,
    from which ``has_miss`` is derived).
    """
    import jax
    import jax.numpy as jnp

    from modin_tpu.ops.structural import pad_len

    perm, lo, counts, total_inner, total_left = _jit_match_bounds(
        int(n_left), int(n_right)
    )(left_key, right_key)
    inner_count, left_count = (
        int(v) for v in jax.device_get((total_inner, total_left))
    )
    n_out = left_count if how == "left" else inner_count
    # a left-join miss exists iff some left row matched nothing
    has_miss = how == "left" and left_count > inner_count
    p_out = pad_len(max(n_out, 1))
    if n_out == 0:
        zeros = jnp.zeros(p_out, jnp.int64)
        return zeros, jnp.full(p_out, -1, jnp.int64), 0, False
    left_pos, right_pos = _jit_expand(p_out, int(n_left), how == "left")(
        perm, lo, counts
    )
    return left_pos, right_pos, n_out, has_miss


@functools.lru_cache(maxsize=None)
def _jit_gather_with_null(n_cols: int):
    """Gather right-side columns by position; position -1 becomes NaN/NaT."""
    import jax
    import jax.numpy as jnp

    def fn(cols: Tuple, positions):
        safe = jnp.where(positions >= 0, positions, 0)
        out = []
        for c in cols:
            vals = jnp.take(c, safe, axis=0)
            if jnp.issubdtype(c.dtype, jnp.floating):
                vals = jnp.where(positions >= 0, vals, jnp.nan)
            else:
                # int/bool/datetime columns get the int64-min NaT sentinel;
                # the caller promotes dtypes when misses exist
                vals = jnp.where(
                    positions >= 0, vals, _null_sentinel(c.dtype)
                )
            out.append(vals)
        return tuple(out)

    return jax.jit(fn)


def _null_sentinel(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return False
    return np.iinfo(np.dtype(str(dtype))).min


def gather_right_columns(cols, positions) -> list:
    """Gather right columns for the join output (missing -> null sentinel)."""
    if not cols:
        return []
    return list(_jit_gather_with_null(len(cols))(tuple(cols), positions))
