"""Device sort-merge join.

TPU-native replacement for the reference's merge implementations
(modin/core/storage_formats/pandas/merge.py:39 range_partitioning_merge /
:104 row_axis_merge): instead of broadcasting the right frame to every left
partition or shuffling both frames through the object store, the join runs as
one device program family:

1. stable-sort the right keys (keeps pandas' original-order-within-ties);
2. binary-search every left key against the sorted right keys (lo/hi bounds);
3. one host sync for the output row count (data-dependent shape);
4. expand matches with a searchsorted-over-offsets trick and gather both
   sides' columns by position.

Matches pandas ``merge`` row order for ``sort=False``: left order, and
right-side ties in right's original order.  Float keys use an IEEE
total-order int mapping so pandas' merge equality holds exactly
(-0.0 == 0.0; every NaN key matches every other NaN key).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import numpy as np


from modin_tpu.ops.structural import float_total_order as _total_order


from modin_tpu.parallel.engine import materialize as _engine_materialize


@functools.lru_cache(maxsize=None)
def _jit_composite_codes(n_levels: int, float_flags: Tuple[bool, ...]):
    """Fold multi-column join keys into one int64 code per side.

    Per level, both sides' keys rank against the sorted concatenation of the
    two sides (equal values get equal ranks, order is preserved), then the
    running composite re-ranks after each fold so the code stays < |L|+|R|
    and the product never overflows int64.
    """
    import jax
    import jax.numpy as jnp

    def rank_pair(lv, rv):
        allv = jnp.concatenate([lv, rv])
        s = jnp.sort(allv)
        return (
            jnp.searchsorted(s, lv, side="left"),
            jnp.searchsorted(s, rv, side="left"),
        )

    def fn(lkeys: Tuple, rkeys: Tuple):
        total = lkeys[0].shape[0] + rkeys[0].shape[0]
        lc = rc = None
        for lv, rv, is_f in zip(lkeys, rkeys, float_flags):
            if is_f:
                lv, rv = _total_order(lv), _total_order(rv)
            else:
                lv, rv = lv.astype(jnp.int64), rv.astype(jnp.int64)
            l_i, r_i = rank_pair(lv, rv)
            if lc is None:
                lc, rc = l_i, r_i
            else:
                lc, rc = rank_pair(lc * total + l_i, rc * total + r_i)
        return lc, rc

    return jax.jit(fn)


def composite_key_codes(left_keys: list, right_keys: list) -> Tuple[Any, Any]:
    """(left_code, right_code): int64 arrays that compare equal exactly when
    the key tuples compare equal under pandas merge semantics."""
    import jax.numpy as jnp

    float_flags = tuple(
        bool(jnp.issubdtype(k.dtype, jnp.floating)) for k in left_keys
    )
    fn = _jit_composite_codes(len(left_keys), float_flags)
    return fn(tuple(left_keys), tuple(right_keys))


@functools.lru_cache(maxsize=None)
def _jit_match_bounds(n_left: int, n_right: int):
    import jax
    import jax.numpy as jnp

    def fn(left_key, right_key):
        if jnp.issubdtype(right_key.dtype, jnp.floating):
            left_key = _total_order(left_key)
            right_key = _total_order(right_key)
        # pads must sort to the tail and never match
        r_bad = jnp.arange(right_key.shape[0]) >= n_right
        perm0 = jnp.argsort(right_key, stable=True)
        bad_sorted = jnp.take(r_bad, perm0)
        perm = jnp.take(perm0, jnp.argsort(bad_sorted, stable=True))
        n_valid = jnp.sum(~r_bad)
        # the search array must stay monotone through the tail: pads get the
        # dtype's maximum (clipping hi/lo to n_valid excludes boundary ties)
        tail = jnp.arange(right_key.shape[0]) >= n_valid
        if right_key.dtype == jnp.bool_:
            tail_value = True
        else:
            tail_value = np.iinfo(np.dtype(str(right_key.dtype))).max
        rs = jnp.where(tail, tail_value, jnp.take(right_key, perm))

        lo = jnp.searchsorted(rs, left_key, side="left")
        hi = jnp.searchsorted(rs, left_key, side="right")
        lo = jnp.minimum(lo, n_valid)
        hi = jnp.minimum(hi, n_valid)
        counts = hi - lo
        l_valid = jnp.arange(left_key.shape[0]) < n_left
        counts = jnp.where(l_valid, counts, 0)
        total_inner = jnp.sum(counts)
        total_left = jnp.sum(jnp.where(l_valid, jnp.maximum(counts, 1), 0))
        return perm, lo, counts, total_inner, total_left

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_expand(p_out: int, n_left: int, how_left: bool):
    import jax
    import jax.numpy as jnp

    def fn(perm, lo, counts):
        l_valid = jnp.arange(counts.shape[0]) < n_left
        if how_left:
            emit = jnp.where(l_valid, jnp.maximum(counts, 1), 0)
        else:
            emit = counts
        ends = jnp.cumsum(emit)
        out_pos = jnp.arange(p_out, dtype=jnp.int64)
        # which left row produced output row j (output pads land on the last
        # left row and are sliced off logically)
        left_pos = jnp.searchsorted(ends, out_pos, side="right")
        left_pos = jnp.minimum(left_pos, counts.shape[0] - 1)
        starts = ends - emit
        within = out_pos - jnp.take(starts, left_pos)
        sorted_right_pos = jnp.take(lo, left_pos) + within
        sorted_right_pos = jnp.clip(sorted_right_pos, 0, perm.shape[0] - 1)
        right_pos = jnp.take(perm, sorted_right_pos)
        if how_left:
            has_match = jnp.take(counts, left_pos) > 0
            right_pos = jnp.where(has_match, right_pos, -1)
        return left_pos, right_pos

    return jax.jit(fn)


def sort_merge_positions(
    left_key: Any,
    right_key: Any,
    n_left: int,
    n_right: int,
    how: str = "inner",
) -> Tuple[Any, Any, int]:
    """(left_positions, right_positions, n_out, has_miss) for the joined rows.

    Positions are padded device arrays; ``right_positions == -1`` marks a
    left-join miss.  Exactly one host sync (the inner/left output counts,
    from which ``has_miss`` is derived).
    """
    import jax
    import jax.numpy as jnp

    from modin_tpu.ops.structural import pad_len

    perm, lo, counts, total_inner, total_left = _jit_match_bounds(
        int(n_left), int(n_right)
    )(left_key, right_key)
    inner_count, left_count = (
        int(v) for v in _engine_materialize((total_inner, total_left))
    )
    n_out = left_count if how == "left" else inner_count
    # a left-join miss exists iff some left row matched nothing
    has_miss = how == "left" and left_count > inner_count
    p_out = pad_len(max(n_out, 1))
    if n_out == 0:
        zeros = jnp.zeros(p_out, jnp.int64)
        return zeros, jnp.full(p_out, -1, jnp.int64), 0, False
    left_pos, right_pos = _jit_expand(p_out, int(n_left), how == "left")(
        perm, lo, counts
    )
    return left_pos, right_pos, n_out, has_miss


def merge_positions(
    left_key: Any,
    right_key: Any,
    n_left: int,
    n_right: int,
    how: str = "inner",
) -> Tuple[Any, Any, int, bool]:
    """Router-dispatched match positions (graftmesh).

    When ``decide_layout`` predicts the collective pays at this (rows, mesh
    shape), the right-side sort runs through the all_to_all shuffle
    (ops/spmd.py) — bit-identical positions, different substrate cost; the
    local sort-merge kernel is the fallback for single-shard meshes, small
    frames, and pathological key skew.
    """
    from modin_tpu.ops import router

    if router.decide_layout("merge", int(n_right), payload_cols=1) == "sharded":
        from modin_tpu.ops import spmd

        result = spmd.sharded_merge_positions(
            left_key, right_key, int(n_left), int(n_right), how
        )
        if result is not None:
            return result
    return sort_merge_positions(left_key, right_key, n_left, n_right, how)


@functools.lru_cache(maxsize=None)
def _jit_right_only(p_right: int, n_right: int, n_out: int):
    """Right rows untouched by a left join: (order, count).

    ``order`` sorts unmatched valid right positions first, in original right
    order (pandas outer-merge appendix order); ``count`` is how many.
    """
    import jax
    import jax.numpy as jnp

    def fn(right_pos):
        valid_out = jnp.arange(right_pos.shape[0]) < n_out
        hit = valid_out & (right_pos >= 0)
        safe = jnp.where(hit, right_pos, 0)
        flags = jnp.zeros(p_right, bool).at[safe].set(True)
        # row 0 may have been set by masked-out pads pointing at 0
        flags = flags.at[0].set(jnp.any(hit & (right_pos == 0)))
        valid_r = jnp.arange(p_right) < n_right
        unmatched = (~flags) & valid_r
        m = jnp.sum(unmatched)
        order = jnp.argsort(~unmatched, stable=True)
        return order, m

    return jax.jit(fn)


def right_only_positions(right_pos, p_right: int, n_right: int, n_out: int):
    """(positions, count) of right rows missing from the left-join output."""
    import jax

    order, m = _jit_right_only(int(p_right), int(n_right), int(n_out))(right_pos)
    return order, int(_engine_materialize(m))


@functools.lru_cache(maxsize=None)
def _jit_gather_with_null(n_cols: int):
    """Gather right-side columns by position; position -1 becomes NaN/NaT."""
    import jax
    import jax.numpy as jnp

    def fn(cols: Tuple, positions):
        safe = jnp.where(positions >= 0, positions, 0)
        out = []
        for c in cols:
            vals = jnp.take(c, safe, axis=0)
            if jnp.issubdtype(c.dtype, jnp.floating):
                vals = jnp.where(positions >= 0, vals, jnp.nan)
            else:
                # int/bool/datetime columns get the int64-min NaT sentinel;
                # the caller promotes dtypes when misses exist
                vals = jnp.where(
                    positions >= 0, vals, _null_sentinel(c.dtype)
                )
            out.append(vals)
        return tuple(out)

    return jax.jit(fn)


def _null_sentinel(dtype):
    import jax.numpy as jnp

    if dtype == jnp.bool_:
        return False
    return np.iinfo(np.dtype(str(dtype))).min


def gather_right_columns(cols, positions) -> list:
    """Gather right columns for the join output (missing -> null sentinel)."""
    if not cols:
        return []
    return list(_jit_gather_with_null(len(cols))(tuple(cols), positions))


@functools.lru_cache(maxsize=None)
def _jit_duplicated(n_cols: int, float_flags: Tuple[bool, ...], n: int, keep: Any):
    """Row-duplicate mask over one frame's key columns.

    The same rank-fold as the join codes, against a single frame: per
    column rank via sorted searchsorted (floats through the IEEE total
    order, so every NaN compares equal — pandas duplicated treats NaNs as
    duplicates of each other), composite re-ranked per fold to stay in
    int64.  A stable argsort of the codes groups equal rows with original
    order preserved; first/last flags inside each group give every keep
    variant, scattered back to row positions."""
    import jax
    import jax.numpy as jnp

    def rank(v):
        s = jnp.sort(v)
        return jnp.searchsorted(s, v, side="left")

    def fn(cols: Tuple):
        P = cols[0].shape[0]
        valid = jnp.arange(P) < n
        code = None
        for c, is_f in zip(cols, float_flags):
            v = _total_order(c) if is_f else c.astype(jnp.int64)
            r = rank(v)
            code = r if code is None else rank(code * jnp.int64(P) + r)
        code = jnp.where(valid, code, jnp.int64(-1))  # pads group below
        order = jnp.argsort(code, stable=True)
        sc = jnp.take(code, order)
        change = sc[1:] != sc[:-1]
        first = jnp.concatenate([jnp.ones(1, bool), change])
        last = jnp.concatenate([change, jnp.ones(1, bool)])
        if keep == "first":
            dup_sorted = ~first
        elif keep == "last":
            dup_sorted = ~last
        else:  # keep=False: every member of a >1 group
            dup_sorted = ~(first & last)
        return jnp.zeros(P, bool).at[order].set(dup_sorted)

    return jax.jit(fn)


def duplicated_mask(cols: list, n: int, keep: Any):
    """Boolean duplicate-row mask (pandas ``duplicated`` semantics) over
    padded device key columns."""
    import jax.numpy as jnp

    float_flags = tuple(
        bool(jnp.issubdtype(c.dtype, jnp.floating)) for c in cols
    )
    fn = _jit_duplicated(len(cols), float_flags, int(n), keep)
    return fn(tuple(cols))
