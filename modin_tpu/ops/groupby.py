"""Groupby reductions as device segment operations (pad-aware).

TPU-native replacement for the reference's GroupByReduce map+reduce pair
(modin/core/dataframe/algebra/groupby.py:33, partition_manager.py:303): the
per-block local groupby + cross-block regroup collapses into factorize (code
assignment) + ``jax.ops.segment_*`` in one compiled program.  On a sharded
array XLA emits per-shard segment partials + a psum over ICI — exactly the
map/tree-reduce structure of the reference, compiled instead of scheduled.

Key factorization strategies:
- int-like keys with a small value range     -> direct offset codes (no sort)
- anything else                              -> jnp.unique (device sort, one
                                                host sync for the group count)

Pad rows (positions >= n) are always routed to the overflow bucket
``num_groups`` and sliced off after aggregation; NaN keys share that bucket
when ``dropna=True``.
"""

from __future__ import annotations

import functools
from types import MappingProxyType
from typing import Any, List, Optional, Tuple

import numpy as np

# aggregations expressible as segment reductions
SEGMENT_AGGS = {
    "sum", "count", "mean", "min", "max", "prod", "size", "var", "std",
    "any", "all", "sem",
}

# order-statistic aggregations: device sort within groups (the reference
# routes these through range-partitioning + per-shard pandas,
# modin/core/dataframe/pandas/dataframe/dataframe.py:4163; on TPU a
# lexsort + gather keeps the whole thing on device)
ORDER_AGGS = {"median", "quantile", "nunique", "first", "last"}

_RANGE_LIMIT = 1 << 22  # max direct-range width before falling back to unique


from modin_tpu.parallel.engine import materialize as _engine_materialize


class _TooManyGroups(Exception):
    pass


def _slice_pad(r, n_groups: int, p_out: int):
    """Slice off the overflow bucket and pad the result to the shard multiple."""
    import jax.numpy as jnp

    r = r[:n_groups]
    if p_out > n_groups:
        r = jnp.concatenate([r, jnp.zeros(p_out - n_groups, r.dtype)])
    return r


@functools.lru_cache(maxsize=None)
def _jit_key_minmax(n: int):
    import jax
    import jax.numpy as jnp

    def fn(k):
        valid = jnp.arange(k.shape[0]) < n
        kmin = jnp.min(jnp.where(valid, k, np.iinfo(np.int64).max))
        kmax = jnp.max(jnp.where(valid, k, np.iinfo(np.int64).min))
        return kmin, kmax

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_range_ids(n: int, width: int):
    # kmin is a traced operand: recompiles key on (n, width) only
    import jax
    import jax.numpy as jnp

    def fn(k, kmin):
        valid = jnp.arange(k.shape[0]) < n
        return jnp.where(valid, jnp.clip(k - kmin, 0, width), width)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_scatter_counts(width: int):
    import jax
    import jax.numpy as jnp

    def fn(ids):
        return jnp.zeros(width + 1, jnp.int64).at[ids].add(1)[:width]

    return jax.jit(fn)


def _count_ids(ids, width: int):
    """Histogram of ids in [0, width); overflow id == width is dropped.

    On TPU uses the pallas VPU kernel (XLA's scatter-add serializes there);
    elsewhere the scatter path.
    """
    from modin_tpu.ops.pallas.groupby_kernels import (
        bincount_supported,
        pallas_bincount,
    )

    if bincount_supported(ids, width):
        return pallas_bincount(ids, width)
    return _jit_scatter_counts(width)(ids)


@functools.lru_cache(maxsize=None)
def _jit_range_codes(n: int, n_groups: int):
    import jax
    import jax.numpy as jnp

    def fn(k, kmin, remap):
        valid = jnp.arange(k.shape[0]) < n
        width = remap.shape[0]
        safe = jnp.where(valid, jnp.clip(k - kmin, 0, width - 1), 0)
        return jnp.where(valid, jnp.take(remap, safe), n_groups)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_float_prep(n: int):
    import jax
    import jax.numpy as jnp

    def fn(k):
        valid = jnp.arange(k.shape[0]) < n
        has_nan = jnp.any(jnp.isnan(k) & valid)
        return jnp.where(valid, k, jnp.nan), has_nan

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_int_prep(n: int):
    import jax
    import jax.numpy as jnp

    def fn(k):
        valid = jnp.arange(k.shape[0]) < n
        return jnp.where(valid, k, k[0])

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_mask_codes(n: int, overflow: int):
    import jax
    import jax.numpy as jnp

    def fn(codes):
        valid = jnp.arange(codes.shape[0]) < n
        return jnp.where(valid, codes, overflow)

    return jax.jit(fn)


# Bounded memo of key factorizations.  Grouping by the same key columns
# repeatedly (df.groupby(k).sum() then .mean() ...) re-derives identical
# codes; the cache keys on the device arrays' identity so any new/modified
# column misses.  Strong refs to the key arrays keep ids stable; the size
# bound caps pinned device memory.
_FACTORIZE_CACHE: List[
    Tuple[Tuple, List[Any], Tuple[Any, int, List[np.ndarray], Any]]
] = []
_FACTORIZE_CACHE_MAX = 8


def clear_factorize_cache() -> None:
    """Drop all memoized key factorizations (cold-path benchmarking and
    tests: a warm memo turns groupby timings into cache-hit lookups)."""
    _FACTORIZE_CACHE.clear()


def factorize_keys_cached(
    key_cols: List[Any], n: int, dropna: bool = True
) -> Tuple[Any, int, List[np.ndarray], Any]:
    """Memoized :func:`factorize_keys` (same-identity key columns hit)."""
    cache_key = (tuple(id(k) for k in key_cols), int(n), bool(dropna))
    for entry_key, _refs, result in _FACTORIZE_CACHE:
        if entry_key == cache_key:
            return result
    result = factorize_keys(key_cols, n, dropna)
    _FACTORIZE_CACHE.append((cache_key, list(key_cols), result))
    if len(_FACTORIZE_CACHE) > _FACTORIZE_CACHE_MAX:
        _FACTORIZE_CACHE.pop(0)
    return result


def factorize_keys(
    key_cols: List[Any], n: int, dropna: bool = True
) -> Tuple[Any, int, List[np.ndarray], Any]:
    """Device factorization of one or more padded key columns (logical len n).

    Returns (codes, num_groups, group_key_arrays_host, sizes): ``codes`` maps
    each row to [0, num_groups), with pads (and NaN keys when dropna) mapped
    to ``num_groups``.  Group key values are host-side, sorted ascending
    (pandas sort=True order); a NaN group, when kept, is last.  ``sizes`` is
    a host int64 array of per-group row counts where the factorization
    computed one anyway (range/multi-key paths), else None — callers reuse it
    so ``size``/``mean`` aggregations skip a histogram pass.
    """
    import jax
    import jax.numpy as jnp

    if len(key_cols) == 1:
        k = key_cols[0]
        kdt = k.dtype
        if jnp.issubdtype(kdt, jnp.integer) or kdt == jnp.bool_:
            k64 = k.astype(jnp.int64)
            kmin, kmax = (int(v) for v in _engine_materialize(_jit_key_minmax(n)(k64)))
            width = kmax - kmin + 1
            if width <= _RANGE_LIMIT:
                ids = _jit_range_ids(n, width)(k64, jnp.int64(kmin))
                counts = np.asarray(_engine_materialize(_count_ids(ids, width)))
                present = np.nonzero(counts)[0]
                remap = np.full(width, len(present), dtype=np.int64)
                remap[present] = np.arange(len(present))
                codes = _jit_range_codes(n, len(present))(
                    k64, jnp.int64(kmin), jnp.asarray(remap)
                )
                uniques = (present + kmin).astype(np.int64)
                if kdt == jnp.bool_:
                    uniques = uniques.astype(bool)
                else:
                    uniques = uniques.astype(np.dtype(str(kdt)))
                return codes, len(present), [uniques], counts[present]
            # large-range ints: unique path with pads mapped to k[0]
            k_prepped = _jit_int_prep(n)(k64)
            uniques, codes = jnp.unique(k_prepped, return_inverse=True)
            n_groups = int(uniques.shape[0])
            codes = _jit_mask_codes(n, n_groups)(codes)
            uniques_host = np.asarray(_engine_materialize(uniques)).astype(np.dtype(str(kdt)))
            return codes, n_groups, [uniques_host], None
        if jnp.issubdtype(kdt, jnp.floating):
            k_prepped, has_nan = _jit_float_prep(n)(k)
            # the nan flag is a device scalar: fetch it through the seam so a
            # device failure here classifies/retries instead of surfacing raw
            has_nan = bool(_engine_materialize(has_nan))
            uniques, codes = jnp.unique(k_prepped, return_inverse=True)
            uniques_host = np.asarray(_engine_materialize(uniques))
            n_valid = int(np.sum(~np.isnan(uniques_host)))
            # jnp.unique sorts NaN last; every NaN row (and pad) got a code
            # >= n_valid — clamp them to one bucket
            if dropna or not has_nan:
                codes = _jit_clamp_codes(n, n_valid)(codes)
                return codes, n_valid, [uniques_host[:n_valid]], None
            # keep the NaN group (real NaNs), pads -> overflow
            codes = _jit_nan_group_codes(n, n_valid)(codes, k)
            return codes, n_valid + 1, [
                np.concatenate([uniques_host[:n_valid], [np.nan]])
            ], None
        raise _TooManyGroups()

    # multi-key: combine per-level codes into one composite code
    level_codes = []
    level_uniques = []
    n_groups_each = []
    for k in key_cols:
        codes_i, n_i, uniques_i, _sizes_i = factorize_keys([k], n, dropna=dropna)
        level_codes.append(codes_i)
        level_uniques.append(uniques_i[0])
        n_groups_each.append(n_i)
    total = int(np.prod(n_groups_each))
    if total > _RANGE_LIMIT * 4:
        raise _TooManyGroups()
    composite = _jit_composite(tuple(n_groups_each), n, total)(tuple(level_codes))
    counts = np.asarray(_engine_materialize(_jit_bincount(total)(composite)))
    present = np.nonzero(counts)[0]
    remap = np.full(total + 1, len(present), dtype=np.int64)
    remap[present] = np.arange(len(present))
    import jax.numpy as jnp2

    codes = _jit_remap(len(present))(composite, jnp2.asarray(remap))
    keys_out: List[np.ndarray] = []
    rem = present.copy()
    for uniques_i, n_i in zip(reversed(level_uniques), reversed(n_groups_each)):
        keys_out.append(np.asarray(uniques_i)[rem % n_i])
        rem = rem // n_i
    keys_out.reverse()
    return codes, len(present), keys_out, counts[present]


@functools.lru_cache(maxsize=None)
def _jit_clamp_codes(n: int, n_valid: int):
    import jax
    import jax.numpy as jnp

    def fn(codes):
        valid = jnp.arange(codes.shape[0]) < n
        return jnp.where(valid, jnp.minimum(codes, n_valid), n_valid)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_nan_group_codes(n: int, n_valid: int):
    import jax
    import jax.numpy as jnp

    def fn(codes, k):
        valid = jnp.arange(codes.shape[0]) < n
        is_nan = jnp.isnan(k) & valid
        clamped = jnp.minimum(codes, n_valid + 1)
        out = jnp.where(is_nan, n_valid, clamped)
        return jnp.where(valid, out, n_valid + 1)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_composite(n_groups_each: Tuple[int, ...], n: int, total: int):
    import jax
    import jax.numpy as jnp

    def fn(level_codes: Tuple):
        valid = jnp.arange(level_codes[0].shape[0]) < n
        # a row is valid only if every level code is in range
        in_range = valid
        for codes_i, n_i in zip(level_codes, n_groups_each):
            in_range = in_range & (codes_i < n_i)
        composite = jnp.zeros(level_codes[0].shape, jnp.int64)
        for codes_i, n_i in zip(level_codes, n_groups_each):
            composite = composite * n_i + jnp.minimum(codes_i, n_i - 1)
        return jnp.where(in_range, composite, total)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_bincount(total: int):
    import jax
    import jax.numpy as jnp

    def fn(composite):
        return jnp.zeros(total + 1, jnp.int64).at[composite].add(1)[:total]

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_remap(n_present: int):
    import jax
    import jax.numpy as jnp

    def fn(composite, remap):
        return jnp.take(remap, composite)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_segment_agg(
    agg: str, n_cols: int, num_segments: int, ddof: int, p_out: int,
    adaptive: bool = False,
    has_sizes: bool = False,
):
    """One jit computing the aggregation for every value column; results are
    sliced to the real group count and padded to the shard multiple.

    ``adaptive`` (single-shard meshes only — lax.cond over sharded operands
    is unsafe under SPMD) runs the unmasked segment sum first and falls into
    the NaN-masked form only when the result shows a NaN occurred, sharing
    one group-sizes histogram across clean columns.  With ``has_sizes`` the
    histogram arrives precomputed (factorization by-product) as an operand.
    """
    import jax
    import jax.numpy as jnp

    n_groups = num_segments - 1

    def finish(r):
        return _slice_pad(r, n_groups, p_out)

    def seg_adaptive(c, codes, sizes):
        import jax.lax as lax

        ns = num_segments
        if agg == "count":
            # no value aggregation needed: probe NaNs directly (a segment
            # scatter just for the probe would cost more than it saves)
            has_nan = jnp.any(jnp.isnan(c) & (codes < n_groups))
            s_raw = None
        else:
            s_raw = jax.ops.segment_sum(c, codes, num_segments=ns)
            has_nan = jnp.isnan(jnp.sum(s_raw[:n_groups]))

        def dirty():
            if agg == "count":
                vcnt = jax.ops.segment_sum(
                    (~jnp.isnan(c)).astype(jnp.int32), codes, num_segments=ns
                )
                return vcnt.astype(jnp.int64)
            x = jnp.where(jnp.isnan(c), 0, c)
            s = jax.ops.segment_sum(x, codes, num_segments=ns)
            if agg == "sum":
                return s
            vcnt = jax.ops.segment_sum(
                (~jnp.isnan(c)).astype(jnp.int32), codes, num_segments=ns
            )
            return s / vcnt  # mean

        def clean():
            if agg == "sum":
                return s_raw
            if agg == "count":
                return sizes
            # cast sizes to the SUM dtype: cond branches must type-match and
            # the masked path keeps float32 means float32
            return s_raw / sizes.astype(s_raw.dtype)

        return finish(lax.cond(has_nan, dirty, clean))

    def seg(c, codes):
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        ns = num_segments
        if agg in ("sum", "mean", "var", "std", "sem"):
            x = jnp.where(jnp.isnan(c), 0, c) if is_f else c
            s = jax.ops.segment_sum(x, codes, num_segments=ns)
            if agg == "sum":
                return s
            valid = (~jnp.isnan(c)).astype(jnp.int64) if is_f else jnp.ones(c.shape, jnp.int64)
            ncnt = jax.ops.segment_sum(valid, codes, num_segments=ns)
            # divide in the sum's dtype: float32 means stay float32 (pandas)
            mean = s / (ncnt.astype(s.dtype) if is_f else ncnt)
            if agg == "mean":
                return mean
            # two-pass centered variance: gathering the group mean back per row
            # avoids the catastrophic cancellation of E[x^2]-E[x]^2
            d = x.astype(jnp.float64) - jnp.take(mean, codes)
            d = jnp.where(valid.astype(bool), d, 0.0)
            s2 = jax.ops.segment_sum(d * d, codes, num_segments=ns)
            var = s2 / jnp.maximum(ncnt - ddof, 1)
            var = jnp.where(ncnt - ddof > 0, var, jnp.nan)
            if agg == "var":
                return var
            if agg == "std":
                return jnp.sqrt(var)
            return jnp.sqrt(var / ncnt)  # sem
        if agg == "count":
            valid = (~jnp.isnan(c)).astype(jnp.int64) if is_f else jnp.ones(c.shape, jnp.int64)
            return jax.ops.segment_sum(valid, codes, num_segments=ns)
        if agg == "prod":
            x = jnp.where(jnp.isnan(c), 1, c) if is_f else c
            return jax.ops.segment_prod(x, codes, num_segments=ns)
        if agg == "min":
            x = jnp.where(jnp.isnan(c), jnp.inf, c) if is_f else c
            r = jax.ops.segment_min(x, codes, num_segments=ns)
            return jnp.where(jnp.isposinf(r), jnp.nan, r) if is_f else r
        if agg == "max":
            x = jnp.where(jnp.isnan(c), -jnp.inf, c) if is_f else c
            r = jax.ops.segment_max(x, codes, num_segments=ns)
            return jnp.where(jnp.isneginf(r), jnp.nan, r) if is_f else r
        if agg == "any":
            x = jnp.where(jnp.isnan(c), False, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
            return jax.ops.segment_max(x.astype(jnp.int32), codes, num_segments=ns).astype(bool)
        if agg == "all":
            x = jnp.where(jnp.isnan(c), True, c != 0) if is_f else (c != 0 if c.dtype != jnp.bool_ else c)
            return jax.ops.segment_min(x.astype(jnp.int32), codes, num_segments=ns).astype(bool)
        raise ValueError(agg)

    def fn(cols: Tuple, codes, sizes_in=None):
        sizes = None
        if adaptive and agg in ("sum", "mean", "count"):
            if has_sizes:
                sizes = sizes_in
            else:
                sizes = jax.ops.segment_sum(
                    jnp.ones(codes.shape, jnp.int64), codes,
                    num_segments=num_segments,
                )
        out = []
        for c in cols:
            if sizes is not None and jnp.issubdtype(c.dtype, jnp.floating):
                out.append(seg_adaptive(c, codes, sizes))
            elif sizes is not None and agg == "count":
                out.append(finish(sizes))
            else:
                out.append(finish(seg(c, codes)))
        return tuple(out)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_pad_to(p_out: int):
    import jax
    import jax.numpy as jnp

    def fn(r):
        if r.shape[0] < p_out:
            return jnp.concatenate([r, jnp.zeros(p_out - r.shape[0], r.dtype)])
        return r

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_segment_size(num_segments: int, p_out: int):
    import jax
    import jax.numpy as jnp

    n_groups = num_segments - 1

    def fn(codes):
        r = jax.ops.segment_sum(
            jnp.ones(codes.shape, jnp.int64), codes, num_segments=num_segments
        )[:n_groups]
        if p_out > n_groups:
            r = jnp.concatenate([r, jnp.zeros(p_out - n_groups, r.dtype)])
        return r

    return jax.jit(fn)


# Above this many groups the masked-scan kernel's O(n*G) work loses to the
# scatter-based segment ops; below it, the scan avoids TPU's slow scatters
# (measured: segment_sum ~1s vs masked reduce ~50ms at 1e7 rows, G=101).
_MASKED_SCAN_MAX_GROUPS = 1024
_SCAN_CHUNK = 65536
_FORCE_KERNEL = None  # test hook: "masked_scan" | "segment" | None


@functools.lru_cache(maxsize=None)
def _jit_masked_scan_agg(agg: str, n_cols: int, num_segments: int, ddof: int, p_out: int, chunk: int):
    """Chunked masked-reduce aggregation: one lax.scan over row chunks, each
    step reducing a [chunk, G+1] one-hot mask on the VPU (no scatters)."""
    import jax
    import jax.numpy as jnp

    G = num_segments  # includes the overflow bucket
    n_groups = num_segments - 1

    def fn(cols: Tuple, codes):
        P = codes.shape[0]
        steps = -(-P // chunk)
        pad = steps * chunk - P
        cpad = jnp.concatenate(
            [codes, jnp.full(pad, n_groups, codes.dtype)]
        ).reshape(steps, chunk)
        xpads = tuple(
            jnp.concatenate([c, jnp.zeros(pad, c.dtype)]).reshape(steps, chunk)
            for c in cols
        )
        group_ids = jnp.arange(G)

        def body(carry, inp):
            cc = inp[0]
            oh = cc[:, None] == group_ids[None, :]  # [chunk, G] bool
            new_carry = []
            ci = 0
            for i in range(n_cols):
                xc = inp[1 + i]
                is_f = jnp.issubdtype(xc.dtype, jnp.floating)
                nanm = jnp.isnan(xc) if is_f else None
                if agg in ("sum", "mean"):
                    xz = jnp.where(nanm, 0, xc) if is_f else xc
                    s = carry[ci] + jnp.sum(
                        jnp.where(oh, xz[:, None], 0), axis=0
                    )
                    new_carry.append(s)
                    ci += 1
                    if agg != "sum":
                        v = (~nanm if is_f else jnp.ones(xc.shape, bool))
                        cnt = carry[ci] + jnp.sum(oh & v[:, None], axis=0)
                        new_carry.append(cnt)
                        ci += 1
                elif agg == "count":
                    v = (~nanm if is_f else jnp.ones(xc.shape, bool))
                    cnt = carry[ci] + jnp.sum(oh & v[:, None], axis=0)
                    new_carry.append(cnt)
                    ci += 1
                elif agg == "prod":
                    xz = jnp.where(nanm, 1, xc) if is_f else xc
                    pr = carry[ci] * jnp.prod(
                        jnp.where(oh, xz[:, None], 1), axis=0
                    )
                    new_carry.append(pr)
                    ci += 1
                elif agg == "min":
                    xz = jnp.where(nanm, jnp.inf, xc) if is_f else xc
                    neutral = jnp.inf if is_f else _INT_MAXES[str(xc.dtype)]
                    m = jnp.minimum(
                        carry[ci],
                        jnp.min(jnp.where(oh, xz[:, None], neutral), axis=0),
                    )
                    new_carry.append(m)
                    ci += 1
                elif agg == "max":
                    xz = jnp.where(nanm, -jnp.inf, xc) if is_f else xc
                    neutral = -jnp.inf if is_f else _INT_MINS[str(xc.dtype)]
                    m = jnp.maximum(
                        carry[ci],
                        jnp.max(jnp.where(oh, xz[:, None], neutral), axis=0),
                    )
                    new_carry.append(m)
                    ci += 1
                elif agg in ("any", "all"):
                    if is_f:
                        t = jnp.where(nanm, agg == "all", xc != 0)
                    else:
                        t = xc != 0 if xc.dtype != jnp.bool_ else xc
                    if agg == "any":
                        r = carry[ci] | jnp.any(oh & t[:, None], axis=0)
                    else:
                        r = carry[ci] & jnp.all((~oh) | t[:, None], axis=0)
                    new_carry.append(r)
                    ci += 1
                else:
                    raise ValueError(agg)
            return tuple(new_carry), None

        # build initial carry matching the body's layout
        init = []
        for c in cols:
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            if agg in ("sum", "mean"):
                init.append(jnp.zeros(G, c.dtype))
                if agg != "sum":
                    init.append(jnp.zeros(G, jnp.int64))
            elif agg == "count":
                init.append(jnp.zeros(G, jnp.int64))
            elif agg == "prod":
                init.append(jnp.ones(G, c.dtype))
            elif agg == "min":
                init.append(
                    jnp.full(G, jnp.inf if is_f else _INT_MAXES[str(c.dtype)], c.dtype)
                )
            elif agg == "max":
                init.append(
                    jnp.full(G, -jnp.inf if is_f else _INT_MINS[str(c.dtype)], c.dtype)
                )
            elif agg == "any":
                init.append(jnp.zeros(G, bool))
            elif agg == "all":
                init.append(jnp.ones(G, bool))
        carry, _ = jax.lax.scan(body, tuple(init), (cpad, *xpads))

        # finalize per column
        def finish(r):
            return _slice_pad(r, n_groups, p_out)

        out = []
        ci = 0
        for c in cols:
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            if agg == "sum":
                out.append(finish(carry[ci])); ci += 1
            elif agg == "mean":
                s = carry[ci]; ci += 1
                cnt = carry[ci]; ci += 1
                out.append(finish(s / cnt))
            elif agg == "count":
                out.append(finish(carry[ci])); ci += 1
            elif agg == "min":
                r = carry[ci]; ci += 1
                out.append(finish(jnp.where(jnp.isposinf(r), jnp.nan, r) if is_f else r))
            elif agg == "max":
                r = carry[ci]; ci += 1
                out.append(finish(jnp.where(jnp.isneginf(r), jnp.nan, r) if is_f else r))
            else:
                out.append(finish(carry[ci])); ci += 1
        return tuple(out)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_masked_scan_smc(
    agg: str,
    n_cols: int,
    num_segments: int,
    p_out: int,
    chunk: int,
    adaptive: bool,
    has_sizes: bool,
):
    """sum/mean/count masked-scan with a SHARED group-size histogram.

    The main scan accumulates every column's nan-zeroed sum plus ONE sizes
    histogram (skipped when the factorization by-product arrives as an
    operand).  Per-column valid counts then come for free on clean data:
    int columns always equal the shared sizes; float columns probe NaNs with
    one cheap pass and (``adaptive``, single-shard meshes only — lax.cond
    over sharded operands is unsafe under SPMD) fall into a dedicated
    count-scan only when a NaN actually occurred.  Cuts mean from 2 O(n*G)
    passes per column to 1, and count to a single shared pass.
    """
    import jax
    import jax.numpy as jnp

    G = num_segments
    n_groups = num_segments - 1

    def finish(r):
        return _slice_pad(r, n_groups, p_out)

    def fn(cols: Tuple, codes, sizes_in=None):
        P = codes.shape[0]
        steps = -(-P // chunk)
        pad = steps * chunk - P
        cpad = jnp.concatenate(
            [codes, jnp.full(pad, n_groups, codes.dtype)]
        ).reshape(steps, chunk)
        xpads = tuple(
            jnp.concatenate([c, jnp.zeros(pad, c.dtype)]).reshape(steps, chunk)
            for c in cols
        )
        gid = jnp.arange(G)
        is_float = [bool(jnp.issubdtype(c.dtype, jnp.floating)) for c in cols]

        need_sum = agg in ("sum", "mean")
        # shared histogram wanted whenever some column's count can reuse it
        need_sizes = agg in ("mean", "count") and (
            has_sizes or adaptive or not all(is_float)
        )
        # per-column inline count accumulators (non-adaptive float columns)
        inline_count = [
            agg in ("mean", "count") and f and not adaptive for f in is_float
        ]

        def body(carry, inp):
            cc = inp[0]
            oh = cc[:, None] == gid[None, :]
            new_carry = []
            ci = 0
            for i in range(n_cols):
                xc = inp[1 + i]
                nanm = jnp.isnan(xc) if is_float[i] else None
                if need_sum:
                    xz = jnp.where(nanm, 0, xc) if is_float[i] else xc
                    new_carry.append(
                        carry[ci] + jnp.sum(jnp.where(oh, xz[:, None], 0), axis=0)
                    )
                    ci += 1
                if inline_count[i]:
                    new_carry.append(
                        carry[ci]
                        + jnp.sum(
                            oh & (~nanm)[:, None], axis=0, dtype=jnp.int32
                        )
                    )
                    ci += 1
            if need_sizes and not has_sizes:
                new_carry.append(
                    carry[ci] + jnp.sum(oh, axis=0, dtype=jnp.int32)
                )
                ci += 1
            return tuple(new_carry), None

        init = []
        for i, c in enumerate(cols):
            if need_sum:
                init.append(jnp.zeros(G, c.dtype))
            if inline_count[i]:
                init.append(jnp.zeros(G, jnp.int64))
        if need_sizes and not has_sizes:
            init.append(jnp.zeros(G, jnp.int64))
        carry, _ = jax.lax.scan(body, tuple(init), (cpad, *xpads))

        ci = 0
        sums, counts = [], []
        for i in range(n_cols):
            if need_sum:
                sums.append(carry[ci]); ci += 1
            else:
                sums.append(None)
            if inline_count[i]:
                counts.append(carry[ci]); ci += 1
            else:
                counts.append(None)
        if need_sizes:
            sizes = sizes_in if has_sizes else carry[ci]
        else:
            sizes = None

        def count_scan(xpad_c):
            def cbody(carry, inp):
                cc, xi = inp
                oh = cc[:, None] == gid[None, :]
                return (
                    carry
                    + jnp.sum(
                        oh & (~jnp.isnan(xi))[:, None], axis=0, dtype=jnp.int32
                    ),
                    None,
                )

            out, _ = jax.lax.scan(cbody, jnp.zeros(G, jnp.int64), (cpad, xpad_c))
            return out

        out = []
        for i, c in enumerate(cols):
            if agg == "sum":
                out.append(finish(sums[i]))
                continue
            # resolve the valid count for mean/count
            if not is_float[i]:
                cnt = sizes
            elif counts[i] is not None:
                cnt = counts[i]
            else:
                has_nan = jnp.any(jnp.isnan(c))
                cnt = jax.lax.cond(
                    has_nan,
                    lambda i=i: count_scan(xpads[i]),
                    lambda: sizes.astype(jnp.int64),
                )
            if agg == "count":
                out.append(finish(cnt.astype(jnp.int64)))
            else:  # mean — divide in the sum's dtype so f32 means stay f32
                s = sums[i]
                out.append(finish(s / cnt.astype(s.dtype)))
        return tuple(out)

    return jax.jit(fn)


# read from inside jitted bodies (masked-scan min/max neutrals): immutable so
# tracing can't bake in contents that a later mutation would silently miss
_INT_KINDS = ("int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64")
_INT_MAXES = MappingProxyType(
    {**{k: np.iinfo(k).max for k in _INT_KINDS}, "bool": True}
)
_INT_MINS = MappingProxyType(
    {**{k: np.iinfo(k).min for k in _INT_KINDS}, "bool": False}
)


@functools.lru_cache(maxsize=None)
def _jit_first_position(num_segments: int):
    import jax
    import jax.numpy as jnp

    def fn(codes):
        positions = jnp.arange(codes.shape[0], dtype=jnp.int64)
        return jax.ops.segment_min(positions, codes, num_segments=num_segments)

    return jax.jit(fn)


def groupby_first_position(codes: Any, num_groups: int) -> Any:
    """First row position of each group (pandas' tie order for value_counts).

    Pad rows carry the overflow code, so they land in the sliced-off bucket.
    """
    return _jit_first_position(num_groups + 1)(codes)[:num_groups]


def groupby_reduce(
    agg: str,
    value_cols: List[Any],
    codes: Any,
    num_groups: int,
    n: int,
    ddof: int = 1,
    sizes: Any = None,
) -> List[Any]:
    """Aggregate value columns by group codes; returns device arrays padded to
    the shard multiple with logical length num_groups (the overflow pad/NaN
    bucket is sliced off).

    ``sizes`` (host int64 per-group row counts, a factorization by-product)
    lets ``size`` skip the histogram kernel entirely and feeds the adaptive
    sum/mean/count path its denominator for free.
    """
    import jax
    import jax.numpy as jnp

    from modin_tpu.observability import costs as _costs
    from modin_tpu.ops.structural import pad_host, pad_len

    ns = num_groups + 1
    p_out = pad_len(num_groups)
    if _costs.COST_ON:
        # input leg: value columns + codes carry (P - n) pad rows each;
        # output leg: every result column is padded from num_groups to the
        # shard multiple (plus the sliced-off overflow bucket slot)
        in_padded = sum(
            int(c.shape[0]) * c.dtype.itemsize for c in value_cols
        ) + int(codes.shape[0]) * codes.dtype.itemsize
        in_valid = (
            sum(int(n) * c.dtype.itemsize for c in value_cols)
            + int(n) * codes.dtype.itemsize
        )
        _costs.note_padding("groupby.reduce.rows", in_padded, in_valid)
        out_width = max(len(value_cols), 1)
        _costs.note_padding(
            "groupby.reduce.groups",
            out_width * max(ns, p_out) * 8,
            out_width * num_groups * 8,
        )
    if agg == "size":
        if sizes is not None:
            return [jnp.asarray(pad_host(np.asarray(sizes, np.int64), num_groups))]
        from modin_tpu.ops.pallas.groupby_kernels import (
            bincount_supported,
            pallas_bincount,
        )

        if bincount_supported(codes, num_groups):
            counts = pallas_bincount(codes, num_groups)
            return [_jit_pad_to(p_out)(counts)]
        return [_jit_segment_size(ns, p_out)(codes)]
    on_tpu = next(iter(codes.devices())).platform == "tpu"
    if _FORCE_KERNEL == "masked_scan":
        on_tpu = True
    elif _FORCE_KERNEL == "segment":
        on_tpu = False
    use_masked_scan = (
        on_tpu
        and num_groups <= _MASKED_SCAN_MAX_GROUPS
        # var/std/sem need the two-pass centered form -> segment path
        and agg in ("sum", "count", "mean", "min", "max", "prod", "any", "all")
    )
    from modin_tpu.parallel.mesh import num_row_shards

    if use_masked_scan:
        # TPU scatters serialize badly; the masked scan keeps the work on the VPU
        if agg in ("sum", "mean", "count"):
            scan_adaptive = num_row_shards() == 1
            scan_has_sizes = sizes is not None and agg in ("mean", "count")
            fn = _jit_masked_scan_smc(
                agg, len(value_cols), ns, p_out, _SCAN_CHUNK,
                scan_adaptive, scan_has_sizes,
            )
            if scan_has_sizes:
                sizes_dev = jnp.asarray(
                    np.append(np.asarray(sizes, np.int64), 1)
                )
                return list(fn(tuple(value_cols), codes, sizes_dev))
            return list(fn(tuple(value_cols), codes))
        fn = _jit_masked_scan_agg(agg, len(value_cols), ns, int(ddof), p_out, _SCAN_CHUNK)
        return list(fn(tuple(value_cols), codes))

    adaptive = num_row_shards() == 1
    has_sizes = (
        adaptive and sizes is not None and agg in ("sum", "mean", "count")
    )
    fn = _jit_segment_agg(
        agg, len(value_cols), ns, int(ddof), p_out, adaptive, has_sizes
    )
    if has_sizes:
        # operand layout matches the in-kernel histogram: ns slots with an
        # overflow bucket (its value is sliced off, 1 avoids a 0-divide)
        sizes_dev = jnp.asarray(
            np.append(np.asarray(sizes, np.int64), 1)
        )
        return list(fn(tuple(value_cols), codes, sizes_dev))
    return list(fn(tuple(value_cols), codes))


# ---------------------------------------------------------------------- #
# Order-statistic aggregations (median / quantile / nunique / first / last)
# ---------------------------------------------------------------------- #


@functools.lru_cache(maxsize=None)
def _jit_group_quantile(
    n_cols: int,
    num_segments: int,
    p_out: int,
    q: float,
    interpolation: str,
    preserve_float_dtype: bool = False,
):
    """Grouped quantile: lexsort by (code, value), gather at quantile ranks.

    NaNs sort to each group's tail (jnp sort order), so the non-NaN prefix of
    a group is its valid sample; ranks index into that prefix.
    """
    import jax
    import jax.numpy as jnp

    n_groups = num_segments - 1

    def finish(r):
        return _slice_pad(r, n_groups, p_out)

    def one(c, codes, starts):
        # pandas keeps the integer dtype for the non-interpolating kinds
        keep_int = (
            interpolation in ("lower", "higher", "nearest")
            and not jnp.issubdtype(c.dtype, jnp.floating)
        )
        x = c if keep_int else c.astype(jnp.float64)
        nanm = (
            jnp.isnan(x) if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.zeros(x.shape, bool)
        )
        order = jnp.lexsort((x, codes))
        xs = jnp.take(x, order)
        vcnt = jax.ops.segment_sum(
            (~nanm).astype(jnp.int64), codes, num_segments=num_segments
        )[:n_groups]
        g_start = starts[:n_groups]
        target = q * (vcnt.astype(jnp.float64) - 1.0)
        lo = jnp.floor(target).astype(jnp.int64)
        hi = jnp.ceil(target).astype(jnp.int64)
        max_pos = xs.shape[0] - 1
        v_lo = jnp.take(xs, jnp.clip(g_start + lo, 0, max_pos))
        v_hi = jnp.take(xs, jnp.clip(g_start + hi, 0, max_pos))
        frac = target - lo.astype(jnp.float64)
        if interpolation == "linear":
            r = v_lo + (v_hi - v_lo) * frac
        elif interpolation == "lower":
            r = v_lo
        elif interpolation == "higher":
            r = v_hi
        elif interpolation == "midpoint":
            r = (v_lo + v_hi) * 0.5
        else:  # nearest — numpy rounds the virtual rank half-to-even
            pos = jnp.round(target).astype(jnp.int64)
            r = jnp.take(xs, jnp.clip(g_start + pos, 0, max_pos))
        if not keep_int:
            r = jnp.where(vcnt == 0, jnp.nan, r)
            if preserve_float_dtype and jnp.issubdtype(c.dtype, jnp.floating):
                # pandas groupby median keeps float32; quantile widens to f64
                r = r.astype(c.dtype)
        return finish(r)

    def fn(cols: Tuple, codes):
        total = jax.ops.segment_sum(
            jnp.ones(codes.shape, jnp.int64), codes, num_segments=num_segments
        )
        starts = jnp.cumsum(total) - total
        return tuple(one(c, codes, starts) for c in cols)

    return jax.jit(fn)


def groupby_quantile(
    value_cols: List[Any],
    codes: Any,
    num_groups: int,
    n: int,
    q: float = 0.5,
    interpolation: str = "linear",
    preserve_float_dtype: bool = False,
) -> List[Any]:
    """Per-group quantile of each value column (device lexsort + gather)."""
    from modin_tpu.ops.structural import pad_len

    fn = _jit_group_quantile(
        len(value_cols), num_groups + 1, pad_len(num_groups), float(q),
        str(interpolation), bool(preserve_float_dtype),
    )
    return list(fn(tuple(value_cols), codes))


@functools.lru_cache(maxsize=None)
def _jit_group_nunique(n_cols: int, num_segments: int, p_out: int, dropna: bool):
    """Grouped distinct-count: lexsort by (code, value), count run heads."""
    import jax
    import jax.numpy as jnp

    n_groups = num_segments - 1

    def finish(r):
        return _slice_pad(r, n_groups, p_out)

    def one(c, codes):
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        nanm = jnp.isnan(c) if is_f else jnp.zeros(c.shape, bool)
        order = jnp.lexsort((c, codes))
        xs = jnp.take(c, order)
        cs = jnp.take(codes, order)
        nm = jnp.take(nanm, order)
        newgrp = jnp.concatenate([jnp.ones(1, bool), cs[1:] != cs[:-1]])
        newval = jnp.concatenate([jnp.ones(1, bool), xs[1:] != xs[:-1]])
        head = (newgrp | newval) & ~nm
        cnt = jax.ops.segment_sum(
            head.astype(jnp.int64), cs, num_segments=num_segments
        )
        if not dropna:
            has_nan = jax.ops.segment_max(
                nanm.astype(jnp.int64), codes, num_segments=num_segments
            )
            cnt = cnt + has_nan
        return finish(cnt)

    def fn(cols: Tuple, codes):
        return tuple(one(c, codes) for c in cols)

    return jax.jit(fn)


def groupby_nunique(
    value_cols: List[Any], codes: Any, num_groups: int, n: int, dropna: bool = True
) -> List[Any]:
    from modin_tpu.ops.structural import pad_len

    fn = _jit_group_nunique(
        len(value_cols), num_groups + 1, pad_len(num_groups), bool(dropna)
    )
    return list(fn(tuple(value_cols), codes))


@functools.lru_cache(maxsize=None)
def _jit_group_first_last(last: bool, n_cols: int, num_segments: int, p_out: int):
    """Grouped first/last non-NaN value in row order (segment arg-extremum)."""
    import jax
    import jax.numpy as jnp

    n_groups = num_segments - 1

    def finish(r):
        return _slice_pad(r, n_groups, p_out)

    def one(c, codes):
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        P = c.shape[0]
        valid = ~jnp.isnan(c) if is_f else jnp.ones(c.shape, bool)
        iota = jnp.arange(P, dtype=jnp.int64)
        if last:
            key = jnp.where(valid, iota, -1)
            idx = jax.ops.segment_max(key, codes, num_segments=num_segments)
            has = idx >= 0
        else:
            key = jnp.where(valid, iota, P)
            idx = jax.ops.segment_min(key, codes, num_segments=num_segments)
            has = idx < P
        vals = jnp.take(c, jnp.clip(idx, 0, P - 1))
        if is_f:
            vals = jnp.where(has, vals, jnp.nan)
        return finish(vals)

    def fn(cols: Tuple, codes):
        return tuple(one(c, codes) for c in cols)

    return jax.jit(fn)


def groupby_first_last(
    agg: str, value_cols: List[Any], codes: Any, num_groups: int, n: int
) -> List[Any]:
    from modin_tpu.ops.structural import pad_len

    fn = _jit_group_first_last(
        agg == "last", len(value_cols), num_groups + 1, pad_len(num_groups)
    )
    return list(fn(tuple(value_cols), codes))


@functools.lru_cache(maxsize=None)
def _jit_broadcast_groups(n_cols: int):
    """Gather each row's group aggregate back to row positions (transform)."""
    import jax
    import jax.numpy as jnp

    def fn(aggs: Tuple, codes):
        out = []
        for a in aggs:
            safe = jnp.minimum(codes, a.shape[0] - 1)  # pad rows: garbage, sliced off
            out.append(jnp.take(a, safe))
        return tuple(out)

    return jax.jit(fn)


def groupby_broadcast(agg_cols: List[Any], codes: Any) -> List[Any]:
    """Row-shaped device arrays where row i holds its group's aggregate."""
    return list(_jit_broadcast_groups(len(agg_cols))(tuple(agg_cols), codes))


# row-shaped cumulative aggregations (segmented scan)
CUM_AGGS = {"cumsum", "cumprod", "cummax", "cummin"}


@functools.lru_cache(maxsize=None)
def _jit_grouped_cum(op: str, n_cols: int):
    """Grouped cumulatives: sort rows by group code, run ONE segmented
    associative scan (reset at group boundaries), scatter back to row order.
    pandas NaN semantics: a NaN keeps its position without poisoning later
    entries."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    op_fn = {
        "cumsum": jnp.add, "cumprod": jnp.multiply,
        "cummax": jnp.maximum, "cummin": jnp.minimum,
    }[op]
    float_neutral = {
        "cumsum": 0.0, "cumprod": 1.0, "cummax": -jnp.inf, "cummin": jnp.inf,
    }[op]

    def one(c, order, inv, newgrp):
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        x = jnp.take(c, order)
        nanm = jnp.isnan(x) if is_f else None
        filled = jnp.where(nanm, float_neutral, x) if is_f else x

        def combine(a, b):
            fa, va = a
            fb, vb = b
            return fa | fb, jnp.where(fb, vb, op_fn(va, vb))

        _, scanned = lax.associative_scan(combine, (newgrp, filled))
        if is_f:
            scanned = jnp.where(nanm, jnp.nan, scanned)
        return jnp.take(scanned, inv)

    def fn(cols: Tuple, codes):
        order = jnp.argsort(codes, stable=True)
        inv = jnp.argsort(order)
        cs = jnp.take(codes, order)
        newgrp = jnp.concatenate([jnp.ones(1, bool), cs[1:] != cs[:-1]])
        return tuple(one(c, order, inv, newgrp) for c in cols)

    return jax.jit(fn)


def groupby_cumulative(op: str, value_cols: List[Any], codes: Any) -> List[Any]:
    """Row-shaped grouped cumsum/cumprod/cummax/cummin."""
    fn = _jit_grouped_cum(op, len(value_cols))
    return list(fn(tuple(value_cols), codes))


# ---------------------------------------------------------------------- #
# graftfuse: whole-plan fused groupby (bounded-range int/bool keys)
# ---------------------------------------------------------------------- #

#: aggregations with a masked scatter form the fused whole-plan program
#: can express in one pass (pandas groupby semantics: NaN values always
#: skipped; all-NaN float groups answer sum=0 / count=0 / min=max=mean=NaN)
FUSED_GROUPBY_AGGS = frozenset({"sum", "prod", "count", "mean", "min", "max"})

#: widest group-id table a fused program will scatter into (pow2-padded);
#: wider key ranges decline to the staged factorize path
FUSED_MAX_GROUPS = 1 << 16


def fused_groups_bucket(width: int) -> int:
    """Pow2-padded group-table size for a key range of ``width`` values —
    the same shape discipline the histogram reductions use, so a dozen
    nearby cardinalities share one compiled program."""
    return 1 << max(int(width - 1).bit_length(), 3)


def fused_group_probe(
    key_expr: Any, keep: Optional[Any], n: int
) -> Tuple[int, int, int]:
    """(key_min, key_max, kept_rows) of the masked key column, one dispatch.

    The filter/map chain below the key fuses into this probe program; the
    three scalars are the only host fetch.  ``keep`` may be None (no
    filter: only the pad rows are masked).  ``kept_rows == 0`` tells the
    caller to decline (pandas empty-groupby semantics stay with the staged
    path).  Keys must be integral (int/uint/bool) — the caller gates.
    """
    from modin_tpu.ops.lazy import run_fused

    has_mask = keep is not None

    def tail(arrs):
        import jax.numpy as jnp

        if has_mask:
            k, m, n_t = arrs
        else:
            k, n_t = arrs
            m = True
        k64 = k.astype(jnp.int64)
        valid = m & (jnp.arange(k64.shape[0]) < n_t)
        kept = jnp.sum(valid, dtype=jnp.int64)
        kmin = jnp.min(jnp.where(valid, k64, jnp.iinfo(jnp.int64).max))
        kmax = jnp.max(jnp.where(valid, k64, jnp.iinfo(jnp.int64).min))
        return kmin, kmax, kept

    roots = [key_expr] + ([keep] if has_mask else []) + [int(n)]
    results = run_fused(
        roots,
        tail_key=("fuse_gb_probe", has_mask),
        tail_builder=tail,
    )
    kmin, kmax, kept = [int(np.asarray(r)) for r in _engine_materialize(results)]
    return kmin, kmax, kept


def fused_group_agg(
    agg: str,
    key_expr: Any,
    cols: List[Any],
    keep: Optional[Any],
    n: int,
    kmin: int,
    n_buckets: int,
    donate_cols: Optional[List[Any]] = None,
) -> Tuple[np.ndarray, List[np.ndarray], List[np.ndarray]]:
    """The whole post-scan chain + masked group aggregation, one dispatch.

    Scatters every kept row into a ``n_buckets``-slot table (slot =
    ``key - kmin``, with ``kmin`` a runtime scalar so one program serves
    any key offset at a bucket size); dropped/pad rows land in the
    overflow slot and are sliced off.  Returns host arrays
    ``(group_sizes[n_buckets], per-column aggregates, per-column non-NaN
    counts)`` — the caller keeps slots with ``group_sizes > 0`` (observed
    groups, already in sorted key order) and applies pandas dtype rules.
    """
    from modin_tpu.ops.reductions import _mark_and_run

    G = int(n_buckets)
    has_mask = keep is not None

    def tail(arrs):
        import jax.numpy as jnp

        if has_mask:
            k, *col_arrs, m, n_t, kmin_t = arrs
        else:
            k, *col_arrs, n_t, kmin_t = arrs
            m = True
        k64 = k.astype(jnp.int64)
        valid = m & (jnp.arange(k64.shape[0]) < n_t)
        ids = jnp.where(valid, jnp.clip(k64 - kmin_t, 0, G - 1), G)
        sizes = jnp.zeros(G + 1, jnp.int64).at[ids].add(
            jnp.where(valid, 1, 0)
        )
        tables = []
        counts = []
        for c in col_arrs:
            is_f = jnp.issubdtype(c.dtype, jnp.floating)
            use = valid & ~jnp.isnan(c) if is_f else valid
            nn = jnp.zeros(G + 1, jnp.int64).at[ids].add(jnp.where(use, 1, 0))
            counts.append(nn)
            if agg == "count":
                tables.append(nn)
                continue
            x = c.astype(jnp.int64) if c.dtype == jnp.bool_ else c
            if agg in ("sum", "mean"):
                acc = x.astype(jnp.float64) if agg == "mean" else x
                neutral = jnp.zeros((), acc.dtype)
                t = jnp.zeros(G + 1, acc.dtype).at[ids].add(
                    jnp.where(use, acc, neutral)
                )
                if agg == "mean":
                    t = jnp.where(nn > 0, t / nn, jnp.nan)
                tables.append(t)
            elif agg == "prod":
                t = jnp.ones(G + 1, x.dtype).at[ids].multiply(
                    jnp.where(use, x, jnp.ones((), x.dtype))
                )
                tables.append(t)
            elif agg in ("min", "max"):
                from modin_tpu.ops.reductions import _int_max, _int_min

                if is_f:
                    neutral = jnp.inf if agg == "min" else -jnp.inf
                else:
                    neutral = (
                        _int_max(x.dtype) if agg == "min" else _int_min(x.dtype)
                    )
                init = jnp.full(G + 1, neutral, x.dtype)
                at = init.at[ids]
                t = (at.min if agg == "min" else at.max)(
                    jnp.where(use, x, jnp.full((), neutral, x.dtype))
                )
                if is_f:
                    # all-NaN (or empty) slot: the neutral infinity means
                    # "no value"; pandas answers NaN there
                    t = jnp.where(nn > 0, t, jnp.nan)
                tables.append(t)
            else:
                raise ValueError(agg)
        return (sizes,) + tuple(tables) + tuple(counts)

    roots = (
        [key_expr, *cols]
        + ([keep] if has_mask else [])
        + [int(n), int(kmin)]
    )
    results = _mark_and_run(
        roots,
        ("fuse_gb_agg", agg, G, len(cols), has_mask),
        tail,
        donate_cols,
    )
    fetched = [np.asarray(r) for r in _engine_materialize(results)]
    sizes = fetched[0]
    n_cols = len(cols)
    return sizes, fetched[1 : 1 + n_cols], fetched[1 + n_cols :]
