"""One versioned calibration store for every measured-coefficient table.

Before graftopt, three subsystems each hand-rolled the same persistence
idiom — measure once, validate a cached JSON against (version, platform,
mesh shape), atomically rewrite it under ``CacheDir``:

- the kernel-router calibration table (sorted-reduce device/host
  coefficients plus the graftmesh collective entries), ``ops/router.py``;
- the graftcost substrate roofline peaks, ``observability/costs.py``;
- and two copies of the n·log n crossover scaling inside ``ops/router.py``
  itself (``predicted_costs`` vs ``decide_layout``) that had started to
  drift.

This module is that idiom, once.  It is deliberately thin: callers keep
their own table *contents* and in-memory resolve-once state (each already
guards it with its registered lock); the store owns only the naming,
validation, and atomic persistence.  File names are kept byte-compatible
with the pre-consolidation layout (``kernel_router_{platform}_mesh{mesh}_
v{N}.json``, ``roofline_{platform}.json``) so existing caches stay warm
across the refactor.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, Optional


def nlogn_scale(n: int, cal_rows: int) -> float:
    """The n·log n crossover scale from a calibration row count to ``n``.

    THE shared helper for every sort-shaped cost extrapolation (kernel
    router, layout router, graftopt's plan-time model): a measured wall at
    ``cal_rows`` rows scales to ``n`` rows by the ratio of n·log2(n)
    terms.  Both operands are floored at 2 so tiny frames never divide by
    zero or go negative through log2.
    """
    cal_rows = max(int(cal_rows), 2)
    n = max(int(n), 2)
    return (n * math.log2(n)) / (cal_rows * math.log2(cal_rows))


def linear_scale(n: int, cal_rows: int) -> float:
    """The linear per-row scale from a calibration row count to ``n``."""
    return max(int(n), 0) / max(int(cal_rows), 2)


def table_path(
    kind: str,
    platform: str,
    mesh_key: Optional[str] = None,
    version: Optional[int] = None,
) -> Optional[str]:
    """The CacheDir path for one calibration table, or None (no CacheDir).

    ``kind`` names the table family (``kernel_router``, ``roofline``);
    ``mesh_key`` and ``version`` fold into the name exactly as the
    pre-consolidation callers spelled them, so existing caches validate.
    """
    try:
        from modin_tpu.config import CacheDir

        cache_dir = CacheDir.get()
        if not cache_dir:
            return None
    except Exception:  # graftlint: disable=EXC-HYGIENE -- an unconfigured CacheDir means "no persistence", never a failed query
        return None
    name = f"{kind}_{platform}"
    if mesh_key is not None:
        name += f"_mesh{mesh_key}"
    if version is not None:
        name += f"_v{version}"
    return os.path.join(str(cache_dir), f"{name}.json")


def load_table(
    path: Optional[str],
    version: Optional[int] = None,
    platform: Optional[str] = None,
    mesh_key: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """A cached table when it exists AND matches every given key, else None.

    Each non-None keyword is validated against the table's own recorded
    field — a table measured on another substrate, mesh topology, or
    schema version never leaks into this process's cost model.
    """
    if path is None:
        return None
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(table, dict):
        return None
    if version is not None and table.get("version") != version:
        return None
    if platform is not None and table.get("platform") != platform:
        return None
    if mesh_key is not None and table.get("mesh") != mesh_key:
        return None
    return table


def store_table(path: Optional[str], table: Dict[str, Any]) -> None:
    """Atomically persist one table; an unwritable CacheDir is a no-op
    (the owner simply re-measures next process)."""
    if path is None:
        return
    try:
        from modin_tpu.utils.atomic_io import atomic_write_json

        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_json(path, table)
    except OSError:
        pass
