"""graftmesh: sharded (SPMD) sort & merge-join kernels over ``range_shuffle``.

The 2-D partition grid of the reference maps onto the JAX device mesh where
row-partitioning is a sharding spec, not a Python object (SURVEY §7).  Most
hot paths exploit that for free — a ``jnp.sum`` over a row-sharded array
lowers to per-shard partials + a ``psum``, elementwise/groupby likewise —
but the sort-shaped kernels did not: a global ``jnp.argsort`` over a sharded
array gathers everything onto one device on most backends, and the
merge-join's right-side sort has the same shape.  This module routes those
two through the existing sample -> pivots -> ``lax.all_to_all`` -> per-shard
local sort machinery (parallel/shuffle.py), the MapReduce-onto-shard_map
design DrJAX (arXiv:2403.07128) and Xorbits' operator tiling
(arXiv:2401.00865) describe:

- :func:`sharded_sorted_valid` — the sorted-representation build (the
  shared prefix of median/quantile/nunique/mode, ops/sort.py
  ``sorted_valid``) as one range-partitioned shuffle + per-shard local
  sorts, bit-identical to the local build (NaN/pad rows collapse to the
  same +inf / int-max tail);
- :func:`sharded_merge_positions` — the merge-join's match positions with
  the right-side O(n log n) sort replaced by the shuffle; the probe
  (searchsorted) and expansion stages reuse ops/join.py unchanged, so the
  output position arrays are bit-identical to the local path's.

Every entry point returns ``None`` when the sharded path declines (single
shard, pathological key skew) — callers keep their local kernels as the
fallback, and ops/router.py ``decide_layout`` decides when the collective
pays (the router, not a flag).
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import numpy as np

from modin_tpu.parallel.engine import materialize as _engine_materialize


@functools.lru_cache(maxsize=None)
def _jit_prep_sorted(n: int):
    """NaN/pad collapse + valid count, mirroring ops/sort.py sorted_valid:
    floats map NaN (and pad rows) to +inf with ``n_valid`` excluding NaNs,
    ints map pad rows to the dtype max with ``n_valid == n``."""
    import jax
    import jax.numpy as jnp

    def fn(c):
        from modin_tpu.ops.reductions import _int_max, _valid_mask

        if c.dtype == jnp.bool_:
            c = c.astype(jnp.int8)  # XLA sort keys; 0/1 round-trips any caller
        is_f = jnp.issubdtype(c.dtype, jnp.floating)
        valid = _valid_mask(c, n) if c.shape[0] != n else None
        if is_f:
            nanm = jnp.isnan(c) if valid is None else (jnp.isnan(c) | ~valid)
            x = jnp.where(nanm, jnp.inf, c)
            n_valid = (n if valid is None else jnp.sum(valid)) - jnp.sum(
                jnp.isnan(c) if valid is None else (jnp.isnan(c) & valid)
            )
            n_valid = jnp.asarray(n_valid, jnp.int64)
        else:
            x = c if valid is None else jnp.where(valid, c, _int_max(c.dtype))
            n_valid = jnp.asarray(n, jnp.int64)
        return x, n_valid

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_seal_tail(n: int):
    """Overwrite the compacted shuffle output's pad tail (gather garbage)
    with the sorted-representation sentinel, making the rep byte-identical
    to the local ``jnp.sort`` build."""
    import jax
    import jax.numpy as jnp

    def fn(xs):
        from modin_tpu.ops.reductions import _int_max

        idx = jnp.arange(xs.shape[0])
        if jnp.issubdtype(xs.dtype, jnp.floating):
            sentinel = jnp.inf
        else:
            sentinel = _int_max(xs.dtype)
        return jnp.where(idx < n, xs, sentinel)

    return jax.jit(fn)


def sharded_sorted_valid(c: Any, n: int) -> Optional[Tuple[Any, Any]]:
    """``(sorted values, n_valid)`` of one padded column via the all_to_all
    shuffle, or None when the sharded path declines (single shard /
    pathological skew) — the caller's local ``sorted_valid`` is the
    fallback and produces the identical representation.
    """
    from modin_tpu.observability import costs as _costs
    from modin_tpu.parallel.mesh import num_row_shards
    from modin_tpu.parallel.shuffle import ShuffleSkewError, range_shuffle

    if num_row_shards() < 2:
        return None
    if _costs.COST_ON:
        # same site + accounting as the local build (sort.sorted_valid):
        # padding waste must describe the workload, not the routing choice
        _costs.note_padding(
            "sort.sorted_valid",
            int(c.shape[0]) * c.dtype.itemsize,
            int(n) * c.dtype.itemsize,
        )
    x, n_valid = _jit_prep_sorted(int(n))(c)
    try:
        xs, _cols, _counts, _pivots = range_shuffle(x, [], int(n), local_sort=True)
    except ShuffleSkewError:
        return None
    return _jit_seal_tail(int(n))(xs), n_valid


def sharded_sorted_valid_columns(
    arrays: List[Any], n: int
) -> Optional[List[Tuple[Any, Any]]]:
    """Sharded rep build for a batch of columns; None when ANY column
    declines, so a mixed batch falls back to the one-jit local build whole
    (callers never mix build provenance within one plan)."""
    out = []
    for c in arrays:
        pair = sharded_sorted_valid(c, n)
        if pair is None:
            return None
        out.append(pair)
    return out


@functools.lru_cache(maxsize=None)
def _jit_total_codes():
    """Both sides' join keys as int64 total-order codes (one jit): floats
    through the IEEE total order (-0.0 == 0.0, every NaN -> one key — the
    pandas merge equality), everything else widened to int64."""
    import jax
    import jax.numpy as jnp

    from modin_tpu.ops.structural import float_total_order

    def enc(v):
        if jnp.issubdtype(v.dtype, jnp.floating):
            return float_total_order(v)
        return v.astype(jnp.int64)

    def fn(lk, rk):
        return enc(lk), enc(rk)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jit_match_presorted(n_left: int, n_right: int):
    """Match bounds of raw left keys against an ALREADY globally sorted
    right key column (the shuffle's compacted output).  The pad tail is
    sealed to int64 max so the search array stays monotone; clipping lo/hi
    to ``n_right`` excludes boundary ties exactly like the local
    ``_jit_match_bounds``."""
    import jax
    import jax.numpy as jnp

    def fn(left_key, rs):
        i64max = np.iinfo(np.int64).max
        tail = jnp.arange(rs.shape[0]) >= n_right
        rs = jnp.where(tail, i64max, rs)
        lo = jnp.searchsorted(rs, left_key, side="left")
        hi = jnp.searchsorted(rs, left_key, side="right")
        lo = jnp.minimum(lo, n_right)
        hi = jnp.minimum(hi, n_right)
        counts = hi - lo
        l_valid = jnp.arange(left_key.shape[0]) < n_left
        counts = jnp.where(l_valid, counts, 0)
        total_inner = jnp.sum(counts)
        total_left = jnp.sum(jnp.where(l_valid, jnp.maximum(counts, 1), 0))
        return lo, counts, total_inner, total_left

    return jax.jit(fn)


def sharded_merge_positions(
    left_key: Any,
    right_key: Any,
    n_left: int,
    n_right: int,
    how: str = "inner",
) -> Optional[Tuple[Any, Any, int, bool]]:
    """``sort_merge_positions`` with the right-side sort done by the
    all_to_all shuffle; same contract, bit-identical positions.

    The right keys (int64 total-order codes) range-partition over the mesh
    with per-shard local sorts — arrival order within a shard is original
    right order, so equal keys keep right-original tie order exactly like
    the local stable sort.  The shuffled row-id payload IS the local
    path's ``perm``; probe + expansion reuse ops/join.py.  None = decline
    (single shard / skew), caller falls back to the local kernel.
    """
    import jax.numpy as jnp

    from modin_tpu.ops.join import _jit_expand
    from modin_tpu.ops.structural import pad_len
    from modin_tpu.parallel.mesh import num_row_shards
    from modin_tpu.parallel.shuffle import ShuffleSkewError, range_shuffle

    if num_row_shards() < 2:
        return None
    lk, rk = _jit_total_codes()(left_key, right_key)
    iota = jnp.arange(rk.shape[0], dtype=jnp.int64)
    try:
        rs, (perm,), _counts, _pivots = range_shuffle(
            rk, [iota], int(n_right), local_sort=True
        )
    except ShuffleSkewError:
        return None
    lo, counts, total_inner, total_left = _jit_match_presorted(
        int(n_left), int(n_right)
    )(lk, rs)
    inner_count, left_count = (
        int(v) for v in _engine_materialize((total_inner, total_left))
    )
    n_out = left_count if how == "left" else inner_count
    has_miss = how == "left" and left_count > inner_count
    p_out = pad_len(max(n_out, 1))
    if n_out == 0:
        zeros = jnp.zeros(p_out, jnp.int64)
        return zeros, jnp.full(p_out, -1, jnp.int64), 0, False
    left_pos, right_pos = _jit_expand(p_out, int(n_left), how == "left")(
        perm, lo, counts
    )
    return left_pos, right_pos, n_out, has_miss
