"""Dictionary encoding: device codes for host string/object columns.

The device stores no strings.  A host object column becomes device-computable
for EQUALITY/ORDER-shaped ops (groupby keys, merge keys, sort keys, isin,
nunique, value_counts, drop_duplicates) through a lazy, cached factorization:

- ``categories``: the column's distinct values, **sorted** (host-side, small)
- ``codes``: per-row positions into categories, as a padded sharded device
  array of **float64 with NaN for missing** — NOT int32 with a -1 sentinel.
  Sorted categories make codes order-isomorphic to the strings, and NaN
  codes make every existing numeric-key kernel's missing-data semantics
  (groupby dropna, the strict IEEE total order shared by sort and
  sort-merge join, na_position) apply to string keys verbatim, with zero
  special-casing in the kernels.

This is the staged design SURVEY §7 calls for (codes on device, categories
on host); the reference instead ships whole object partitions to workers
(modin/core/storage_formats/pandas/query_compiler.py groupby/merge on
object keys).  The encoding also powers the ``.str`` PREDICATE/MEASURE ops
(len/contains/startswith/is*/count/find/match — TpuQueryCompiler's
``_try_str_lut`` runs the pandas op once per category and gathers the
lookup table by code on device); only string-OUTPUT str ops
(lower/strip/replace/...) stay host-side.

Encoding is lazy (first use) and cached on the column, so unused string
columns cost nothing and a repeated ``df.groupby("city")`` factorizes once.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np
import pandas

# Downcast float64->float32 device policies keep integers exact only to
# 2^24; a column with more distinct values than that stays host-only.
_MAX_CATEGORIES = 1 << 24


class DictEncoding(NamedTuple):
    codes: Any  # DeviceColumn of float64 codes (NaN = missing)
    categories: np.ndarray  # sorted distinct values, host-side
    has_nan: bool  # whether any row is missing (NaN code present)


def encode_host_column(col: Any) -> Optional[DictEncoding]:
    """The column's :class:`DictEncoding`, or None.

    None means the column is not dictionary-encodable (non-object dtype,
    unorderable mixed values, or category count past the device-exactness
    bound).  The result is cached on the column either way.
    """
    cached = getattr(col, "_dict_cache", None)
    if cached is not None:
        return cached if cached is not False else None
    result = _encode(col)
    col._dict_cache = result if result is not None else False
    return result


def _encode(col: Any) -> Optional[DictEncoding]:
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn

    from pandas.api.types import is_object_dtype

    dtype = col.pandas_dtype
    # NOTE: NumpyEADtype("object") != np.dtype(object) under ==, so the
    # object check must go through is_object_dtype
    if not (
        is_object_dtype(dtype)
        or (hasattr(pandas, "StringDtype") and isinstance(dtype, pandas.StringDtype))
    ):
        return None
    values = np.asarray(col.to_numpy(), dtype=object)
    try:
        codes, categories = pandas.factorize(values, sort=True, use_na_sentinel=True)
    except TypeError:
        return None  # unorderable mixed values
    categories = np.asarray(categories, dtype=object)
    if len(categories) > _MAX_CATEGORIES:
        return None
    fcodes = codes.astype(np.float64)
    has_nan = bool((codes == -1).any())
    if has_nan:
        fcodes[codes == -1] = np.nan
    return DictEncoding(DeviceColumn.from_numpy(fcodes), categories, has_nan)


def encodable(col: Any) -> bool:
    return encode_host_column(col) is not None


def encode_categorical_column(col: Any) -> Optional[DictEncoding]:
    """Encoding for a host CATEGORICAL column: pandas already stores codes,
    so this is a cast + device_put (cached).  Categories keep their CATEGORY
    order (not lexicographic) — pandas sorts categorical groups by category
    order, which is exactly ascending-code order.  Cached under
    ``_cat_cache``, NEVER ``_dict_cache``: consumers of the sorted-category
    encoding (isin/nunique/value_counts/sort) must not receive this
    category-ordered one."""
    cached = getattr(col, "_cat_cache", None)
    if cached is not None:
        return cached if cached is not False else None
    from modin_tpu.core.dataframe.tpu.dataframe import DeviceColumn

    try:
        cat = col.data
        codes = np.asarray(cat.codes)
        categories = np.asarray(cat.categories)
    except Exception:  # graftlint: disable=EXC-HYGIENE -- host pandas Categorical probe; any failure means 'not encodable'
        col._cat_cache = False
        return None
    fcodes = codes.astype(np.float64)
    has_nan = bool((codes == -1).any())
    if has_nan:
        fcodes[codes == -1] = np.nan
    result = DictEncoding(DeviceColumn.from_numpy(fcodes), categories, has_nan)
    col._cat_cache = result
    return result


def decode_codes(code_values: np.ndarray, categories: np.ndarray) -> np.ndarray:
    """Host object array for (possibly NaN) float code values."""
    out = np.empty(len(code_values), dtype=object)
    codes = np.asarray(code_values, dtype=np.float64)
    if len(categories) == 0:
        # an all-missing column factorizes to empty categories; every code
        # is NaN
        out[:] = np.nan
        return out
    nan_mask = np.isnan(codes)
    idx = np.where(nan_mask, 0, codes).astype(np.int64)
    out[:] = categories[idx]
    if nan_mask.any():
        out[nan_mask] = np.nan
    return out


def union_categories(
    left: np.ndarray, right: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(union, left_map, right_map): the sorted union of two sorted category
    arrays plus, per side, old-code -> union-code translation tables.

    Both maps preserve order (union is sorted), so remapped codes stay
    order-isomorphic and the device sort-merge join applies unchanged.
    """
    union = np.union1d(left, right)
    left_map = np.searchsorted(union, left).astype(np.float64)
    right_map = np.searchsorted(union, right).astype(np.float64)
    return union, left_map, right_map


def remap_codes_device(codes: Any, table: np.ndarray) -> Any:
    """Device gather: new_codes[i] = table[codes[i]], NaN passing through.

    ``codes`` is the padded float64 device array; ``table`` a small host
    translation array (device_put once)."""
    import jax.numpy as jnp

    t = jnp.asarray(table, dtype=jnp.float64)
    safe = jnp.where(jnp.isnan(codes), 0.0, codes).astype(jnp.int32)
    gathered = jnp.take(t, safe, mode="clip")
    return jnp.where(jnp.isnan(codes), jnp.nan, gathered)


def lookup_values(values: List[Any], categories: np.ndarray) -> np.ndarray:
    """Float codes of ``values`` within ``categories`` (NaN when absent):
    the host half of a device ``isin`` on an encoded column."""
    out = np.full(len(values), np.nan, dtype=np.float64)
    for i, v in enumerate(values):
        try:
            pos = np.searchsorted(categories, v)
            if pos < len(categories) and categories[pos] == v:
                out[i] = float(pos)
        except TypeError:
            continue  # unorderable value can't be present
    return out
