"""Deferred elementwise expression DAG — the op-fusion layer.

TPU-native analogue of the reference's DeferredExecution batching
(modin/core/execution/ray/common/deferred_execution.py:43): the reference
accumulates chained operations per partition and materializes them in one
remote call; here the batching currency is the *XLA program*.  Chained
column expressions accumulate into a small DAG of ``LazyExpr`` nodes, and the
whole chain compiles as ONE jit when a consumer needs concrete data — so
``(a * b + c).sum()`` lowers to a single fused kernel (one dispatch, no
intermediate HBM round-trips) instead of three.

Design notes:

- Leaves are concrete jax.Arrays (padded, sharded device columns) or Python /
  numpy scalars.  Scalars are passed as *runtime jit arguments*, not baked
  into the compiled program, so ``df * 2`` and ``df * 3`` share a
  compilation; jax keeps Python scalars weakly typed, preserving numpy
  promotion semantics.
- Graphs are linearized (postorder, diamond nodes computed once) into a
  structural fingerprint; compiled executables are cached per fingerprint.
  jit itself re-specializes per input sharding, so one cache entry serves
  any mesh layout.
- A fused call can end in a *tail* (e.g. the per-column reduction kernels),
  fusing map chains into their consuming reduction: ``(a*b+c).sum()`` is the
  canonical win.
- ``_MAX_NODES`` caps the fusion window so pathological op chains (loops
  mutating a column thousands of times) do not build unbounded XLA programs.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import meters as graftmeter
from modin_tpu.serving import context as serving_context

_MAX_NODES = 160

_SCALAR_TYPES = (int, float, bool, np.integer, np.floating, np.bool_)

# fingerprint -> jitted executable, LRU-bounded by MODIN_TPU_FUSED_CACHE_SIZE
# (each entry pins an XLA executable; a long session with varying expression
# shapes previously grew this without limit).  All access is serialized by
# _FUSED_LOCK: concurrent queries (graftgate) hit this cache from many
# threads, and an unguarded OrderedDict move_to_end racing a popitem can
# corrupt the dict's internal linkage, not just return a stale entry.
_FUSED_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_FUSED_LOCK = named_lock("ops.fused_cache")
_evictions = 0


def _fused_cache_get(key: Any) -> Optional[Any]:
    with _FUSED_LOCK:
        fn = _FUSED_CACHE.get(key)
        if fn is not None:
            _FUSED_CACHE.move_to_end(key)
    if fn is not None and graftmeter.ACCOUNTING_ON:
        emit_metric("fusion.cache.hit", 1)
    return fn


def _fused_cache_put(key: Any, fn: Any) -> None:
    global _evictions
    from modin_tpu.config import FusedCacheSize

    limit = FusedCacheSize.get()
    evicted = 0
    with _FUSED_LOCK:
        _FUSED_CACHE[key] = fn
        _FUSED_CACHE.move_to_end(key)
        if limit > 0:
            while len(_FUSED_CACHE) > limit:
                _FUSED_CACHE.popitem(last=False)
                evicted += 1
        if evicted:
            _evictions += evicted
    if evicted:
        emit_metric("fusion.cache.evict", evicted)


def fused_cache_evictions() -> int:
    """Process-lifetime count of fused executables evicted by the LRU."""
    return _evictions


def fused_cache_len() -> int:
    return len(_FUSED_CACHE)


class LazyExpr:
    """One deferred op node: ``op(*args, **dict(static))``.

    ``op`` names a function in the elementwise registry
    (:func:`modin_tpu.ops.elementwise.get_op`); ``args`` are LazyExpr
    children, jax.Array leaves, or scalars; ``static`` is a hashable tuple of
    keyword pairs compiled into the program (e.g. round decimals).
    """

    __slots__ = ("op", "args", "static", "aval", "size", "_result")

    def __init__(self, op: str, args: Tuple[Any, ...], static: Tuple = ()):
        self.op = op
        self.args = args
        self.static = static
        self._result = None
        size = 1
        for a in args:
            if isinstance(a, LazyExpr) and a._result is None:
                size += a.size
        self.size = size
        self.aval = _eval_aval(op, args, static)

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def shape(self):
        return self.aval.shape

    def astype(self, dtype) -> "LazyExpr":
        return lazy_op("astype", self, static=(("dtype", str(np.dtype(dtype))),))

    def __repr__(self) -> str:
        return f"LazyExpr({self.op}, size={self.size}, aval={self.aval})"


def _eval_aval(op: str, args: Tuple[Any, ...], static: Tuple):
    """Abstract-evaluate one node (shape/dtype only; no compile)."""
    import jax

    from modin_tpu.ops.elementwise import get_op

    fn = get_op(op)
    kw = dict(static)
    abstract_args = []
    for a in args:
        if isinstance(a, LazyExpr):
            abstract_args.append(
                a._result if a._result is not None else a.aval
            )
        else:
            # concrete arrays and scalars: eval_shape abstracts them itself,
            # preserving weak typing for Python scalars
            abstract_args.append(a)
    return jax.eval_shape(lambda *xs: fn(*xs, **kw), *abstract_args)


def is_lazy(x: Any) -> bool:
    return isinstance(x, LazyExpr) and x._result is None


def _distinct_size(root: LazyExpr) -> int:
    """Exact count of distinct unmaterialized nodes (diamonds counted once)."""
    seen = set()
    stack = [root]
    while stack:
        e = stack.pop()
        if not isinstance(e, LazyExpr) or e._result is not None or id(e) in seen:
            continue
        seen.add(id(e))
        stack.extend(a for a in e.args if isinstance(a, LazyExpr))
    return len(seen)


def lazy_op(op: str, *args: Any, static: Tuple = ()) -> LazyExpr:
    """Build a deferred node; oversized graphs materialize immediately."""
    e = LazyExpr(op, args, static)
    if e.size > _MAX_NODES:
        # size is a cheap upper bound that double-counts diamond sharing;
        # confirm with the exact distinct count before giving up on fusion
        e.size = _distinct_size(e)
        if e.size > _MAX_NODES:
            materialize_exprs([e])
    return e


def _linearize(roots: Sequence[Any]):
    """Flatten an expression forest into an executable spec.

    Returns (nodes, out_refs, leaves, scalars, fingerprint): ``nodes`` is a
    postorder list of (op, arg_refs, static); a ref is ('n', i) node, ('l', i)
    leaf, or ('s', i) scalar.  Diamond-shared nodes appear once.
    """
    nodes: List[Tuple] = []
    node_idx: Dict[int, int] = {}
    leaves: List[Any] = []
    leaf_idx: Dict[int, int] = {}
    leaf_tags: List[Tuple] = []
    scalars: List[Any] = []
    scalar_tags: List[str] = []

    def visit_leaf(x) -> Tuple[str, int]:
        i = leaf_idx.get(id(x))
        if i is None:
            i = len(leaves)
            leaves.append(x)
            leaf_idx[id(x)] = i
            leaf_tags.append((str(x.dtype), x.shape, bool(getattr(x, "weak_type", False))))
        return ("l", i)

    def visit(e) -> Tuple[str, int]:
        if isinstance(e, LazyExpr):
            if e._result is not None:
                return visit_leaf(e._result)
            i = node_idx.get(id(e))
            if i is not None:
                return ("n", i)
            refs = tuple(visit(a) for a in e.args)
            nodes.append((e.op, refs, e.static))
            i = len(nodes) - 1
            node_idx[id(e)] = i
            return ("n", i)
        if isinstance(e, _SCALAR_TYPES):
            scalars.append(e)
            scalar_tags.append(
                str(np.dtype(type(e))) if isinstance(e, np.generic) else type(e).__name__
            )
            return ("s", len(scalars) - 1)
        return visit_leaf(e)

    out_refs = tuple(visit(r) for r in roots)
    fingerprint = (
        tuple(nodes),
        out_refs,
        tuple(leaf_tags),
        tuple(scalar_tags),
    )
    return nodes, out_refs, leaves, scalars, fingerprint


def _cache_epoch_key() -> Tuple:
    """(mesh shape, device epoch) component of every fused-cache key.

    A program traced under one mesh topology bakes that topology's
    sharding into its compiled executable — an in-process ``MeshShape``
    flip (the ``_jit_shuffle`` stale-program class graftmesh fixed) must
    never reuse it.  The device epoch guards the same way across a
    graftguard re-seat: post-loss executables are retraced rather than
    trusted to hold no dead device state.  Both reads are cached module
    attributes (no lock, no mesh build) on the hot path.
    """
    try:
        from modin_tpu.core.execution.recovery import current_epoch
        from modin_tpu.parallel.mesh import mesh_shape_key

        return (mesh_shape_key(), current_epoch())
    except Exception:  # graftlint: disable=EXC-HYGIENE -- no backend/mesh yet: a single unkeyed epoch is the pre-mesh world
        return ("unknown", 0)


_donation_filter_installed = False


def _ensure_donation_warning_filter() -> None:
    """One-time, process-wide suppression of jax's "Some donated buffers
    were not usable" UserWarning.

    The fused reduce/groupby tails output scalars and small tables, so no
    output shape ever aliases a full-length donated input and jax warns on
    every compiled shape — but the donation is still doing its job (the
    buffer is deleted at dispatch, the early HBM release the ledger
    records), so the warning is pure noise.  Installed lazily at the first
    donated dispatch (a process that never donates keeps its filters
    untouched) and module-global rather than per-dispatch: a scoped
    ``catch_warnings`` mutates process-global filter state non-atomically,
    which two concurrently-dispatching threads can corrupt.
    """
    global _donation_filter_installed
    if _donation_filter_installed:
        return
    import warnings

    with _FUSED_LOCK:
        if not _donation_filter_installed:
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable"
            )
            _donation_filter_installed = True


def run_fused(
    roots: Sequence[Any],
    tail_key: Optional[Tuple] = None,
    tail_builder: Optional[Callable[[List[Any]], Any]] = None,
    donate: Optional[frozenset] = None,
):
    """Compile + run the whole forest (and optional tail) as one jit.

    Without a tail: returns the list of concrete arrays for ``roots`` and
    memoizes each root LazyExpr's result.  With a tail: the tail builder is
    traced over the root arrays inside the same jit (fusing e.g. a reduction
    into its elementwise producers) and its output is returned.

    ``donate`` is a set of ``id(buffer)`` for concrete leaf arrays the
    caller proved have no other live consumer (graftfuse: the device ledger
    ref-count): those leaves are passed in donated positions
    (``donate_argnums``), so XLA frees them the moment the dispatch is done
    with them — and reuses them in place where an output shape aliases an
    input — instead of every input surviving to the next GC pass.  The
    caller owns the donation contract — marking the owning columns spilled
    so later reads restore via lineage instead of touching the consumed
    buffer.
    """
    import jax

    if serving_context.CONTEXT_ON:
        # graftgate deadline boundary: fused-chain materialization is where
        # a deferred query finally pays for its whole expression forest —
        # check before linearize/compile, not after
        serving_context.check_deadline("fusion.run_fused")

    if tail_builder is None and not any(is_lazy(r) for r in roots):
        return [r._result if isinstance(r, LazyExpr) else r for r in roots]

    nodes, out_refs, leaves, scalars, fingerprint = _linearize(roots)
    donate_positions: Tuple[int, ...] = ()
    if donate:
        donate_positions = tuple(
            i for i, leaf in enumerate(leaves) if id(leaf) in donate
        )
    # the donated positions are part of the executable's identity: jit
    # fixes donate_argnums at wrap time, so the same forest with and
    # without donation is two programs
    key = (fingerprint, tail_key, _cache_epoch_key(), donate_positions)
    fn = _fused_cache_get(key)
    if fn is None:
        from modin_tpu.ops.elementwise import get_op

        nodes_spec = tuple(nodes)

        def execute(scalar_vals: Tuple, *leaf_vals):
            vals: List[Any] = []

            def res(ref):
                kind, i = ref
                if kind == "n":
                    return vals[i]
                if kind == "l":
                    return leaf_vals[i]
                return scalar_vals[i]

            for op, refs, static in nodes_spec:
                vals.append(get_op(op)(*[res(r) for r in refs], **dict(static)))
            outs = [res(r) for r in out_refs]
            return tail_builder(outs) if tail_builder is not None else tuple(outs)

        fn = jax.jit(
            execute,
            # +1: argument 0 is the scalar tuple (never donated)
            donate_argnums=tuple(p + 1 for p in donate_positions),
        )
        _fused_cache_put(key, fn)

    # dispatch through the engine seam: the fused call gets the resilience
    # policy (classify/retry/recovery) and op-replay lineage provenance
    # exactly like every other device computation
    from modin_tpu.parallel.engine import JaxWrapper

    if donate_positions:
        _ensure_donation_warning_filter()
        result = JaxWrapper.deploy(
            fn,
            (tuple(scalars), *leaves),
            # a donated program must never be replayed from provenance:
            # replay would re-donate (and delete) the freshly restored
            # input buffers under their columns.  Its outputs are
            # materialized to host at the call site, so they never need
            # op-replay lineage anyway.
            donated=True,
        )
    else:
        result = JaxWrapper.deploy(fn, (tuple(scalars), *leaves))
    if tail_builder is not None:
        return result
    for root, value in zip(roots, result):
        if isinstance(root, LazyExpr):
            root._result = value
    return list(result)


def leaf_buffer_ids(roots: Sequence[Any]) -> frozenset:
    """``id()`` of every concrete array leaf an expression forest consumes.

    The graftfuse donation path intersects its candidate columns with this
    set so only buffers the program actually receives are marked consumed —
    a candidate outside the forest must stay resident.
    """
    ids = set()
    seen = set()
    stack = list(roots)
    while stack:
        e = stack.pop()
        if isinstance(e, LazyExpr):
            if e._result is not None:
                ids.add(id(e._result))
                continue
            if id(e) in seen:
                continue
            seen.add(id(e))
            stack.extend(e.args)
        elif not isinstance(e, _SCALAR_TYPES) and hasattr(e, "dtype"):
            ids.add(id(e))
    return frozenset(ids)


def materialize_exprs(items: Sequence[Any]) -> List[Any]:
    """Concrete jax.Arrays for a mixed list of arrays/exprs (one jit)."""
    return run_fused(items)


def materialize(item: Any):
    if is_lazy(item):
        return run_fused([item])[0]
    return item._result if isinstance(item, LazyExpr) else item
