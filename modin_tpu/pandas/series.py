"""``Series`` — the pandas.Series-compatible distributed one-column frame.

Reference design: /root/reference/modin/pandas/series.py.  Internally a Series
is a one-column query compiler (column label ``__reduced__`` when unnamed);
the API squeezes on materialization.
"""

from __future__ import annotations

from typing import Any, Hashable, Iterator, Optional, Union

import numpy as np
import pandas
from pandas._libs.lib import no_default
from pandas.api.types import is_list_like
from pandas.core.dtypes.common import is_bool_dtype, is_integer

from modin_tpu.logging import disable_logging
from modin_tpu.pandas.base import BasePandasDataset, _install_fallbacks
from modin_tpu.utils import (
    MODIN_UNNAMED_SERIES_LABEL,
    _inherit_docstrings,
    hashable,
    try_cast_to_pandas,
)


@_inherit_docstrings(pandas.Series)
class Series(BasePandasDataset):
    _pandas_class = pandas.Series
    ndim = 1

    def __init__(
        self,
        data: Any = None,
        index: Any = None,
        dtype: Any = None,
        name: Any = None,
        copy: Any = None,
        query_compiler: Any = None,
    ) -> None:
        from modin_tpu.pandas.dataframe import DataFrame

        if query_compiler is not None:
            assert data is None and index is None
            query_compiler._shape_hint = "column"
            self._set_query_compiler(query_compiler)
            if name is not None:
                self.name = name
            return
        if isinstance(data, Series):
            if index is None and dtype is None:
                self._set_query_compiler(data._query_compiler.copy())
                if name is not None:
                    self.name = name
                return
            data = data._to_pandas()
        if isinstance(data, DataFrame):
            raise ValueError("Data cannot be a DataFrame")
        if isinstance(data, dict):
            data = {
                k: (try_cast_to_pandas(v, squeeze=True) if isinstance(v, BasePandasDataset) else v)
                for k, v in data.items()
            }
        pandas_series = pandas.Series(
            data=data, index=index, dtype=dtype, name=name, copy=copy
        )
        frame = pandas_series.to_frame(
            pandas_series.name
            if pandas_series.name is not None
            else MODIN_UNNAMED_SERIES_LABEL
        )
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        qc = FactoryDispatcher.from_pandas(frame)
        qc._shape_hint = "column"
        self._set_query_compiler(qc)

    # ------------------------------------------------------------------ #
    # Metadata
    # ------------------------------------------------------------------ #

    @property
    def name(self) -> Optional[Hashable]:
        columns = self._query_compiler.columns
        name = columns[0]
        if name == MODIN_UNNAMED_SERIES_LABEL:
            return None
        return name

    @name.setter
    def name(self, name: Optional[Hashable]) -> None:
        if name is None:
            name = MODIN_UNNAMED_SERIES_LABEL
        self._query_compiler.columns = pandas.Index([name])

    def rename(
        self,
        index: Any = None,
        *,
        axis: Any = None,
        copy: Any = None,
        inplace: bool = False,
        level: Any = None,
        errors: str = "ignore",
    ):
        non_mapping = index is None or (
            hashable(index) and not isinstance(index, (dict,))
            and not callable(index)
        )
        if non_mapping:
            if inplace:
                self.name = index
                return None
            result = self.copy()
            result.name = index
            return result
        result = self._default_to_pandas(
            "rename", index, level=level, errors=errors
        )
        if inplace:
            self._update_inplace(result._query_compiler)
            return None
        return result

    @property
    def dtype(self):
        return self._query_compiler.dtypes.iloc[0]

    @property
    def dtypes(self):
        return self.dtype

    @property
    def shape(self) -> tuple:
        return (len(self),)

    @property
    def hasnans(self) -> bool:
        return bool(self.isna().sum())

    @property
    def nbytes(self) -> int:
        return self._to_pandas().nbytes

    @property
    def is_unique(self) -> bool:
        return self.nunique(dropna=False) == len(self)

    @property
    def is_monotonic_increasing(self) -> bool:
        return self._query_compiler.is_monotonic_increasing()

    @property
    def is_monotonic_decreasing(self) -> bool:
        return self._query_compiler.is_monotonic_decreasing()

    @property
    def T(self) -> "Series":
        return self

    def transpose(self, *args: Any, **kwargs: Any) -> "Series":
        return self

    @property
    def array(self):
        return self._to_pandas().array

    def item(self):
        if len(self) != 1:
            raise ValueError("can only convert an array of size 1 to a Python scalar")
        return self._to_pandas().item()

    # ------------------------------------------------------------------ #
    # Display & materialization
    # ------------------------------------------------------------------ #

    def __repr__(self) -> str:
        import re

        num_rows = pandas.get_option("display.max_rows") or 60
        frame = self._build_repr_df(num_rows)
        series = frame[frame.columns[0]]
        if series.name == MODIN_UNNAMED_SERIES_LABEL:
            series.name = None
        result = repr(series)
        n = len(self)
        if n > num_rows:
            return re.sub(r"Length: \d+", f"Length: {n}", result)
        return result

    def _to_pandas(self) -> pandas.Series:
        df = self._query_compiler.to_pandas()
        series = df[df.columns[0]]
        if series.name == MODIN_UNNAMED_SERIES_LABEL:
            series.name = None
        return series

    def to_frame(self, name: Any = no_default):
        from modin_tpu.pandas.dataframe import DataFrame

        if name is no_default:
            name = self.name
        new_qc = self._query_compiler.copy()
        new_qc.columns = pandas.Index(
            [name if name is not None else MODIN_UNNAMED_SERIES_LABEL]
        )
        new_qc._shape_hint = None
        result = DataFrame(query_compiler=new_qc)
        if name is None:
            result.columns = pandas.Index([0])
        return result

    def to_list(self) -> list:
        return self._to_pandas().to_list()

    tolist = to_list

    def to_numpy(self, dtype: Any = None, copy: bool = False, na_value: Any = no_default, **kwargs: Any) -> np.ndarray:
        return (
            self._query_compiler.to_numpy(dtype=dtype, copy=copy, na_value=na_value)
            .flatten()
        )

    def to_dict(self, into: Any = dict) -> dict:
        return self._to_pandas().to_dict(into=into)

    # ------------------------------------------------------------------ #
    # Reductions returning scalars
    # ------------------------------------------------------------------ #

    def _reduce_dimension(self, query_compiler) -> Any:
        if not hasattr(query_compiler, "to_pandas"):
            return query_compiler
        result = query_compiler.to_pandas()
        if result.shape == (1, 1):
            return result.iloc[0, 0]
        return result.squeeze()

    def count(self, axis: Any = 0, numeric_only: bool = False):
        return super().count(axis=axis)

    def nunique(self, dropna: bool = True) -> int:
        result = self._query_compiler.nunique(axis=0, dropna=dropna)
        if hasattr(result, "to_pandas"):
            return int(result.to_pandas().iloc[0, 0])
        return int(result)

    def unique(self) -> np.ndarray:
        return self._query_compiler.unique().to_numpy().flatten()

    def value_counts(self, normalize: bool = False, sort: bool = True, ascending: bool = False, bins: Any = None, dropna: bool = True):
        qc = self._query_compiler.series_value_counts(
            normalize=normalize, sort=sort, ascending=ascending, bins=bins, dropna=dropna
        )
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def argmax(self, axis: Any = None, skipna: bool = True, *args: Any, **kwargs: Any) -> int:
        return self._default_to_pandas("argmax", axis=axis, skipna=skipna)

    def argmin(self, axis: Any = None, skipna: bool = True, *args: Any, **kwargs: Any) -> int:
        return self._default_to_pandas("argmin", axis=axis, skipna=skipna)

    def argsort(self, axis: Any = 0, kind: str = "quicksort", order: Any = None, stable: Any = None) -> "Series":
        qc = self._query_compiler.series_argsort(kind=kind)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def autocorr(self, lag: int = 1) -> float:
        return self._query_compiler.series_autocorr(lag=lag)

    def between(self, left: Any, right: Any, inclusive: str = "both") -> "Series":
        qc = self._query_compiler.series_between(left=left, right=right, inclusive=inclusive)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def corr(self, other: "Series", method: Any = "pearson", min_periods: Any = None) -> float:
        return self._default_to_pandas(
            "corr", try_cast_to_pandas(other, squeeze=True), method=method, min_periods=min_periods
        )

    def cov(self, other: "Series", min_periods: Any = None, ddof: int = 1) -> float:
        return self._default_to_pandas(
            "cov", try_cast_to_pandas(other, squeeze=True), min_periods=min_periods, ddof=ddof
        )

    def dot(self, other: Any):
        return self._binary_op("series_dot", other)

    def idxmax(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        result = self._query_compiler.idxmax(axis=0, skipna=skipna)
        return self._reduce_dimension(result)

    def idxmin(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        result = self._query_compiler.idxmin(axis=0, skipna=skipna)
        return self._reduce_dimension(result)

    def quantile(self, q: Any = 0.5, interpolation: str = "linear"):
        result_qc = self._query_compiler.quantile(q=q, interpolation=interpolation)
        if is_list_like(q):
            qc = result_qc
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return self._reduce_dimension(result_qc)

    def mode(self, dropna: bool = True) -> "Series":
        qc = self._query_compiler.mode(dropna=dropna)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def describe(self, percentiles: Any = None, include: Any = None, exclude: Any = None) -> "Series":
        qc = self._query_compiler.describe(percentiles=percentiles)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def all(self, axis: Any = 0, bool_only: bool = False, skipna: bool = True, **kwargs: Any):
        return super().all(axis=axis, bool_only=bool_only, skipna=skipna, **kwargs)

    def searchsorted(self, value: Any, side: str = "left", sorter: Any = None):
        result = self._query_compiler.searchsorted(value=value, side=side, sorter=sorter)
        arr = result.to_numpy().flatten()
        if np.isscalar(value) and len(arr) == 1:
            return arr[0]
        return arr

    # ------------------------------------------------------------------ #
    # Item access
    # ------------------------------------------------------------------ #

    def __getitem__(self, key: Any) -> Any:
        if isinstance(key, Series) and key.dtype == bool:
            return Series(
                query_compiler=self._query_compiler.getitem_array(key._query_compiler)
            )
        if isinstance(key, (np.ndarray, pandas.Series, list)) and getattr(
            np.asarray(key), "dtype", None
        ) == np.dtype(bool):
            return Series(
                query_compiler=self._query_compiler.getitem_array(np.asarray(key))
            )
        if isinstance(key, slice):
            # pandas: slices through [] are positional unless labels are non-ints
            if (is_integer(key.start) or key.start is None) and (
                is_integer(key.stop) or key.stop is None
            ):
                return self.iloc[key]
            return self.loc[key]
        if is_list_like(key):
            return self.loc[list(key)]
        return self.loc[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        if isinstance(value, BasePandasDataset):
            value = try_cast_to_pandas(value, squeeze=True)

        def setter(s: pandas.Series) -> pandas.Series:
            s = s.copy()
            s[key] = value
            return s

        df_setter = lambda df: setter(df.squeeze(axis=1)).to_frame(  # noqa: E731
            df.columns[0]
        )
        self._update_inplace(self._query_compiler.default_to_pandas(df_setter))

    @disable_logging
    def __getattr__(self, key: str) -> Any:
        return object.__getattribute__(self, key)

    def __iter__(self) -> Iterator:
        return iter(self._to_pandas())

    def __contains__(self, key: Hashable) -> bool:
        return key in self.index

    def keys(self) -> pandas.Index:
        return self.index

    def items(self) -> Iterator:
        return self._to_pandas().items()

    # ------------------------------------------------------------------ #
    # Function application
    # ------------------------------------------------------------------ #

    def apply(self, func: Any, convert_dtype: Any = no_default, args: tuple = (), *, by_row: Any = "compat", **kwargs: Any):
        result = self._default_to_pandas("apply", func, args=args, **kwargs)
        return result

    def map(self, arg: Any, na_action: Any = None, **kwargs: Any) -> "Series":
        if isinstance(arg, Series):
            arg = arg._to_pandas()
        if not kwargs:
            # dict mappings ride the QC (device translate for dict-encoded
            # string columns / numeric lookup kernel); other args take the
            # generated pandas default inside the same QC method
            return Series(
                query_compiler=self._query_compiler.series_map(
                    arg, na_action=na_action
                )
            )
        return self._default_to_pandas("map", arg, na_action=na_action, **kwargs)

    def aggregate(self, func: Any = None, axis: Any = 0, *args: Any, **kwargs: Any):
        return self._default_to_pandas("agg", func, axis, *args, **kwargs)

    agg = aggregate

    def groupby(
        self,
        by: Any = None,
        level: Any = None,
        as_index: bool = True,
        sort: bool = True,
        group_keys: bool = True,
        observed: Any = True,
        dropna: bool = True,
    ):
        from modin_tpu.pandas.groupby import SeriesGroupBy

        if by is None and level is None:
            raise TypeError("You have to supply one of 'by' and 'level'")
        return SeriesGroupBy(
            self,
            by=by,
            level=level,
            as_index=as_index,
            sort=sort,
            group_keys=group_keys,
            observed=observed,
            dropna=dropna,
        )

    # ------------------------------------------------------------------ #
    # Ordering / structure
    # ------------------------------------------------------------------ #

    def sort_values(
        self,
        *,
        axis: Any = 0,
        ascending: Any = True,
        inplace: bool = False,
        kind: str = "quicksort",
        na_position: str = "last",
        ignore_index: bool = False,
        key: Any = None,
    ):
        from modin_tpu.pandas.dataframe import DataFrame

        # sort via the single-column frame
        frame = self.to_frame("__sort_col__")
        sorted_frame = frame.sort_values(
            by="__sort_col__",
            ascending=ascending,
            kind=kind,
            na_position=na_position,
            ignore_index=ignore_index,
            key=key,
        )
        qc = sorted_frame._query_compiler.copy()
        qc.columns = pandas.Index(
            [self.name if self.name is not None else MODIN_UNNAMED_SERIES_LABEL]
        )
        qc._shape_hint = "column"
        result = Series(query_compiler=qc)
        if inplace:
            self._update_inplace(result._query_compiler)
            return None
        return result

    def nlargest(self, n: int = 5, keep: str = "first") -> "Series":
        return self._default_to_pandas("nlargest", n=n, keep=keep)

    def nsmallest(self, n: int = 5, keep: str = "first") -> "Series":
        return self._default_to_pandas("nsmallest", n=n, keep=keep)

    def explode(self, ignore_index: bool = False) -> "Series":
        return self._default_to_pandas("explode", ignore_index=ignore_index)

    def repeat(self, repeats: Any, axis: Any = None) -> "Series":
        qc = self._query_compiler.repeat(repeats)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def duplicated(self, keep: Any = "first") -> "Series":
        # pandas keeps the series name on the boolean result
        return self.to_frame("__dup__").duplicated(keep=keep).rename(self.name)

    def drop_duplicates(self, *, keep: Any = "first", inplace: bool = False, ignore_index: bool = False):
        # value-dedup of a Series IS row-dedup of its single-column frame
        new_qc = self._query_compiler.drop_duplicates(
            subset=None, keep=keep, ignore_index=ignore_index
        )
        new_qc._shape_hint = "column"
        if inplace:
            self._update_inplace(new_qc)
            return None
        return Series(query_compiler=new_qc)

    def _series_reset_index(self, level: Any, inplace: bool):
        """reset_index(drop=False) — becomes a DataFrame."""
        from modin_tpu.pandas.dataframe import DataFrame

        if inplace:
            raise TypeError(
                "Cannot reset_index inplace on a Series to create a DataFrame"
            )
        pandas_result = self._to_pandas().reset_index(level=level, drop=False)
        return self._wrap_pandas(pandas_result)

    def reset_index(self, level: Any = None, *, drop: bool = False, name: Any = no_default, inplace: bool = False, allow_duplicates: bool = False):
        if drop and level is None:
            new_qc = self._query_compiler.reset_index(drop=True)
            new_qc._shape_hint = "column"
            if not inplace:
                result = Series(query_compiler=new_qc)
                if name is not no_default:
                    result.name = name
                return result
            self._update_inplace(new_qc)
            return None
        obj = self.copy()
        if name is not no_default:
            obj.name = name
        return obj._series_reset_index(level, inplace)

    def update(self, other: Any) -> None:
        if not isinstance(other, Series):
            other = Series(other)
        qc = self._query_compiler.series_update(other._query_compiler)
        self._update_inplace(qc)

    def case_when(self, caselist: list) -> "Series":
        caselist = [
            tuple(
                c._query_compiler if isinstance(c, Series) else c for c in case
            )
            for case in caselist
        ]
        qc = self._query_compiler.case_when(caselist)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def isin(self, values: Any) -> "Series":
        result = super().isin(values)
        result._query_compiler._shape_hint = "column"
        return result

    def where(self, cond: Any, other: Any = np.nan, *, inplace: bool = False, axis: Any = None, level: Any = None):
        return super().where(cond, other, inplace=inplace, axis=axis, level=level)

    def ravel(self, order: str = "C") -> np.ndarray:
        return self.to_numpy()

    def compare(self, other: "Series", align_axis: Any = 1, keep_shape: bool = False, keep_equal: bool = False, result_names: Any = ("self", "other")):
        return self._default_to_pandas(
            "compare", try_cast_to_pandas(other, squeeze=True), align_axis=align_axis,
            keep_shape=keep_shape, keep_equal=keep_equal, result_names=result_names,
        )

    def equals(self, other: Any) -> bool:
        return self._query_compiler.equals(
            other._query_compiler if isinstance(other, Series) else other
        )

    def pop(self, item: Any):
        result = self[item]
        self.drop(labels=[item], inplace=True)
        return result

    def divmod(self, other: Any, level: Any = None, fill_value: Any = None, axis: Any = 0):
        div, mod = self._query_compiler.divmod(
            try_cast_to_pandas(other, squeeze=True),
            level=level, fill_value=fill_value, axis=axis,
        )
        return self.__constructor__(div), self.__constructor__(mod)

    def rdivmod(self, other: Any, level: Any = None, fill_value: Any = None, axis: Any = 0):
        div, mod = self._query_compiler.rdivmod(
            try_cast_to_pandas(other, squeeze=True),
            level=level, fill_value=fill_value, axis=axis,
        )
        return self.__constructor__(div), self.__constructor__(mod)

    # ------------------------------------------------------------------ #
    # Accessors
    # ------------------------------------------------------------------ #

    @property
    def str(self):
        from modin_tpu.pandas.series_utils import StringMethods

        return StringMethods(self)

    @property
    def dt(self):
        from modin_tpu.pandas.series_utils import DatetimeProperties

        return DatetimeProperties(self)

    @property
    def cat(self):
        from modin_tpu.pandas.series_utils import CategoryMethods

        return CategoryMethods(self)

    @property
    def list(self):
        from modin_tpu.pandas.series_utils import ListAccessor

        return ListAccessor(self)

    @property
    def struct(self):
        from modin_tpu.pandas.series_utils import StructAccessor

        return StructAccessor(self)

    @property
    def plot(self):
        return self._to_pandas().plot

    @property
    def modin(self):
        from modin_tpu.pandas.accessor import ModinAPI

        return ModinAPI(self)

    # ------------------------------------------------------------------ #
    # IO
    # ------------------------------------------------------------------ #

    def __divmod__(self, other: Any):
        return self.divmod(other)

    def __rdivmod__(self, other: Any):
        return self.rdivmod(other)

    def __matmul__(self, other: Any):
        return self.dot(other)

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())


_install_fallbacks(Series, pandas.Series)
