"""API-layer helpers: wrapping pandas objects into modin_tpu objects and back.

Reference design: /root/reference/modin/pandas/utils.py.
"""

from __future__ import annotations

from typing import Any

import pandas
from pandas.util._decorators import doc

from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL


def from_pandas(df: pandas.DataFrame):
    """Convert a pandas DataFrame to a modin_tpu DataFrame on the current backend."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )
    from modin_tpu.pandas import DataFrame

    return DataFrame(query_compiler=FactoryDispatcher.from_pandas(df))


def from_arrow(at: Any):
    """Convert a pyarrow Table to a modin_tpu DataFrame."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )
    from modin_tpu.pandas import DataFrame

    return DataFrame(query_compiler=FactoryDispatcher.from_arrow(at))


def from_non_pandas(df: Any, index: Any, columns: Any, dtype: Any):
    """Try converting an arbitrary object via the engine's from_non_pandas hook."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )

    new_qc = FactoryDispatcher.from_non_pandas(df, index, columns, dtype)
    if new_qc is not None:
        from modin_tpu.pandas import DataFrame

        return DataFrame(query_compiler=new_qc)
    return new_qc


def from_dataframe(df: Any):
    """Convert an interchange-protocol object to a modin_tpu DataFrame."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )
    from modin_tpu.pandas import DataFrame

    return DataFrame(query_compiler=FactoryDispatcher.from_interchange_dataframe(df))


def is_scalar(obj: Any) -> bool:
    """Whether obj is a scalar (never true for modin_tpu objects)."""
    from pandas.api.types import is_scalar as pandas_is_scalar

    from modin_tpu.pandas.base import BasePandasDataset

    return not isinstance(obj, BasePandasDataset) and pandas_is_scalar(obj)


def is_full_grab_slice(slc: slice, sequence_len: Any = None) -> bool:
    """Whether the slice grabs the whole axis."""
    assert isinstance(slc, slice), "slice object required"
    return (
        slc.start in (None, 0)
        and slc.step in (None, 1)
        and (
            slc.stop is None
            or (isinstance(slc.stop, int) and sequence_len is not None and slc.stop >= sequence_len)
        )
    )


def from_modin_frame_to_mi(df: Any, sortorder: Any = None, names: Any = None):
    """Create a pandas MultiIndex from a modin_tpu DataFrame."""
    from modin_tpu.pandas import DataFrame

    if isinstance(df, DataFrame):
        df = df._to_pandas()
    return pandas.MultiIndex.from_frame(df, sortorder=sortorder, names=names)


def cast_function_modin2pandas(func: Any) -> Any:
    """Replace a modin_tpu method reference with its pandas counterpart."""
    if callable(func):
        module = getattr(func, "__module__", "") or ""
        if module.startswith("modin_tpu.pandas"):
            name = func.__name__
            if module.endswith("series"):
                return getattr(pandas.Series, name, func)
            return getattr(pandas.DataFrame, name, func)
    return func


SET_DATAFRAME_ATTRIBUTE_WARNING = (
    "modin_tpu doesn't allow columns to be created via a new attribute name - see "
    "https://pandas.pydata.org/pandas-docs/stable/indexing.html#attribute-access"
)

GET_BACKEND_DOC = "Get the current backend name for this object."
SET_BACKEND_DOC = "Move this object's data to the named backend."
