"""``DataFrame`` — the pandas.DataFrame-compatible distributed frame.

Reference design: /root/reference/modin/pandas/dataframe.py.  Holds no data;
owns only a ``_query_compiler`` handle (reference: dataframe.py:147-212).
"""

from __future__ import annotations

import functools
import itertools
import re
from typing import Any, Hashable, Iterator, Optional, Sequence, Union

import numpy as np
import pandas
from pandas._libs.lib import no_default
from pandas.api.types import is_list_like
from pandas.core.dtypes.common import is_bool_dtype, is_integer

from modin_tpu.error_message import ErrorMessage
from modin_tpu.logging import disable_logging
from modin_tpu.pandas.base import BasePandasDataset, _install_fallbacks
from modin_tpu.utils import (
    MODIN_UNNAMED_SERIES_LABEL,
    _inherit_docstrings,
    hashable,
    try_cast_to_pandas,
)


@_inherit_docstrings(pandas.DataFrame)
class DataFrame(BasePandasDataset):
    _pandas_class = pandas.DataFrame
    ndim = 2

    def __init__(
        self,
        data: Any = None,
        index: Any = None,
        columns: Any = None,
        dtype: Any = None,
        copy: Any = None,
        query_compiler: Any = None,
    ) -> None:
        from modin_tpu.pandas.series import Series

        if query_compiler is not None:
            assert (
                data is None and index is None and columns is None
            ), "Cannot pass both query_compiler and data/index/columns"
            self._set_query_compiler(query_compiler)
            return
        if isinstance(data, DataFrame):
            if index is None and columns is None and dtype is None:
                self._set_query_compiler(data._query_compiler.copy())
                return
            pandas_df = data._to_pandas()
            new_pandas = pandas.DataFrame(
                pandas_df, index=index, columns=columns, dtype=dtype, copy=copy
            )
            self._set_query_compiler(self._from_pandas_qc(new_pandas))
            return
        if isinstance(data, Series):
            data = data._to_pandas()
        if isinstance(data, pandas.DataFrame):
            if index is None and columns is None and dtype is None:
                self._set_query_compiler(self._from_pandas_qc(data.copy()))
                return
            data = pandas.DataFrame(
                data, index=index, columns=columns, dtype=dtype, copy=copy
            )
            self._set_query_compiler(self._from_pandas_qc(data))
            return
        elif isinstance(data, dict):
            data = {
                k: try_cast_to_pandas(v) if isinstance(v, BasePandasDataset) else v
                for k, v in data.items()
            }
        elif is_list_like(data) and not isinstance(data, np.ndarray):
            data = [
                try_cast_to_pandas(v) if isinstance(v, BasePandasDataset) else v
                for v in data
            ]
        pandas_df = pandas.DataFrame(
            data=data, index=index, columns=columns, dtype=dtype, copy=copy
        )
        self._set_query_compiler(self._from_pandas_qc(pandas_df))

    @staticmethod
    def _from_pandas_qc(pandas_df: pandas.DataFrame):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.from_pandas(pandas_df)

    # ------------------------------------------------------------------ #
    # Metadata
    # ------------------------------------------------------------------ #

    def _get_columns(self) -> pandas.Index:
        return self._query_compiler.columns

    def _set_columns(self, new_columns: Any) -> None:
        self._query_compiler.columns = (
            new_columns
            if isinstance(new_columns, pandas.Index)
            else pandas.Index(new_columns)
        )

    columns = property(_get_columns, _set_columns)

    @property
    def shape(self) -> tuple:
        return len(self.index), len(self.columns)

    @property
    def T(self) -> "DataFrame":
        return self.transpose()

    def transpose(self, copy: bool = False, *args: Any) -> "DataFrame":
        return DataFrame(query_compiler=self._query_compiler.transpose(*args))

    # ------------------------------------------------------------------ #
    # Display
    # ------------------------------------------------------------------ #

    def __repr__(self) -> str:
        num_rows = pandas.get_option("display.max_rows") or len(self)
        num_cols = pandas.get_option("display.max_columns") or len(self.columns)
        result = repr(self._build_repr_df(num_rows, num_cols))
        nrows, ncols = self.shape
        if nrows > num_rows or ncols > num_cols:
            return re.sub(
                r"\[\d+ rows x \d+ columns\]",
                f"[{nrows} rows x {ncols} columns]",
                result,
            )
        return result

    def _repr_html_(self) -> str:
        num_rows = pandas.get_option("display.max_rows") or 60
        num_cols = pandas.get_option("display.max_columns") or 20
        result = self._build_repr_df(num_rows, num_cols)._repr_html_()
        nrows, ncols = self.shape
        if nrows > num_rows or ncols > num_cols:
            return re.sub(
                r"<p>\d+ rows [x×] \d+ columns</p>",
                f"<p>{nrows} rows x {ncols} columns</p>",
                result,
            )
        return result

    # ------------------------------------------------------------------ #
    # Materialization
    # ------------------------------------------------------------------ #

    def _to_pandas(self) -> pandas.DataFrame:
        return self._query_compiler.to_pandas()

    def __dataframe__(self, nan_as_null: bool = False, allow_copy: bool = True):
        return self._query_compiler.to_interchange_dataframe(
            nan_as_null=nan_as_null, allow_copy=allow_copy
        )

    # ------------------------------------------------------------------ #
    # Item access
    # ------------------------------------------------------------------ #

    def __getitem__(self, key: Any) -> Any:
        from modin_tpu.pandas.series import Series

        if isinstance(key, (Series, np.ndarray, pandas.Series)) and (
            getattr(key, "dtype", None) is not None and is_bool_dtype(key.dtype)
        ):
            if isinstance(key, Series):
                return DataFrame(
                    query_compiler=self._query_compiler.getitem_array(
                        key._query_compiler
                    )
                )
            return DataFrame(query_compiler=self._query_compiler.getitem_array(np.asarray(key)))
        if isinstance(key, DataFrame):
            return self.where(key)
        if isinstance(key, slice):
            if (is_integer(key.start) or key.start is None) and (
                is_integer(key.stop) or key.stop is None
            ):
                return self.iloc[key]
            return self.loc[key]
        if isinstance(key, tuple) and isinstance(self.columns, pandas.MultiIndex):
            return self._default_to_pandas(lambda df: df[key])
        if hashable(key):
            if key not in self.columns:
                raise KeyError(key)
            return self._getitem_column(key)
        if is_list_like(key):
            key_list = list(key)
            if len(key_list) and np.asarray(key_list).dtype == bool:
                return DataFrame(
                    query_compiler=self._query_compiler.getitem_array(
                        np.asarray(key_list)
                    )
                )
            missing = [k for k in key_list if k not in self.columns]
            if missing:
                raise KeyError(f"{missing} not in index")
            return DataFrame(
                query_compiler=self._query_compiler.getitem_column_array(key_list)
            )
        return self._default_to_pandas(lambda df: df[key])

    def _getitem_column(self, key: Hashable):
        from modin_tpu.pandas.series import Series

        positions = self.columns.get_indexer_for([key])
        if len(positions) > 1:
            return DataFrame(
                query_compiler=self._query_compiler.getitem_column_array(
                    list(positions), numeric=True
                )
            )
        qc = self._query_compiler.getitem_column_array([key])
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def __setitem__(self, key: Any, value: Any) -> None:
        from modin_tpu.pandas.series import Series

        if isinstance(value, BasePandasDataset):
            value = value._query_compiler
        if hashable(key) and not isinstance(key, tuple):
            self._update_inplace(self._query_compiler.setitem(0, key, value))
            return
        # fancy cases: boolean mask rows, multiple columns, tuples
        def setter(df: pandas.DataFrame) -> pandas.DataFrame:
            df = df.copy()
            df[key] = try_cast_to_pandas(value)
            return df

        self._update_inplace(self._query_compiler.default_to_pandas(setter))

    def __delitem__(self, key: Any) -> None:
        if key not in self.columns:
            raise KeyError(key)
        self._update_inplace(self._query_compiler.drop(columns=[key]))

    @disable_logging
    def __getattr__(self, key: str) -> Any:
        try:
            return object.__getattribute__(self, key)
        except AttributeError as err:
            if key not in _ATTRS_NO_LOOKUP:
                qc = object.__getattribute__(self, "_query_compiler")
                if qc is not None and key in qc.columns:
                    return self[key]
            raise err

    def __setattr__(self, key: str, value: Any) -> None:
        if key in ("_query_compiler", "_siblings", "_attrs"):
            object.__setattr__(self, key, value)
            return
        if key in type(self).__dict__ or key in BasePandasDataset.__dict__:
            object.__setattr__(self, key, value)
            return
        qc = getattr(self, "_query_compiler", None)
        if qc is not None and key in qc.columns:
            self[key] = value
            return
        if qc is not None and isinstance(value, (pandas.Series,)):
            import warnings

            from modin_tpu.pandas.utils import SET_DATAFRAME_ATTRIBUTE_WARNING

            warnings.warn(SET_DATAFRAME_ATTRIBUTE_WARNING, UserWarning)
        object.__setattr__(self, key, value)

    def __iter__(self) -> Iterator[Hashable]:
        return iter(self.columns)

    def __contains__(self, key: Hashable) -> bool:
        return self.columns.__contains__(key)

    def keys(self) -> pandas.Index:
        return self.columns

    # ------------------------------------------------------------------ #
    # Column/row manipulation
    # ------------------------------------------------------------------ #

    def insert(self, loc: int, column: Hashable, value: Any, allow_duplicates: Any = no_default) -> None:
        if (
            allow_duplicates is not True and column in self.columns
        ):
            raise ValueError(f"cannot insert {column}, already exists")
        if not -len(self.columns) <= loc <= len(self.columns):
            raise IndexError(
                f"index {loc} is out of bounds for axis 0 with size {len(self.columns)}"
            )
        if isinstance(value, BasePandasDataset):
            value = value._query_compiler
        self._update_inplace(self._query_compiler.insert(loc, column, value))

    def pop(self, item: Hashable):
        result = self[item]
        self._update_inplace(self._query_compiler.drop(columns=[item]))
        return result

    def rename(
        self,
        mapper: Any = None,
        *,
        index: Any = None,
        columns: Any = None,
        axis: Any = None,
        copy: Any = None,
        inplace: bool = False,
        level: Any = None,
        errors: str = "ignore",
    ):
        if mapper is None and index is None and columns is None:
            raise TypeError("must pass an index to rename")
        if mapper is not None:
            axis_num = self._get_axis_number(axis) if axis is not None else 0
            if axis_num == 0:
                index = mapper
            else:
                columns = mapper
        new_qc = self._query_compiler
        if columns is not None and level is None and not callable(columns) and not isinstance(self.columns, pandas.MultiIndex):
            if errors == "raise":
                missing = [c for c in columns if c not in self.columns]
                if missing:
                    raise KeyError(f"{missing} not found in axis")
            new_columns = [
                columns.get(c, c) if isinstance(columns, dict) else c
                for c in self.columns
            ]
            new_qc = new_qc.copy()
            new_qc.columns = pandas.Index(new_columns, name=self.columns.name)
            columns = None
        if index is not None or columns is not None or level is not None:
            result = new_qc.default_to_pandas(
                pandas.DataFrame.rename,
                index=index,
                columns=columns,
                level=level,
                errors=errors,
            )
            new_qc = result
        return self._create_or_update_from_compiler(new_qc, inplace)

    def set_index(self, keys: Any, *, drop: bool = True, append: bool = False, inplace: bool = False, verify_integrity: bool = False):
        if not isinstance(keys, list):
            keys = [keys]
        from modin_tpu.pandas.series import Series

        keys = [
            k._to_pandas() if isinstance(k, Series) else k for k in keys
        ]
        plain_labels = all(hashable(k) and not isinstance(k, (pandas.Series, pandas.Index, np.ndarray)) for k in keys)
        if plain_labels:
            for k in keys:
                if k not in self.columns:
                    raise KeyError(f"None of {[k]} are in the columns")
            new_qc = self._query_compiler.set_index_from_columns(
                keys, drop=drop, append=append
            )
        else:
            new_qc = self._query_compiler.default_to_pandas(
                pandas.DataFrame.set_index,
                keys,
                drop=drop,
                append=append,
                verify_integrity=verify_integrity,
            )
        return self._create_or_update_from_compiler(new_qc, inplace)

    def sort_values(
        self,
        by: Any,
        *,
        axis: Any = 0,
        ascending: Any = True,
        inplace: bool = False,
        kind: str = "quicksort",
        na_position: str = "last",
        ignore_index: bool = False,
        key: Any = None,
    ):
        axis = self._get_axis_number(axis)
        ascending = self._validate_ascending(ascending)
        if not is_list_like(by):
            by = [by]
        if axis == 0:
            missing = [b for b in by if b not in self.columns and b not in (self.index.names or [])]
            if missing:
                raise KeyError(missing[0])
            new_qc = self._query_compiler.sort_rows_by_column_values(
                by,
                ascending=ascending,
                kind=kind,
                na_position=na_position,
                ignore_index=ignore_index,
                key=key,
            )
        else:
            new_qc = self._query_compiler.sort_columns_by_row_values(
                by,
                ascending=ascending,
                kind=kind,
                na_position=na_position,
                key=key,
            )
        return self._create_or_update_from_compiler(new_qc, inplace)

    @staticmethod
    def _validate_ascending(ascending: Any) -> Any:
        if isinstance(ascending, (list, tuple)):
            return list(ascending)
        return bool(ascending)

    def nlargest(self, n: int, columns: Any, keep: str = "first") -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.nlargest(n=n, columns=columns, keep=keep)
        )

    def nsmallest(self, n: int, columns: Any, keep: str = "first") -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.nsmallest(n=n, columns=columns, keep=keep)
        )

    def duplicated(self, subset: Any = None, keep: Any = "first"):
        from modin_tpu.pandas.series import Series

        qc = self._query_compiler.duplicated(subset=subset, keep=keep)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)

    def drop_duplicates(self, subset: Any = None, *, keep: Any = "first", inplace: bool = False, ignore_index: bool = False):
        new_qc = self._query_compiler.drop_duplicates(
            subset=subset, keep=keep, ignore_index=ignore_index
        )
        return self._create_or_update_from_compiler(new_qc, inplace)

    # ------------------------------------------------------------------ #
    # Combination
    # ------------------------------------------------------------------ #

    def merge(
        self,
        right: Any,
        how: str = "inner",
        on: Any = None,
        left_on: Any = None,
        right_on: Any = None,
        left_index: bool = False,
        right_index: bool = False,
        sort: bool = False,
        suffixes: Any = ("_x", "_y"),
        copy: Any = None,
        indicator: bool = False,
        validate: Any = None,
    ) -> "DataFrame":
        from modin_tpu.pandas.series import Series

        if isinstance(right, Series):
            if right.name is None:
                raise ValueError("Cannot merge a Series without a name")
            right = right.to_frame()
        if not isinstance(right, DataFrame):
            raise TypeError(
                f"Can only merge Series or DataFrame objects, a {type(right)} was passed"
            )
        return DataFrame(
            query_compiler=self._query_compiler.merge(
                right._query_compiler,
                how=how,
                on=on,
                left_on=left_on,
                right_on=right_on,
                left_index=left_index,
                right_index=right_index,
                sort=sort,
                suffixes=suffixes,
                indicator=indicator,
                validate=validate,
            )
        )

    def join(
        self,
        other: Any,
        on: Any = None,
        how: str = "left",
        lsuffix: str = "",
        rsuffix: str = "",
        sort: bool = False,
        validate: Any = None,
    ) -> "DataFrame":
        from modin_tpu.pandas.series import Series

        if isinstance(other, Series):
            if other.name is None:
                raise ValueError("Other Series must have a name")
            other = other.to_frame()
        if isinstance(other, DataFrame):
            other = other._query_compiler
        elif is_list_like(other):
            other = [
                o._query_compiler if isinstance(o, (DataFrame, Series)) else o
                for o in other
            ]
        return DataFrame(
            query_compiler=self._query_compiler.join(
                other,
                on=on,
                how=how,
                lsuffix=lsuffix,
                rsuffix=rsuffix,
                sort=sort,
                validate=validate,
            )
        )

    def update(self, other: Any, join: str = "left", overwrite: bool = True, filter_func: Any = None, errors: str = "ignore") -> None:
        if not isinstance(other, DataFrame):
            other = DataFrame(other)
        qc = self._query_compiler.df_update(
            other._query_compiler,
            join=join,
            overwrite=overwrite,
            filter_func=filter_func,
            errors=errors,
        )
        self._update_inplace(qc)

    def assign(self, **kwargs: Any) -> "DataFrame":
        df = self.copy()
        for k, v in kwargs.items():
            if callable(v):
                df[k] = v(df)
            else:
                df[k] = v
        return df

    def compare(self, other: Any, align_axis: Any = 1, keep_shape: bool = False, keep_equal: bool = False, result_names: Any = ("self", "other")) -> "DataFrame":
        if not isinstance(other, DataFrame):
            raise TypeError(f"can only compare with DataFrame, not {type(other)}")
        return DataFrame(
            query_compiler=self._query_compiler.compare(
                other._query_compiler,
                align_axis=align_axis,
                keep_shape=keep_shape,
                keep_equal=keep_equal,
                result_names=result_names,
            )
        )

    # ------------------------------------------------------------------ #
    # Groupby
    # ------------------------------------------------------------------ #

    def groupby(
        self,
        by: Any = None,
        level: Any = None,
        as_index: bool = True,
        sort: bool = True,
        group_keys: bool = True,
        observed: Any = True,
        dropna: bool = True,
    ):
        from modin_tpu.pandas.groupby import DataFrameGroupBy

        if by is None and level is None:
            raise TypeError("You have to supply one of 'by' and 'level'")
        return DataFrameGroupBy(
            self,
            by=by,
            level=level,
            as_index=as_index,
            sort=sort,
            group_keys=group_keys,
            observed=observed,
            dropna=dropna,
        )

    # ------------------------------------------------------------------ #
    # Function application
    # ------------------------------------------------------------------ #

    def apply(
        self,
        func: Any,
        axis: Any = 0,
        raw: bool = False,
        result_type: Any = None,
        args: tuple = (),
        by_row: Any = "compat",
        engine: Any = "python",
        engine_kwargs: Any = None,
        **kwargs: Any,
    ):
        axis = self._get_axis_number(axis)
        result_qc = self._query_compiler.apply(
            func,
            axis=axis,
            raw=raw,
            result_type=result_type,
            args=args,
            **kwargs,
        )
        if not hasattr(result_qc, "to_pandas"):
            return result_qc
        result = DataFrame(query_compiler=result_qc)
        # pandas may reduce to a Series
        if (
            len(result.columns) == 1
            and result.columns[0] == MODIN_UNNAMED_SERIES_LABEL
        ):
            from modin_tpu.pandas.series import Series

            result_qc._shape_hint = "column"
            return Series(query_compiler=result_qc)
        return result

    def map(self, func: Any, na_action: Any = None, **kwargs: Any) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.map(func, na_action=na_action, **kwargs)
        )

    def applymap(self, func: Any, na_action: Any = None, **kwargs: Any) -> "DataFrame":
        # removed in pandas 3; kept for compatibility with older user code
        return self.map(func, na_action=na_action, **kwargs)

    _AGG_REDUCTIONS = frozenset(
        ["sum", "mean", "min", "max", "prod", "product", "count", "median",
         "std", "var", "sem", "skew", "kurt", "any", "all"]
    )

    def aggregate(self, func: Any = None, axis: Any = 0, *args: Any, **kwargs: Any):
        # a bare named reduction IS that reduction (pandas applies the same
        # Series method per column): route it through the reduction surface
        # so the device kernels — and a pending graftplan — serve it instead
        # of a host materialization
        if (
            isinstance(func, str)
            and func in self._AGG_REDUCTIONS
            and not args
            and not kwargs
            and self._get_axis_number(axis) == 0
        ):
            return getattr(self, func)()
        return self._default_to_pandas("agg", func, axis, *args, **kwargs)

    agg = aggregate

    def corr(self, method: Any = "pearson", min_periods: int = 1, numeric_only: bool = False) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.corr(
                method=method, min_periods=min_periods, numeric_only=numeric_only
            )
        )

    def cov(self, min_periods: Any = None, ddof: int = 1, numeric_only: bool = False) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.cov(
                min_periods=min_periods, ddof=ddof, numeric_only=numeric_only
            )
        )

    def corrwith(self, other: Any, axis: Any = 0, drop: bool = False, method: Any = "pearson", numeric_only: bool = False):
        return self._reduce_dimension(
            self._query_compiler.corrwith(
                other._query_compiler if isinstance(other, BasePandasDataset) else other,
                axis=axis, drop=drop, method=method, numeric_only=numeric_only,
            )
        )

    def equals(self, other: Any) -> bool:
        return self._query_compiler.equals(
            other._query_compiler if isinstance(other, BasePandasDataset) else other
        )

    def select_dtypes(self, include: Any = None, exclude: Any = None) -> "DataFrame":
        # metadata-only: pandas resolves the include/exclude rules against an
        # EMPTY shell with our dtypes, then we slice columns positionally —
        # no device data moves
        shell = pandas.DataFrame(
            {i: pandas.Series(dtype=dt) for i, dt in enumerate(self.dtypes)}
        )
        keep = list(shell.select_dtypes(include=include, exclude=exclude).columns)
        return DataFrame(
            query_compiler=self._query_compiler.take_2d_positional(columns=keep)
        )

    def dot(self, other: Any):
        return self._binary_op("dot", other)

    def idxmin(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False):
        axis = self._get_axis_number(axis)
        return self._reduce_dimension(
            self._query_compiler.idxmin(axis=axis, skipna=skipna, numeric_only=numeric_only)
        )

    def idxmax(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False):
        axis = self._get_axis_number(axis)
        return self._reduce_dimension(
            self._query_compiler.idxmax(axis=axis, skipna=skipna, numeric_only=numeric_only)
        )

    def quantile(
        self,
        q: Any = 0.5,
        axis: Any = 0,
        numeric_only: bool = False,
        interpolation: str = "linear",
        method: str = "single",
    ):
        axis = self._get_axis_number(axis)
        result_qc = self._query_compiler.quantile(
            q=q, axis=axis, numeric_only=numeric_only,
            interpolation=interpolation, method=method,
        )
        if is_list_like(q):
            return DataFrame(query_compiler=result_qc)
        return self._reduce_dimension(result_qc)

    def mode(self, axis: Any = 0, numeric_only: bool = False, dropna: bool = True) -> "DataFrame":
        axis = self._get_axis_number(axis)
        return DataFrame(
            query_compiler=self._query_compiler.mode(
                axis=axis, numeric_only=numeric_only, dropna=dropna
            )
        )

    def describe(self, percentiles: Any = None, include: Any = None, exclude: Any = None) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.describe(
                percentiles=percentiles, include=include, exclude=exclude
            )
        )

    def round(self, decimals: Any = 0, *args: Any, **kwargs: Any) -> "DataFrame":
        if isinstance(decimals, BasePandasDataset):
            decimals = try_cast_to_pandas(decimals, squeeze=True)
        return DataFrame(query_compiler=self._query_compiler.round(decimals=decimals))

    # ------------------------------------------------------------------ #
    # Reshaping
    # ------------------------------------------------------------------ #

    def pivot(self, *, columns: Any, index: Any = no_default, values: Any = no_default) -> "DataFrame":
        kwargs = {"columns": columns}
        if index is not no_default:
            kwargs["index"] = index
        if values is not no_default:
            kwargs["values"] = values
        return DataFrame(query_compiler=self._query_compiler.pivot(**kwargs))

    def pivot_table(
        self,
        values: Any = None,
        index: Any = None,
        columns: Any = None,
        aggfunc: Any = "mean",
        fill_value: Any = None,
        margins: bool = False,
        dropna: bool = True,
        margins_name: str = "All",
        observed: Any = True,
        sort: bool = True,
    ) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.pivot_table(
                values=try_cast_to_pandas(values, squeeze=True),
                index=try_cast_to_pandas(index, squeeze=True),
                columns=try_cast_to_pandas(columns, squeeze=True),
                aggfunc=try_cast_to_pandas(aggfunc),
                fill_value=fill_value, margins=margins, dropna=dropna,
                margins_name=margins_name, observed=observed, sort=sort,
            )
        )

    def melt(
        self,
        id_vars: Any = None,
        value_vars: Any = None,
        var_name: Any = None,
        value_name: Any = "value",
        col_level: Any = None,
        ignore_index: bool = True,
    ) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.melt(
                id_vars=id_vars, value_vars=value_vars, var_name=var_name,
                value_name=value_name, col_level=col_level, ignore_index=ignore_index,
            )
        )

    def stack(self, level: Any = -1, dropna: Any = no_default, sort: Any = no_default, future_stack: bool = True):
        kwargs = {"level": level}
        if dropna is not no_default:
            kwargs["dropna"] = dropna
        if sort is not no_default:
            kwargs["sort"] = sort
        result = self._query_compiler.stack(**kwargs)
        return self._wrap_from_qc_auto(result)

    def unstack(self, level: Any = -1, fill_value: Any = None, sort: bool = True):
        result = self._query_compiler.unstack(level=level, fill_value=fill_value)
        return self._wrap_from_qc_auto(result)

    def _wrap_from_qc_auto(self, qc: Any):
        """Wrap a QC as Series if single unnamed column, else DataFrame."""
        from modin_tpu.pandas.series import Series

        if not hasattr(qc, "to_pandas"):
            return qc
        cols = qc.columns
        if len(cols) == 1 and cols[0] == MODIN_UNNAMED_SERIES_LABEL:
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return DataFrame(query_compiler=qc)

    def explode(self, column: Any, ignore_index: bool = False) -> "DataFrame":
        return DataFrame(
            query_compiler=self._query_compiler.explode(column, ignore_index=ignore_index)
        )

    def squeeze(self, axis: Any = None):
        return super().squeeze(axis)

    def value_counts(self, subset: Any = None, normalize: bool = False, sort: bool = True, ascending: bool = False, dropna: bool = True):
        from modin_tpu.pandas.series import Series

        return self._default_to_pandas(
            "value_counts", subset=subset, normalize=normalize, sort=sort,
            ascending=ascending, dropna=dropna,
        )

    # ------------------------------------------------------------------ #
    # Iteration
    # ------------------------------------------------------------------ #

    def items(self) -> Iterator:
        for col in self.columns:
            yield col, self[col]

    def iterrows(self) -> Iterator:
        for row in self._to_pandas().iterrows():
            yield row

    def itertuples(self, index: bool = True, name: Any = "Pandas") -> Iterator:
        return self._to_pandas().itertuples(index=index, name=name)

    # ------------------------------------------------------------------ #
    # Info / output
    # ------------------------------------------------------------------ #

    def info(self, verbose: Any = None, buf: Any = None, max_cols: Any = None, memory_usage: Any = None, show_counts: Any = None) -> None:
        self._default_to_pandas(
            "info", verbose=verbose, buf=buf, max_cols=max_cols,
            memory_usage=memory_usage, show_counts=show_counts,
        )

    def to_parquet(self, path: Any = None, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_parquet(self._query_compiler, path=path, **kwargs)

    def to_feather(self, path: Any, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_feather(self._query_compiler, path=path, **kwargs)

    def to_orc(self, path: Any = None, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_orc(self._query_compiler, path=path, **kwargs)

    def to_stata(self, path: Any, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_stata(self._query_compiler, path=path, **kwargs)

    def to_xml(self, path_or_buffer: Any = None, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_xml(
            self._query_compiler, path_or_buffer=path_or_buffer, **kwargs
        )

    def to_records(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_records", *args, **kwargs)

    def to_html(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_html", *args, **kwargs)

    # ------------------------------------------------------------------ #
    # Plotting & accessors
    # ------------------------------------------------------------------ #

    @property
    def plot(self):
        return self._to_pandas().plot

    def hist(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("hist", *args, **kwargs)

    def boxplot(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("boxplot", *args, **kwargs)

    @property
    def style(self):
        return self._to_pandas().style

    @property
    def modin(self):
        """The ``df.modin`` accessor: to_pandas / device introspection."""
        from modin_tpu.pandas.accessor import ModinAPI

        return ModinAPI(self)

    @property
    def sparse(self):
        return self._default_to_pandas(lambda df: df.sparse)

    def __divmod__(self, other: Any):
        return self._default_to_pandas("__divmod__", other)

    def __rdivmod__(self, other: Any):
        return self._default_to_pandas("__rdivmod__", other)

    def __matmul__(self, other: Any):
        return self.dot(other)

    def __rmatmul__(self, other: Any):
        return self._default_to_pandas("__rmatmul__", try_cast_to_pandas(other))

    def isetitem(self, loc: Any, value: Any) -> None:
        self._update_inplace(
            self._query_compiler.default_to_pandas(
                lambda df: df.copy().pipe(_isetitem_helper, loc, try_cast_to_pandas(value))
            )
        )

    def eval(self, expr: str, inplace: bool = False, **kwargs: Any):
        from modin_tpu.core.computation.eval import caller_namespace, try_eval

        ns = (
            caller_namespace(int(kwargs.get("level", 0) or 0))
            if "@" in expr and "local_dict" not in kwargs
            else None
        )
        if not kwargs:
            native = try_eval(self, expr, ns)
            if native is not None:
                result, assigned = native
                if assigned is not None:
                    out = self.copy()
                    out[assigned] = result
                    if inplace:
                        self._update_inplace(out._query_compiler)
                        return None
                    return out
                if not inplace:
                    return result
                raise ValueError("Cannot operate inplace if there is no assignment")
        if ns is not None:
            # the pandas fallback runs deep inside the QC layers where the
            # user's locals are out of frame-walking reach; level is already
            # folded into the captured namespace
            kwargs["local_dict"] = ns
            kwargs.pop("level", None)
        result = self._default_to_pandas("eval", expr, **kwargs)
        if inplace:
            if isinstance(result, DataFrame):
                self._update_inplace(result._query_compiler)
                return None
            raise ValueError("Cannot operate inplace if there is no assignment")
        return result

    def query(self, expr: str, *, inplace: bool = False, **kwargs: Any):
        from modin_tpu.core.computation.eval import caller_namespace

        ns = (
            caller_namespace(int(kwargs.get("level", 0) or 0))
            if "@" in expr and "local_dict" not in kwargs
            else None
        )
        if not kwargs:
            # named QC seam first (reference dataframe.py:1788): the storage
            # format compiles simple row-wise expressions natively and raises
            # NotImplementedError to route everything else to the fallback
            try:
                new_qc = self._query_compiler.rowwise_query(expr, local_dict=ns)
            except NotImplementedError:
                new_qc = None
            if new_qc is not None:
                if inplace:
                    self._update_inplace(new_qc)
                    return None
                return DataFrame(query_compiler=new_qc)
        if ns is not None:
            # the pandas fallback runs deep inside the QC layers where the
            # user's locals are out of frame-walking reach; level is already
            # folded into the captured namespace
            kwargs["local_dict"] = ns
            kwargs.pop("level", None)
        result = self._default_to_pandas("query", expr, **kwargs)
        if inplace:
            self._update_inplace(result._query_compiler)
            return None
        return result


def _isetitem_helper(df: pandas.DataFrame, loc: Any, value: Any) -> pandas.DataFrame:
    df.isetitem(loc, value)
    return df


_ATTRS_NO_LOOKUP = {
    "_query_compiler", "_siblings", "_attrs", "__class__", "__dict__",
    "_pandas_class", "_ipython_canary_method_should_not_exist_",
    "_ipython_display_", "_repr_mimebundle_", "__array_struct__",
    "__array_interface__", "_typ", "__deepcopy__", "__copy__",
}

_install_fallbacks(DataFrame, pandas.DataFrame)
