"""``DataFrameGroupBy`` / ``SeriesGroupBy`` — lazy groupby objects.

Reference design: /root/reference/modin/pandas/groupby.py (2,322 LoC): the
groupby object holds (query_compiler, by, kwargs) and dispatches aggregations
to ``qc.groupby_agg``; nothing is computed until an aggregation is requested.
"""

from __future__ import annotations

from typing import Any, Hashable, Optional, Union

import numpy as np
import pandas
from pandas.api.types import is_list_like

from modin_tpu.logging import ClassLogger, disable_logging
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, hashable, try_cast_to_pandas


class DataFrameGroupBy(ClassLogger, modin_layer="PANDAS-API"):
    _pandas_class = pandas.core.groupby.DataFrameGroupBy

    def __init__(
        self,
        df: Any,
        by: Any = None,
        level: Any = None,
        as_index: bool = True,
        sort: bool = True,
        group_keys: bool = True,
        observed: Any = True,
        dropna: bool = True,
        selection: Any = None,
    ) -> None:
        self._df = df
        self._by = by
        self._level = level
        self._selection = selection
        self._kwargs = {
            "level": level,
            "as_index": as_index,
            "sort": sort,
            "group_keys": group_keys,
            "observed": observed,
            "dropna": dropna,
        }

    @property
    def _query_compiler(self):
        # resolved dynamically: the groupby tracks its parent frame, matching
        # pandas' behavior where post-groupby mutations of the frame are seen
        return self._df._query_compiler

    # ------------------------------------------------------------------ #
    # by normalization
    # ------------------------------------------------------------------ #

    def _resolve_by(self):
        """Return (by_for_qc, drop) where label-bys stay labels and external
        Series become query compilers."""
        from modin_tpu.pandas.series import Series

        by = self._by
        if by is None:
            return None, False
        if isinstance(by, Series):
            return by._query_compiler, False
        if callable(by):
            return by, False
        if hashable(by) and not isinstance(by, tuple):
            if by in self._df.columns:
                return [by], True
            return by, False
        if is_list_like(by) and not isinstance(by, (pandas.Series, np.ndarray)):
            by_list = list(by)
            if all(
                hashable(o) and not isinstance(o, Series) and o in self._df.columns
                for o in by_list
            ):
                return by_list, True
            return [
                o._query_compiler if isinstance(o, Series) else o for o in by_list
            ], False
        return by, False

    def _groupby_agg(
        self,
        agg_func: Any,
        agg_args: tuple = (),
        agg_kwargs: Optional[dict] = None,
        numeric_only: Any = None,
        series_groupby: bool = False,
        **extra: Any,
    ):
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        by, drop = self._resolve_by()
        agg_kwargs = dict(agg_kwargs or {})
        if numeric_only is not None:
            agg_kwargs["numeric_only"] = numeric_only
        groupby_kwargs = dict(self._kwargs)
        result_qc = self._query_compiler.groupby_agg(
            by=by,
            agg_func=agg_func,
            axis=0,
            groupby_kwargs=groupby_kwargs,
            agg_args=agg_args,
            agg_kwargs=agg_kwargs,
            drop=drop,
            series_groupby=series_groupby,
            selection=self._selection,
        )
        if not hasattr(result_qc, "to_pandas"):
            return result_qc
        if series_groupby and not isinstance(agg_func, list):
            # a LIST spec always yields a frame, even with one function
            cols = result_qc.columns
            if len(cols) == 1:
                result_qc._shape_hint = "column"
                return Series(query_compiler=result_qc)
        if (
            not series_groupby
            and getattr(result_qc, "_shape_hint", None) == "column"
            and len(result_qc.columns) == 1
        ):
            # the UDF produced a scalar per group: the QC carries the
            # was-a-Series hint so the frame groupby still squeezes
            return Series(query_compiler=result_qc)
        return DataFrame(query_compiler=result_qc)

    # ------------------------------------------------------------------ #
    # aggregations
    # ------------------------------------------------------------------ #

    def sum(self, numeric_only: bool = False, min_count: int = 0, **kwargs: Any):
        return self._groupby_agg("sum", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count})

    def prod(self, numeric_only: bool = False, min_count: int = 0):
        return self._groupby_agg("prod", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count})

    def count(self):
        return self._groupby_agg("count")

    def mean(self, numeric_only: bool = False, engine: Any = None, engine_kwargs: Any = None):
        return self._groupby_agg("mean", agg_kwargs={"numeric_only": numeric_only})

    def median(self, numeric_only: bool = False):
        return self._groupby_agg("median", agg_kwargs={"numeric_only": numeric_only})

    def min(self, numeric_only: bool = False, min_count: int = -1):
        return self._groupby_agg("min", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count})

    def max(self, numeric_only: bool = False, min_count: int = -1):
        return self._groupby_agg("max", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count})

    def std(self, ddof: int = 1, engine: Any = None, engine_kwargs: Any = None, numeric_only: bool = False):
        return self._groupby_agg("std", agg_kwargs={"ddof": ddof, "numeric_only": numeric_only})

    def var(self, ddof: int = 1, engine: Any = None, engine_kwargs: Any = None, numeric_only: bool = False):
        return self._groupby_agg("var", agg_kwargs={"ddof": ddof, "numeric_only": numeric_only})

    def sem(self, ddof: int = 1, numeric_only: bool = False):
        return self._groupby_agg("sem", agg_kwargs={"ddof": ddof, "numeric_only": numeric_only})

    def skew(self, numeric_only: bool = False, **kwargs: Any):
        return self._groupby_agg("skew", agg_kwargs={"numeric_only": numeric_only})

    def first(self, numeric_only: bool = False, min_count: int = -1, skipna: bool = True):
        return self._groupby_agg("first", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count, "skipna": skipna})

    def last(self, numeric_only: bool = False, min_count: int = -1, skipna: bool = True):
        return self._groupby_agg("last", agg_kwargs={"numeric_only": numeric_only, "min_count": min_count, "skipna": skipna})

    def any(self, skipna: bool = True):
        return self._groupby_agg("any", agg_kwargs={"skipna": skipna})

    def all(self, skipna: bool = True):
        return self._groupby_agg("all", agg_kwargs={"skipna": skipna})

    def nunique(self, dropna: bool = True):
        return self._groupby_agg("nunique", agg_kwargs={"dropna": dropna})

    def size(self):
        from modin_tpu.pandas.series import Series

        result = self._groupby_agg("size")
        if self._kwargs.get("as_index", True) and not isinstance(result, Series):
            # size returns a Series in pandas when as_index=True
            qc = result._query_compiler
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return result

    def quantile(self, q: float = 0.5, interpolation: str = "linear", numeric_only: bool = False):
        return self._groupby_agg("quantile", agg_kwargs={"q": q, "interpolation": interpolation, "numeric_only": numeric_only})

    def idxmin(self, skipna: bool = True, numeric_only: bool = False):
        return self._groupby_agg("idxmin", agg_kwargs={"skipna": skipna, "numeric_only": numeric_only})

    def idxmax(self, skipna: bool = True, numeric_only: bool = False):
        return self._groupby_agg("idxmax", agg_kwargs={"skipna": skipna, "numeric_only": numeric_only})

    def cumsum(self, axis: Any = 0, *args: Any, **kwargs: Any):
        return self._groupby_agg("cumsum", agg_args=args, agg_kwargs=kwargs)

    def cumprod(self, axis: Any = 0, *args: Any, **kwargs: Any):
        return self._groupby_agg("cumprod", agg_args=args, agg_kwargs=kwargs)

    def cummax(self, axis: Any = 0, numeric_only: bool = False, **kwargs: Any):
        return self._groupby_agg("cummax", agg_kwargs={"numeric_only": numeric_only})

    def cummin(self, axis: Any = 0, numeric_only: bool = False, **kwargs: Any):
        return self._groupby_agg("cummin", agg_kwargs={"numeric_only": numeric_only})

    def cumcount(self, ascending: bool = True):
        return self._groupby_agg("cumcount", agg_kwargs={"ascending": ascending}, series_groupby=True)

    def ngroup(self, ascending: bool = True):
        return self._groupby_agg("ngroup", agg_kwargs={"ascending": ascending}, series_groupby=True)

    def rank(self, method: str = "average", ascending: bool = True, na_option: str = "keep", pct: bool = False, **kwargs: Any):
        return self._groupby_agg("rank", agg_kwargs={"method": method, "ascending": ascending, "na_option": na_option, "pct": pct})

    def shift(self, periods: int = 1, freq: Any = None, fill_value: Any = None, **kwargs: Any):
        return self._groupby_agg("shift", agg_kwargs={"periods": periods, "freq": freq, "fill_value": fill_value})

    def diff(self, periods: int = 1, **kwargs: Any):
        return self._groupby_agg("diff", agg_kwargs={"periods": periods})

    def pct_change(self, periods: int = 1, **kwargs: Any):
        return self._groupby_agg("pct_change", agg_kwargs={"periods": periods})

    def ffill(self, limit: Any = None):
        return self._groupby_agg("ffill", agg_kwargs={"limit": limit})

    def bfill(self, limit: Any = None):
        return self._groupby_agg("bfill", agg_kwargs={"limit": limit})

    def fillna(self, *args: Any, **kwargs: Any):
        return self._groupby_agg("fillna", agg_args=args, agg_kwargs=kwargs)

    def head(self, n: int = 5):
        return self._groupby_agg("head", agg_kwargs={"n": n})

    def tail(self, n: int = 5):
        return self._groupby_agg("tail", agg_kwargs={"n": n})

    def nth(self, n: Any, dropna: Any = None):
        return self._groupby_agg("nth", agg_kwargs={"n": n})

    def sample(self, n: Any = None, frac: Any = None, replace: bool = False, weights: Any = None, random_state: Any = None):
        return self._groupby_agg("sample", agg_kwargs={"n": n, "frac": frac, "replace": replace, "weights": weights, "random_state": random_state})

    def ohlc(self):
        return self._groupby_agg("ohlc")

    def describe(self, percentiles: Any = None, include: Any = None, exclude: Any = None):
        return self._groupby_agg(
            "describe",
            agg_kwargs={
                "percentiles": percentiles, "include": include, "exclude": exclude,
            },
        )

    def corrwith(self, other: Any, drop: bool = False, method: str = "pearson", numeric_only: bool = False):
        return self._groupby_agg(
            "corrwith",
            agg_kwargs={
                "other": try_cast_to_pandas(other), "drop": drop,
                "method": method, "numeric_only": numeric_only,
            },
        )

    def corr(self, method: str = "pearson", min_periods: int = 1, numeric_only: bool = False):
        return self._groupby_agg("corr", agg_kwargs={"method": method, "min_periods": min_periods, "numeric_only": numeric_only})

    def cov(self, min_periods: Any = None, ddof: int = 1, numeric_only: bool = False):
        return self._groupby_agg("cov", agg_kwargs={"min_periods": min_periods, "ddof": ddof, "numeric_only": numeric_only})

    def agg(self, func: Any = None, *args: Any, engine: Any = None, engine_kwargs: Any = None, **kwargs: Any):
        if func is None and kwargs:
            # named aggregation
            return self._groupby_agg(
                lambda grp, **kw: grp.agg(**kw), agg_kwargs=kwargs
            )
        if (
            not args
            and not kwargs
            and (
                (isinstance(func, list) and all(isinstance(f, str) for f in func))
                or (
                    isinstance(func, dict)
                    and all(isinstance(f, str) for f in func.values())
                )
            )
        ):
            # list-of-strings / dict-of-strings pass through intact so the
            # compiler's device multi-agg path can see them
            return self._groupby_agg(func)
        return self._groupby_agg(
            func if isinstance(func, str) else (lambda grp, *a, **kw: grp.agg(try_cast_to_pandas(func), *a, **kw)),
            agg_args=args,
            agg_kwargs=kwargs,
        )

    aggregate = agg

    def apply(self, func: Any, *args: Any, include_groups: bool = False, **kwargs: Any):
        return self._groupby_agg(
            lambda grp, *a, **kw: grp.apply(func, *a, include_groups=include_groups, **kw)
            if _supports_include_groups(grp)
            else grp.apply(func, *a, **kw),
            agg_args=args,
            agg_kwargs=kwargs,
        )

    def transform(self, func: Any, *args: Any, engine: Any = None, engine_kwargs: Any = None, **kwargs: Any):
        if isinstance(func, str) and not args and not kwargs:
            from modin_tpu.pandas.dataframe import DataFrame
            from modin_tpu.pandas.series import Series

            by, drop = self._resolve_by()
            is_series = self._pandas_class is pandas.core.groupby.SeriesGroupBy
            result_qc = self._query_compiler.groupby_transform(
                by=by,
                agg_func=func,
                groupby_kwargs=dict(self._kwargs),
                drop=drop,
                series_groupby=is_series,
                selection=self._selection,
            )
            if is_series and result_qc.get_axis_len(1) == 1:
                result_qc._shape_hint = "column"
                return Series(query_compiler=result_qc)
            return DataFrame(query_compiler=result_qc)
        transformer = lambda grp, *a, **kw: grp.transform(func, *a, **kw)  # noqa: E731
        # row-shaped result (original frame order): the key-ordered shuffle
        # concat must not claim it
        transformer._row_shaped_groupby = True
        return self._groupby_agg(transformer, agg_args=args, agg_kwargs=kwargs)

    def filter(self, func: Any, dropna: bool = True, *args: Any, **kwargs: Any):
        filterer = lambda grp, *a, **kw: grp.filter(func, dropna=dropna, *a, **kw)  # noqa: E731
        filterer._row_shaped_groupby = True
        return self._groupby_agg(filterer, agg_args=args, agg_kwargs=kwargs)

    def pipe(self, func: Any, *args: Any, **kwargs: Any):
        if isinstance(func, tuple):
            func, target = func
            kwargs[target] = self
            return func(*args, **kwargs)
        return func(self, *args, **kwargs)

    def value_counts(self, subset: Any = None, normalize: bool = False, sort: bool = True, ascending: bool = False, dropna: bool = True):
        return self._groupby_agg(
            "value_counts",
            agg_kwargs={"subset": subset, "normalize": normalize, "sort": sort, "ascending": ascending, "dropna": dropna},
            series_groupby=True,
        )

    def resample(self, rule: Any, *args: Any, **kwargs: Any):
        return self._groupby_agg(
            lambda grp, *a, **kw: grp.resample(rule, *a, **kw).sum(), agg_args=args, agg_kwargs=kwargs
        )

    def rolling(
        self,
        window: Any = None,
        min_periods: Any = None,
        center: bool = False,
        win_type: Any = None,
        on: Any = None,
        closed: Any = None,
        method: str = "single",
    ):
        from modin_tpu.pandas.window import GroupByRolling

        return GroupByRolling(
            self, window, min_periods=min_periods, center=center,
            win_type=win_type, on=on, closed=closed, method=method,
        )

    def expanding(self, min_periods: int = 1, method: str = "single"):
        from modin_tpu.pandas.window import GroupByExpanding

        return GroupByExpanding(self, min_periods=min_periods, method=method)

    def ewm(
        self,
        com: Any = None,
        span: Any = None,
        halflife: Any = None,
        alpha: Any = None,
        min_periods: Any = 0,
        adjust: bool = True,
        ignore_na: bool = False,
        times: Any = None,
        method: str = "single",
    ):
        from modin_tpu.pandas.window import GroupByEwm
        from modin_tpu.utils import try_cast_to_pandas

        return GroupByEwm(
            self, com=com, span=span, halflife=halflife, alpha=alpha,
            min_periods=min_periods, adjust=adjust, ignore_na=ignore_na,
            times=try_cast_to_pandas(times, squeeze=True), method=method,
        )

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    @property
    def groups(self) -> dict:
        return self._to_pandas_groupby().groups

    @property
    def indices(self) -> dict:
        return self._to_pandas_groupby().indices

    @property
    def ngroups(self) -> int:
        return self._to_pandas_groupby().ngroups

    @property
    def dtypes(self):
        return self._df._wrap_pandas(self._to_pandas_groupby().dtypes)

    def get_group(self, name: Any):
        return self._df._wrap_pandas(self._to_pandas_groupby().get_group(name))

    def __len__(self) -> int:
        return self.ngroups

    def __iter__(self):
        for name, group in self._to_pandas_groupby():
            yield name, self._df._wrap_pandas(group)

    def _to_pandas_groupby(self):
        pandas_obj = self._df._to_pandas()
        by = try_cast_to_pandas(self._by, squeeze=True)
        grp = pandas_obj.groupby(by=by, **{k: v for k, v in self._kwargs.items()})
        if self._selection is not None:
            grp = grp[self._selection]
        return grp

    def __getitem__(self, key: Any):
        passthrough = {
            k: v
            for k, v in self._kwargs.items()
            if k in ("as_index", "sort", "group_keys", "observed", "dropna")
        }
        if is_list_like(key) and not isinstance(key, str):
            return DataFrameGroupBy(
                self._df,
                by=self._by,
                level=self._level,
                selection=list(key),
                **passthrough,
            )
        return SeriesGroupBy(
            self._df,
            by=self._by,
            level=self._level,
            selection=key,
            **passthrough,
        )

    def __getattr__(self, key: str):
        try:
            return object.__getattribute__(self, key)
        except AttributeError as err:
            qc = object.__getattribute__(self, "_query_compiler")
            if key in qc.columns:
                return self[key]
            raise err


class SeriesGroupBy(DataFrameGroupBy):
    _pandas_class = pandas.core.groupby.SeriesGroupBy

    def __init__(self, obj: Any, by: Any = None, level: Any = None, selection: Any = None, **kwargs: Any) -> None:
        super().__init__(obj, by=by, level=level, selection=selection, **kwargs)

    def _groupby_agg(self, agg_func: Any, agg_args: tuple = (), agg_kwargs: Optional[dict] = None, numeric_only: Any = None, series_groupby: bool = True, **extra: Any):
        return super()._groupby_agg(
            agg_func,
            agg_args=agg_args,
            agg_kwargs=agg_kwargs,
            numeric_only=numeric_only,
            series_groupby=True,
        )

    def unique(self):
        return self._groupby_agg("unique")

    def nlargest(self, n: int = 5, keep: str = "first"):
        return self._groupby_agg("nlargest", agg_kwargs={"n": n, "keep": keep})

    def nsmallest(self, n: int = 5, keep: str = "first"):
        return self._groupby_agg("nsmallest", agg_kwargs={"n": n, "keep": keep})

    @property
    def is_monotonic_increasing(self):
        return self._groupby_agg(lambda grp: grp.apply(lambda s: s.is_monotonic_increasing))

    @property
    def is_monotonic_decreasing(self):
        return self._groupby_agg(lambda grp: grp.apply(lambda s: s.is_monotonic_decreasing))


def _supports_include_groups(grp: Any) -> bool:
    import inspect

    try:
        return "include_groups" in inspect.signature(grp.apply).parameters
    except (ValueError, TypeError):
        return False
