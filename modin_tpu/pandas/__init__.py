"""``modin_tpu.pandas`` — the drop-in pandas namespace.

Reference design: /root/reference/modin/pandas/__init__.py:14-213 — re-export
the full pandas namespace, substituting the distributed DataFrame/Series and
factory-dispatched IO functions; pass everything else through to pandas.
"""

from __future__ import annotations

import pandas

__pandas_version__ = pandas.__version__

# --- pass-through re-exports (types, dtypes, options, utilities) ---------- #
from pandas import (  # noqa: F401
    NA,
    ArrowDtype,
    BooleanDtype,
    Categorical,
    CategoricalDtype,
    CategoricalIndex,
    DateOffset,
    DatetimeIndex,
    DatetimeTZDtype,
    Flags,
    Float32Dtype,
    Float64Dtype,
    Grouper,
    Index,
    IndexSlice,
    Int8Dtype,
    Int16Dtype,
    Int32Dtype,
    Int64Dtype,
    Interval,
    IntervalDtype,
    IntervalIndex,
    MultiIndex,
    NamedAgg,
    NaT,
    Period,
    PeriodDtype,
    PeriodIndex,
    RangeIndex,
    SparseDtype,
    StringDtype,
    Timedelta,
    TimedeltaIndex,
    Timestamp,
    UInt8Dtype,
    UInt16Dtype,
    UInt32Dtype,
    UInt64Dtype,
    array,
    arrays,
    describe_option,
    eval,
    get_option,
    infer_freq,
    option_context,
    options,
    reset_option,
    set_eng_float_format,
    set_option,
    test,
    testing,
)

import os

from modin_tpu.config import Engine

_is_first_update = {}


def _initialize_engine(engine_cls) -> None:
    """Lazy one-time engine startup on first factory touch.

    Reference design: modin/pandas/__init__.py:121-151.
    """
    engine = engine_cls.get()
    if engine in engine_cls.NOINIT_ENGINES:
        return
    if _is_first_update.get(engine, True):
        _is_first_update[engine] = False
        if engine == "Jax":
            from modin_tpu.parallel.engine import initialize_jax

            initialize_jax()
        else:
            raise ValueError(f"Unknown engine: {engine}")


# --- the distributed API surface ----------------------------------------- #
from modin_tpu.pandas.dataframe import DataFrame  # noqa: E402,F401
from modin_tpu.pandas.series import Series  # noqa: E402,F401
from modin_tpu.pandas.general import (  # noqa: E402,F401
    bdate_range,
    concat,
    crosstab,
    cut,
    date_range,
    factorize,
    from_dummies,
    get_dummies,
    interval_range,
    isna,
    isnull,
    json_normalize,
    lreshape,
    melt,
    merge,
    merge_asof,
    merge_ordered,
    notna,
    notnull,
    period_range,
    pivot,
    pivot_table,
    qcut,
    timedelta_range,
    to_datetime,
    to_numeric,
    to_timedelta,
    unique,
    value_counts,
    wide_to_long,
)
from modin_tpu.pandas.io import (  # noqa: E402,F401
    ExcelFile,
    HDFStore,
    read_clipboard,
    read_csv,
    read_excel,
    read_feather,
    read_fwf,
    read_hdf,
    read_html,
    read_json,
    read_orc,
    read_parquet,
    read_pickle,
    read_sas,
    read_spss,
    read_sql,
    read_sql_query,
    read_sql_table,
    read_stata,
    read_table,
    read_xml,
    to_pickle,
)
from modin_tpu.pandas import api  # noqa: E402,F401
from modin_tpu.pandas.plotting import Plotting as plotting  # noqa: E402,F401

__all__ = [  # noqa: F405
    "DataFrame", "Series", "read_csv", "read_parquet", "read_json",
    "read_html", "read_clipboard", "read_excel", "read_hdf", "read_feather",
    "read_stata", "read_sas", "read_pickle", "read_sql", "read_fwf",
    "read_sql_table", "read_sql_query", "read_spss", "read_orc", "read_xml",
    "read_table", "to_pickle", "concat", "eval", "unique", "value_counts",
    "cut", "to_numeric", "factorize", "qcut", "to_datetime", "get_dummies",
    "isna", "isnull", "merge", "pivot_table", "date_range", "Index",
    "MultiIndex", "Series", "bdate_range", "period_range", "DatetimeIndex",
    "to_timedelta", "set_eng_float_format", "options", "set_option",
    "get_option", "reset_option", "option_context", "CategoricalIndex",
    "Timedelta", "Timestamp", "NaT", "PeriodIndex", "Categorical", "__version__",
    "melt", "crosstab", "plotting", "Interval", "UInt8Dtype", "UInt16Dtype",
    "UInt32Dtype", "UInt64Dtype", "SparseDtype", "Int8Dtype", "Int16Dtype",
    "Int32Dtype", "Int64Dtype", "CategoricalDtype", "DatetimeTZDtype",
    "IntervalDtype", "PeriodDtype", "BooleanDtype", "StringDtype", "NA",
    "RangeIndex", "TimedeltaIndex", "IntervalIndex", "IndexSlice",
    "Grouper", "array", "Period", "DateOffset", "timedelta_range",
    "infer_freq", "interval_range", "ExcelFile", "describe_option",
    "notnull", "notna", "pivot", "test", "api", "lreshape", "wide_to_long",
    "merge_asof", "merge_ordered", "json_normalize", "NamedAgg", "from_dummies",
]

__version__ = pandas.__version__


def __getattr__(name: str):
    """Resolve registered pd extensions (backend-aware, objects returned
    as-is), then forward anything else to pandas (reference: extensions
    module __getattr__, extensions.py:300)."""
    from modin_tpu.pandas.api.extensions.extensions import (
        _PD_EXTENSIONS,
        _resolve_pd_extension,
    )

    if name in _PD_EXTENSIONS:
        return _resolve_pd_extension(name)
    try:
        return getattr(pandas, name)
    except AttributeError:
        raise AttributeError(f"module 'modin_tpu.pandas' has no attribute '{name}'")
