"""``Resampler`` — lazy resample handle.

Reference design: /root/reference/modin/pandas/resample.py (409 LoC).
"""

from __future__ import annotations

from typing import Any

import pandas

from modin_tpu.logging import ClassLogger
from modin_tpu.utils import _inherit_docstrings


@_inherit_docstrings(pandas.core.resample.Resampler)
class Resampler(ClassLogger, modin_layer="PANDAS-API"):
    def __init__(self, dataframe: Any, rule: Any, **kwargs: Any) -> None:
        self._dataframe = dataframe
        self.resample_kwargs = {"rule": rule, **kwargs}

    @property
    def _query_compiler(self):
        return self._dataframe._query_compiler

    def _agg(self, name: str, *args: Any, **kwargs: Any):
        qc_method = getattr(self._query_compiler, f"resample_{name}")
        new_qc = qc_method(_clean_kwargs(self.resample_kwargs), *args, **kwargs)
        return self._wrap(new_qc)

    def _wrap(self, qc: Any):
        if not hasattr(qc, "to_pandas"):
            return qc
        # size() on a frame is a Series too (shape_hint set by the compiler);
        # a series source can still produce a frame (ohlc)
        if (
            self._dataframe.ndim == 1 or qc._shape_hint == "column"
        ) and qc.get_axis_len(1) <= 1:
            from modin_tpu.pandas.series import Series

            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        from modin_tpu.pandas.dataframe import DataFrame

        return DataFrame(query_compiler=qc)

    def __getitem__(self, key: Any):
        subset = self._dataframe[key]
        return Resampler(subset, **self.resample_kwargs)

    @property
    def groups(self):
        return self._dataframe._default_to_pandas(
            lambda obj: obj.resample(**_clean_kwargs(self.resample_kwargs)).groups
        )

    @property
    def indices(self):
        return self._dataframe._default_to_pandas(
            lambda obj: obj.resample(**_clean_kwargs(self.resample_kwargs)).indices
        )

    def get_group(self, name: Any):
        return self._dataframe._wrap_pandas(
            self._dataframe._to_pandas()
            .resample(**_clean_kwargs(self.resample_kwargs))
            .get_group(name)
        )


def _clean_kwargs(kwargs: dict) -> dict:
    return {k: v for k, v in kwargs.items() if v is not None or k in ("rule",)}


for _name in [
    "count", "sum", "mean", "median", "var", "std", "min", "max", "sem",
    "first", "last", "ohlc", "prod", "size", "nunique", "quantile",
    "agg", "aggregate", "apply", "transform", "ffill", "bfill", "nearest",
    "asfreq", "interpolate",
]:

    def _make_resample(name):
        def method(self, *args: Any, **kwargs: Any):
            return self._agg(name, *args, **kwargs)

        method.__name__ = name
        return method

    setattr(Resampler, _name, _make_resample(_name))


def _resample_size(self, *args: Any, **kwargs: Any):
    """size() is a Series regardless of source shape or fallback path."""
    from modin_tpu.pandas.dataframe import DataFrame
    from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL

    out = self._agg("size", *args, **kwargs)
    if isinstance(out, DataFrame) and len(out.columns) == 1:
        label = out.columns[0]
        series = out[label]
        if label == MODIN_UNNAMED_SERIES_LABEL:
            series = series.rename(None)
        return series
    return out


_resample_size.__name__ = "size"
Resampler.size = _resample_size
