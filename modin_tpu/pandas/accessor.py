"""``df.modin`` accessor: conversions and backend introspection.

Reference design: /root/reference/modin/pandas/accessor.py (ModinAPI).
"""

from __future__ import annotations

from typing import Any

from modin_tpu.logging import ClassLogger


class ModinAPI(ClassLogger, modin_layer="PANDAS-API"):
    """Namespace of modin_tpu-specific functionality on DataFrame/Series."""

    def __init__(self, data: Any) -> None:
        self._data = data

    def to_pandas(self):
        """Materialize to a plain pandas object on the host."""
        return self._data._to_pandas()

    def get_backend(self) -> str:
        """Name of the backend currently holding this object's data."""
        return self._data._query_compiler.get_backend()

    def set_backend(self, backend: str, inplace: bool = False):
        """Move this object's data to another backend (e.g. 'Tpu' <-> 'Pandas')."""
        from modin_tpu.config import Backend
        from modin_tpu.core.execution.dispatching.factories import factories
        from modin_tpu.utils import get_current_execution

        execution = Backend.get_execution_for_backend(backend)
        factory_name = f"{execution.storage_format}On{execution.engine}Factory"
        factory = getattr(factories, factory_name)
        factory.prepare()
        new_qc = factory.io_cls.from_pandas(self._data._query_compiler.to_pandas())
        new_qc._shape_hint = self._data._query_compiler._shape_hint
        return self._data._create_or_update_from_compiler(new_qc, inplace)

    def to_device(self, inplace: bool = False):
        """Move to the TPU (sharded jax.Array) backend."""
        return self.set_backend("Tpu", inplace=inplace)

    def to_host(self, inplace: bool = False):
        """Move to the in-process pandas backend."""
        return self.set_backend("Pandas", inplace=inplace)

    def explain(self, analyze: bool = False) -> str:
        """graftplan EXPLAIN: the deferred logical plan before/after rewrite
        with per-rule attribution, or a note that execution is eager.

        ``analyze=True`` (EXPLAIN ANALYZE) executes the plan — bit-exact vs
        plain execution — and annotates every node with its measured wall
        time, rows, bytes, and engine dispatch count, plus the graftmeter
        per-query resource rollup (compiles, bytes parsed, HBM high-water,
        spills, cache hits)."""
        qc = self._data._query_compiler
        if hasattr(qc, "explain"):
            return qc.explain(analyze=analyze)
        return f"status: eager ({type(qc).__name__} has no deferred planner)"

    def repartition(self, axis: Any = None):
        """Rebalance the on-device sharding (no-op for host backends)."""
        return self._data._create_or_update_from_compiler(
            self._data._query_compiler.repartition(axis=axis)
        )


class CachedAccessor:
    """Custom property-like object for accessor namespaces."""

    def __init__(self, name: str, accessor: type) -> None:
        self._name = name
        self._accessor = accessor

    def __get__(self, obj: Any, cls: Any):
        if obj is None:
            return self._accessor
        return self._accessor(obj)
