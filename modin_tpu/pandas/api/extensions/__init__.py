"""Accessor registration (reference: modin/pandas/api/extensions/)."""

from modin_tpu.pandas.api.extensions.extensions import (  # noqa: F401
    register_base_accessor,
    register_dataframe_accessor,
    register_dataframe_groupby_accessor,
    register_pd_accessor,
    register_series_accessor,
    register_series_groupby_accessor,
)
