"""Accessor registration (reference: modin/pandas/api/extensions/).

pandas.api.extensions contents (no_default, ExtensionDtype, take, the
extension-dtype registrars, ...) pass through so this namespace is a
drop-in superset of pandas'.
"""

from pandas.api.extensions import *  # noqa: F401,F403
from pandas.api.extensions import no_default  # noqa: F401  (not in __all__)

from modin_tpu.pandas.api.extensions.extensions import (  # noqa: F401
    register_base_accessor,
    register_dataframe_accessor,
    register_dataframe_groupby_accessor,
    register_pd_accessor,
    register_series_accessor,
    register_series_groupby_accessor,
)
