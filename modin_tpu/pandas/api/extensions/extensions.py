"""Accessor/method registration API with per-backend overrides.

Reference design: modin/pandas/api/extensions/extensions.py:135-371
(register_dataframe_accessor / register_series_accessor /
register_base_accessor / register_pd_accessor, each accepting ``backend=``).
A registration with ``backend=None`` applies to every backend; a registration
naming a backend ("Tpu", "Pandas") is visible ONLY on objects whose query
compiler currently lives on that backend — the lookup happens at attribute
access time, so the same object exposes/hides the extension as it moves
between backends.
"""

from __future__ import annotations

import sys
from types import MethodType
from typing import Any, Callable, Dict, Optional, Tuple

from modin_tpu.pandas.accessor import CachedAccessor

# (owner class, attribute name) -> {backend or None: accessor object}
_EXTENSIONS: Dict[Tuple[type, str], Dict[Optional[str], Any]] = {}
# original class attribute shadowed by the dispatcher (None if absent)
_SHADOWED: Dict[Tuple[type, str], Any] = {}

# module-level (pd) accessors: name -> {backend or None: object}
_PD_EXTENSIONS: Dict[str, Dict[Optional[str], Any]] = {}
# module attribute displaced by a pd extension (None if the module had none)
_PD_SHADOWED: Dict[str, Any] = {}


def _current_backend(instance: Any) -> Optional[str]:
    qc = getattr(instance, "_query_compiler", None)
    if qc is None:
        return None
    try:
        return qc.get_backend()
    except Exception:
        return None


class _BackendDispatchingAttribute:
    """Descriptor resolving an extension by the instance's live backend."""

    def __init__(self, owner: type, name: str):
        self._key = (owner, name)
        self._name = name

    def _resolve(self, instance: Any) -> Any:
        overrides = _EXTENSIONS.get(self._key, {})
        backend = _current_backend(instance)
        if backend in overrides:
            return overrides[backend]
        if None in overrides:
            return overrides[None]
        fallback = _SHADOWED.get(self._key)
        if fallback is None:
            raise AttributeError(
                f"{type(instance).__name__} object has no attribute "
                f"{self._name!r} on backend {backend!r}"
            )
        return fallback

    def __get__(self, instance: Any, owner: Optional[type] = None) -> Any:
        if instance is None:
            return self
        accessor = self._resolve(instance)
        if hasattr(accessor, "__get__"):
            # original descriptor (property, CachedAccessor, function...)
            return accessor.__get__(instance, owner)
        if isinstance(accessor, type):
            return accessor(instance)
        if callable(accessor):
            return MethodType(accessor, instance)
        return accessor


def _register_accessor(name: str, cls: type, backend: Optional[str]) -> Callable:
    def decorator(accessor: Any) -> Any:
        key = (cls, name)
        if key not in _EXTENSIONS:
            # shadow the existing attribute (if any, anywhere on the MRO)
            # behind the dispatcher so unmatched backends keep stock behavior
            shadowed = None
            for klass in cls.__mro__:
                if name in klass.__dict__:
                    shadowed = klass.__dict__[name]
                    break
            _SHADOWED[key] = shadowed
            setattr(cls, name, _BackendDispatchingAttribute(cls, name))
        entry: Any = accessor
        if isinstance(accessor, type):
            # accessor classes get the pandas-style per-instance cache
            entry = CachedAccessor(name, accessor)
        _EXTENSIONS.setdefault(key, {})[backend] = entry
        return accessor

    return decorator


def register_dataframe_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor/method on modin_tpu DataFrame."""
    from modin_tpu.pandas.dataframe import DataFrame

    return _register_accessor(name, DataFrame, backend)


def register_series_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor/method on modin_tpu Series."""
    from modin_tpu.pandas.series import Series

    return _register_accessor(name, Series, backend)


def register_base_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor on the shared DataFrame/Series base."""
    from modin_tpu.pandas.base import BasePandasDataset

    return _register_accessor(name, BasePandasDataset, backend)


def register_dataframe_groupby_accessor(name: str, backend: Optional[str] = None) -> Callable:
    from modin_tpu.pandas.groupby import DataFrameGroupBy

    return _register_accessor(name, DataFrameGroupBy, backend)


def register_series_groupby_accessor(name: str, backend: Optional[str] = None) -> Callable:
    from modin_tpu.pandas.groupby import SeriesGroupBy

    return _register_accessor(name, SeriesGroupBy, backend)


def _resolve_pd_extension(name: str) -> Any:
    """Resolve a module-level extension against the session backend.

    Returns the registered object ITSELF (reference extensions.py:300 — the
    module ``__getattr__`` hands back whatever was registered, callable or
    not), falling back to the module attribute the registration displaced.
    """
    from modin_tpu.config import Backend

    overrides = _PD_EXTENSIONS[name]
    backend = None
    try:
        backend = Backend.get()
    except Exception:
        pass
    if backend in overrides:
        return overrides[backend]
    if None in overrides:
        return overrides[None]
    shadowed = _PD_SHADOWED.get(name)
    if shadowed is not None:
        return shadowed
    raise AttributeError(
        f"module 'modin_tpu.pandas' has no attribute {name!r} on backend {backend!r}"
    )


def register_pd_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom function/object on the modin_tpu.pandas module.

    Resolution happens in the module's ``__getattr__`` at attribute-access
    time, so non-callable registrations (constants, submodules) are returned
    directly and backend-scoped registrations track the live session backend.
    """

    def decorator(obj: Any) -> Any:
        pd_module = sys.modules["modin_tpu.pandas"]
        if name not in _PD_SHADOWED:
            _PD_SHADOWED[name] = pd_module.__dict__.get(name)
        _PD_EXTENSIONS.setdefault(name, {})[backend] = obj
        # clear the plain module attribute so __getattr__ resolves every
        # access against the registry (and the displaced original, if any)
        pd_module.__dict__.pop(name, None)
        return obj

    return decorator
