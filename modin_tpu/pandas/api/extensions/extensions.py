"""Accessor/method registration API.

Reference design: modin/pandas/api/extensions/extensions.py:135-371
(register_dataframe_accessor / register_series_accessor /
register_base_accessor / register_pd_accessor).  Registered accessors are
cached-per-instance like pandas' own extension machinery.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from modin_tpu.pandas.accessor import CachedAccessor


def _register_accessor(name: str, cls: type) -> Callable:
    def decorator(accessor: Any) -> Any:
        if callable(accessor) and not isinstance(accessor, type):
            # function accessor: expose directly as a method
            setattr(cls, name, accessor)
        else:
            setattr(cls, name, CachedAccessor(name, accessor))
        return accessor

    return decorator


def register_dataframe_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor/method on modin_tpu DataFrame."""
    from modin_tpu.pandas.dataframe import DataFrame

    return _register_accessor(name, DataFrame)


def register_series_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor/method on modin_tpu Series."""
    from modin_tpu.pandas.series import Series

    return _register_accessor(name, Series)


def register_base_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom accessor on the shared DataFrame/Series base."""
    from modin_tpu.pandas.base import BasePandasDataset

    return _register_accessor(name, BasePandasDataset)


def register_dataframe_groupby_accessor(name: str, backend: Optional[str] = None) -> Callable:
    from modin_tpu.pandas.groupby import DataFrameGroupBy

    return _register_accessor(name, DataFrameGroupBy)


def register_series_groupby_accessor(name: str, backend: Optional[str] = None) -> Callable:
    from modin_tpu.pandas.groupby import SeriesGroupBy

    return _register_accessor(name, SeriesGroupBy)


def register_pd_accessor(name: str, backend: Optional[str] = None) -> Callable:
    """Register a custom function/object on the modin_tpu.pandas module."""

    def decorator(obj: Any) -> Any:
        import modin_tpu.pandas as pd_module

        setattr(pd_module, name, obj)
        return obj

    return decorator
