"""Public extension APIs (reference: modin/pandas/api/).

``interchange`` is the modin_tpu consumer; the pandas utility namespaces
(``types``, ``indexers``, ``typing``, ``executors``) pass through unchanged.
"""

from pandas.api import executors, indexers, types, typing  # noqa: F401

from modin_tpu.pandas.api import extensions, interchange  # noqa: F401
