"""Public extension APIs (reference: modin/pandas/api/)."""

from modin_tpu.pandas.api import extensions  # noqa: F401
