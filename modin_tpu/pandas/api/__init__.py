"""Public extension APIs (reference: modin/pandas/api/).

``interchange`` is the modin_tpu consumer; the pandas utility namespaces
(``types``, ``indexers``, ``typing``, ``executors``) pass through unchanged.
"""

from pandas.api import indexers, types, typing  # noqa: F401

try:  # pandas >= 3 only; older hosts simply lack the namespace
    from pandas.api import executors  # noqa: F401
except ImportError:  # pragma: no cover - depends on host pandas
    executors = None

from modin_tpu.pandas.api import extensions, interchange  # noqa: F401
