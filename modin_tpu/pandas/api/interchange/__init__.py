"""Interchange-protocol consumer entry point (mirrors pandas.api.interchange)."""

from typing import Any


def from_dataframe(df: Any, allow_copy: bool = True):
    """Build a modin_tpu DataFrame from any __dataframe__ protocol object."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )
    from modin_tpu.pandas.dataframe import DataFrame

    if hasattr(df, "__dataframe__"):
        df = df.__dataframe__(allow_copy=allow_copy)
    return DataFrame(
        query_compiler=FactoryDispatcher.from_interchange_dataframe(df)
    )


__all__ = ["from_dataframe"]
