"""``loc``/``iloc``/``at``/``iat`` indexers.

Reference design: /root/reference/modin/pandas/indexing.py (_LocationIndexerBase
:283, _LocIndexer :698, _iLocIndexer :1059): the API layer parses locators and
computes the result's dimensionality, while *label resolution lives in the
query compiler* — ``qc.take_2d_labels`` / ``qc.get_positions_from_labels``
(reference base/query_compiler.py:4809,4844) — so the storage format sees a
named, cost-modelable operation and device frames stay on device through
``.loc``.  MultiIndex axes resolve through ``Index.get_locs`` in the QC seam
(partial-tuple keys included); level dropping after a partial lookup is an
API-layer fixup, as in the reference (:812-841).  Setitem routes existing-label
assignments through ``qc.write_items`` and the boolean-mask hot path through
``qc.setitem_bool`` (reference indexing.py:954); enlargement and aligned
frame-valued assignment default to pandas.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import pandas
from pandas.api.types import is_bool_dtype, is_list_like, is_scalar
from pandas.core.dtypes.common import is_bool, is_integer

from modin_tpu.logging import ClassLogger


def is_boolean_array(x: Any) -> bool:
    if isinstance(x, (np.ndarray, pandas.Series, pandas.Index)):
        return is_bool_dtype(x.dtype)
    from modin_tpu.pandas.series import Series

    if isinstance(x, Series):
        return is_bool_dtype(x.dtype)
    return isinstance(x, (list, tuple)) and len(x) > 0 and all(is_bool(v) for v in x)


def is_integer_array(x: Any) -> bool:
    if isinstance(x, (np.ndarray, pandas.Series, pandas.Index)):
        return x.dtype.kind in "iu"
    return isinstance(x, (list, tuple)) and len(x) > 0 and all(is_integer(v) for v in x)


class _FallbackToPandas(Exception):
    pass


class _LocationIndexerBase(ClassLogger, modin_layer="PANDAS-API"):
    def __init__(self, modin_df: Any):
        self.df = modin_df
        self.qc = modin_df._query_compiler

    def _fallback_get(self, key: Any, attr: str) -> Any:
        from modin_tpu.utils import try_cast_to_pandas

        # pandas must never see modin objects inside the key (it would
        # treat e.g. a boolean-Series mask as a label list)
        key = try_cast_to_pandas(key)
        return self.df._default_to_pandas(lambda obj: getattr(obj, attr)[key])

    def _fallback_set(self, key: Any, value: Any, attr: str) -> None:
        from modin_tpu.utils import try_cast_to_pandas

        # the key can carry modin objects too (e.g. a boolean-Series mask in
        # a (rows, col) tuple) — pandas must never see them
        key = try_cast_to_pandas(key)
        value = try_cast_to_pandas(value)

        def setter(obj):
            obj = obj.copy()
            getattr(obj, attr)[key] = value
            return obj

        result = self.df._default_to_pandas(setter)
        self.df._update_inplace(result._query_compiler)

    def _wrap_row_series(self, row_qc: Any, label: Any) -> Any:
        """One selected row -> Series indexed by columns."""
        pandas_df = row_qc.to_pandas()
        row_series = pandas_df.iloc[0]
        row_series.name = label
        return self.df._wrap_pandas(row_series)

    def _write_positional(self, row_lookup: Any, col_lookup: Any, value: Any) -> bool:
        """Positional assignment via ``qc.write_items``; False if the value
        needs label alignment (frame-valued) and must take the fallback."""
        from modin_tpu.pandas.base import BasePandasDataset

        if isinstance(value, (BasePandasDataset, pandas.Series, pandas.DataFrame)):
            # .loc/.iloc setitem with a pandas-like value aligns on labels;
            # keep those semantics on the oracle path
            return False
        new_qc = self.qc.write_items(row_lookup, col_lookup, value)
        self.df._update_inplace(new_qc)
        self.qc = self.df._query_compiler
        return True


class _iLocIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if callable(key):
            return self.__getitem__(key(self.df))
        ndim = self.df.ndim
        if isinstance(key, tuple) and ndim == 2:
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)
            if isinstance(row_key, tuple) and ndim == 1:
                if len(row_key) > 1:
                    raise pandas.errors.IndexingError("Too many indexers")
                row_key = row_key[0] if row_key else slice(None)

        nrows = len(self.df.index)
        row_scalar = is_integer(row_key)
        col_scalar = is_integer(col_key)

        row_pos = self._positions(row_key, nrows, "row")
        if ndim == 1:
            if row_scalar:
                return self.df._to_pandas().iloc[row_key]
            new_qc = self.qc.take_2d_positional(index=row_pos)
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)

        ncols = len(self.df.columns)
        col_pos = self._positions(col_key, ncols, "column")
        if row_scalar and col_scalar:
            sub = self.qc.take_2d_positional(index=row_pos, columns=col_pos)
            return sub.to_pandas().iloc[0, 0]
        new_qc = self.qc.take_2d_positional(index=row_pos, columns=col_pos)
        if row_scalar:
            return self._wrap_row_series(new_qc, self.df.index[row_key])
        if col_scalar:
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)
        return DataFrame(query_compiler=new_qc)

    def _positions(self, axis_key: Any, length: int, axis_name: str) -> Any:
        if isinstance(axis_key, slice):
            return axis_key
        if is_integer(axis_key):
            if axis_key < -length or axis_key >= length:
                raise IndexError(
                    f"single positional indexer is out-of-bounds"
                )
            pos = axis_key if axis_key >= 0 else length + axis_key
            return [pos]
        if is_boolean_array(axis_key):
            mask = np.asarray(axis_key)
            if len(mask) != length:
                raise IndexError(
                    f"Boolean index has wrong length: {len(mask)} instead of {length}"
                )
            return list(np.nonzero(mask)[0])
        if is_list_like(axis_key):
            arr = np.asarray(axis_key, dtype=np.int64).ravel()
            if len(arr) and (arr.max(initial=-1) >= length or arr.min(initial=0) < -length):
                raise IndexError("positional indexers are out-of-bounds")
            return [int(i) if i >= 0 else length + int(i) for i in arr]
        raise TypeError(f"Cannot index by location index with a key of type {type(axis_key)}")

    def __setitem__(self, key: Any, value: Any) -> None:
        if callable(key):
            key = key(self.df)
        ndim = self.df.ndim
        if isinstance(key, tuple) and ndim == 2:
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)
        try:
            row_pos = self._positions(row_key, len(self.df.index), "row")
            col_pos = (
                self._positions(col_key, len(self.df.columns), "column")
                if ndim == 2
                else slice(None)
            )
        except (TypeError, IndexError):
            return self._fallback_set(key, value, "iloc")
        if not self._write_positional(row_pos, col_pos, value):
            self._fallback_set(key, value, "iloc")


class _LocIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        if callable(key):
            return self.__getitem__(key(self.df))
        if self.df.empty:
            return self._fallback_get(key, "loc")
        ndim_self = self.df.ndim
        index = self.df.index
        if ndim_self == 2 and isinstance(key, tuple):
            if (
                isinstance(index, pandas.MultiIndex)
                and 2 <= len(key) <= index.nlevels
                and all(is_scalar(k) for k in key)
            ):
                # loc[('a', 'b')] is ambiguous: a (partial) row key or a
                # (row, column) pair.  pandas prefers the row interpretation
                # when it resolves (reference indexing.py:731-747).
                try:
                    return self._getitem_via_qc(key, key, slice(None))
                except KeyError:
                    # a >2-long all-scalar tuple can only be a row key (a
                    # (row, col) pair has 2 parts): pandas surfaces the
                    # KeyError, not "Too many indexers"
                    if len(key) > 2:
                        raise
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)
            if (
                ndim_self == 1
                and isinstance(key, tuple)
                and not isinstance(index, pandas.MultiIndex)
            ):
                if len(key) > 1:
                    raise pandas.errors.IndexingError("Too many indexers")
                row_key = key[0] if key else slice(None)
        try:
            return self._getitem_via_qc(key, row_key, col_key)
        except _FallbackToPandas:
            return self._fallback_get(key, "loc")

    def _getitem_via_qc(self, key: Any, row_key: Any, col_key: Any) -> Any:
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        row_scalar = is_scalar(row_key)
        col_scalar = is_scalar(col_key)
        row_mi_full = self._multiindex_full_key(0, row_key)
        col_mi_full = (
            self._multiindex_full_key(1, col_key) if self.df.ndim == 2 else False
        )

        # Boolean-mask rows on a device frame: reuse the __getitem__ filter
        # fast path (mask fuses into the kernel) instead of materializing
        # positions on the host (reference _handle_boolean_masking :631).
        if (
            self.df.ndim == 2
            and isinstance(row_key, Series)
            and is_boolean_array(row_key)
        ):
            masked = self.df[row_key]
            if isinstance(col_key, slice) and col_key == slice(None):
                return masked
            return masked.loc[:, col_key]

        row_key = self._normalize_key(row_key, 0)
        if self.df.ndim == 2:
            col_key = self._normalize_key(col_key, 1)

        qc_view = self.qc.take_2d_labels(
            row_key, col_key if self.df.ndim == 2 else slice(None)
        )

        # An axis squeezes only when its key pins exactly one label: a scalar
        # (or tuple label) on a flat axis, or a full-depth tuple on a
        # MultiIndex axis.  A PARTIAL MultiIndex key keeps the axis and drops
        # the looked-up levels instead (pandas xs semantics).
        has_mi_rows = self.qc.has_multiindex(0)
        has_mi_cols = self.df.ndim == 2 and self.qc.has_multiindex(1)
        row_squeeze = row_mi_full or (
            (row_scalar or isinstance(row_key, tuple)) and not has_mi_rows
        )
        col_squeeze = col_mi_full or (
            (col_scalar or isinstance(col_key, tuple)) and not has_mi_cols
        )
        if self.df.ndim == 1:
            qc_view._shape_hint = "column"
            result = Series(query_compiler=qc_view)
            if row_squeeze:
                result = result.squeeze(axis=0)
        else:
            result = DataFrame(query_compiler=qc_view)
            if row_squeeze or col_squeeze:
                axis = (
                    None if row_squeeze and col_squeeze else 1 if col_squeeze else 0
                )
                result = result.squeeze(axis=axis)

        result = self._drop_levels(
            result, row_key, col_key, row_scalar, col_scalar,
            levels_already_dropped=row_mi_full or col_mi_full,
            row_squeezed=row_squeeze, col_squeezed=col_squeeze,
        )
        # Keep index state (e.g. DatetimeIndex freq) when selecting all
        # columns by an Index-valued row key (reference indexing.py:843-851)
        if (
            isinstance(key, pandas.Index)
            and not isinstance(key, pandas.MultiIndex)
            and isinstance(col_key, slice)
            and col_key == slice(None)
            and hasattr(result, "index")
            and len(result.index) == len(key)
        ):
            result.index = key
        return result

    def _drop_levels(
        self,
        result: Any,
        row_key: Any,
        col_key: Any,
        row_scalar: bool,
        col_scalar: bool,
        levels_already_dropped: bool,
        row_squeezed: bool = False,
        col_squeezed: bool = False,
    ) -> Any:
        """Partial-key MultiIndex lookups drop the looked-up levels
        (reference indexing.py:812-841)."""
        from modin_tpu.pandas.base import BasePandasDataset
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if not isinstance(result, BasePandasDataset) or levels_already_dropped:
            return result
        col_list = [col_key] if col_scalar else col_key
        row_list = [row_key] if row_scalar else row_key
        if isinstance(result.index, pandas.MultiIndex):
            # a Series whose index came from the COLUMNS (row axis squeezed
            # away, columns kept) drops col-key levels; every other result's
            # index is the row axis and drops row-key levels
            index_is_columns = (
                isinstance(result, Series) and row_squeezed and not col_squeezed
            )
            if index_is_columns:
                # same guard as the row branch below: only a scalar or tuple
                # col key looks up INTO the levels; a LIST key selects whole
                # level-0 entries and pandas keeps all levels
                if (
                    (col_scalar or isinstance(col_key, tuple))
                    and isinstance(col_list, (list, tuple))
                    and 0 < len(col_list) < result.index.nlevels
                    and all(
                        not isinstance(col_list[i], slice)
                        and is_scalar(col_list[i])
                        and col_list[i] in result.index.levels[i]
                        for i in range(len(col_list))
                    )
                ):
                    result.index = result.index.droplevel(list(range(len(col_list))))
            elif (
                (row_scalar or isinstance(row_key, tuple))
                and isinstance(row_list, (list, tuple))
                and 0 < len(row_list) < result.index.nlevels
                and all(
                    not isinstance(row_list[i], slice)
                    and is_scalar(row_list[i])
                    and row_list[i] in result.index.levels[i]
                    for i in range(len(row_list))
                )
            ):
                result.index = result.index.droplevel(list(range(len(row_list))))
        if (
            isinstance(result, DataFrame)
            and isinstance(result.columns, pandas.MultiIndex)
            and (col_scalar or isinstance(col_key, tuple))
            and isinstance(col_list, (list, tuple))
            and 0 < len(col_list) < result.columns.nlevels
            and all(
                not isinstance(col_list[i], slice)
                and is_scalar(col_list[i])
                and col_list[i] in result.columns.levels[i]
                for i in range(len(col_list))
            )
        ):
            result.columns = result.columns.droplevel(list(range(len(col_list))))
        return result

    def _multiindex_full_key(self, axis: int, key: Any) -> bool:
        """Tuple key whose length spans every level of a MultiIndex axis
        (reference _multiindex_possibly_contains_key, indexing.py:664)."""
        if not isinstance(key, tuple) or not self.qc.has_multiindex(axis):
            return False
        if not all(is_scalar(k) for k in key):
            return False
        return len(key) == self.qc.get_axis(axis).nlevels

    def _normalize_key(self, loc: Any, axis: int) -> Any:
        """Materialize modin-object keys; align boolean Series masks by label
        (pandas ``check_bool_indexer`` semantics)."""
        from modin_tpu.pandas.base import BasePandasDataset
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if isinstance(loc, (DataFrame, pandas.DataFrame)):
            raise _FallbackToPandas()
        if isinstance(loc, Series):
            loc = loc._to_pandas()
        if isinstance(loc, BasePandasDataset):
            raise _FallbackToPandas()
        if isinstance(loc, pandas.Series):
            if is_bool_dtype(loc.dtype):
                labels = self.qc.get_axis(axis)
                if not loc.index.equals(labels):
                    loc = loc.reindex(labels)
                    if loc.isna().any():
                        raise pandas.errors.IndexingError(
                            "Unalignable boolean Series provided as indexer "
                            "(index of the boolean Series and of the indexed "
                            "object do not match)."
                        )
                return loc.to_numpy(dtype=bool)
            return loc.to_numpy()
        return loc

    def __setitem__(self, key: Any, value: Any) -> None:
        from modin_tpu.pandas.series import Series

        if callable(key):
            key = key(self.df)
        ndim_self = self.df.ndim
        index = self.df.index
        if isinstance(index, pandas.MultiIndex) or (
            ndim_self == 2 and isinstance(self.df.columns, pandas.MultiIndex)
        ):
            return self._fallback_set(key, value, "loc")
        if isinstance(key, tuple) and ndim_self == 2:
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)

        # The reference's boolean hot path (indexing.py:954): mask rows,
        # scalar value -> one named QC op
        if (
            ndim_self == 2
            and isinstance(row_key, Series)
            and is_boolean_array(row_key)
            and is_scalar(value)
            and not isinstance(col_key, slice)
        ):
            new_qc = self.qc.setitem_bool(row_key._query_compiler, col_key, value)
            self.df._update_inplace(new_qc)
            self.qc = self.df._query_compiler
            return

        try:
            row_norm = self._normalize_key(row_key, 0)
            col_norm = (
                self._normalize_key(col_key, 1) if ndim_self == 2 else slice(None)
            )
            row_lookup, col_lookup = self.qc.get_positions_from_labels(
                row_norm, col_norm
            )
        except KeyError:
            # missing labels: .loc setitem enlarges; keep pandas as the oracle
            return self._fallback_set(key, value, "loc")
        except (_FallbackToPandas, pandas.errors.IndexingError, TypeError):
            return self._fallback_set(key, value, "loc")
        if not self._write_positional(row_lookup, col_lookup, value):
            self._fallback_set(key, value, "loc")


class _AtIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        return self.df.loc[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self.df.loc[key] = value


class _iAtIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        return self.df.iloc[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self.df.iloc[key] = value
