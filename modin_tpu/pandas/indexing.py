"""``loc``/``iloc``/``at``/``iat`` indexers.

Reference design: /root/reference/modin/pandas/indexing.py (_LocationIndexerBase
:283, _LocIndexer :698, _iLocIndexer :1059): label keys are converted to
positions on the host (the index is host metadata), then a single
``take_2d_positional`` runs on the storage format.  Exotic cases (MultiIndex
partial keys, enlargement setitem) default to pandas.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import numpy as np
import pandas
from pandas.api.types import is_bool_dtype, is_list_like
from pandas.core.dtypes.common import is_bool, is_integer

from modin_tpu.logging import ClassLogger
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL


def is_boolean_array(x: Any) -> bool:
    if isinstance(x, (np.ndarray, pandas.Series, pandas.Index)):
        return is_bool_dtype(x.dtype)
    from modin_tpu.pandas.series import Series

    if isinstance(x, Series):
        return is_bool_dtype(x.dtype)
    return isinstance(x, (list, tuple)) and len(x) > 0 and all(is_bool(v) for v in x)


def is_integer_array(x: Any) -> bool:
    if isinstance(x, (np.ndarray, pandas.Series, pandas.Index)):
        return x.dtype.kind in "iu"
    return isinstance(x, (list, tuple)) and len(x) > 0 and all(is_integer(v) for v in x)


class _LocationIndexerBase(ClassLogger, modin_layer="PANDAS-API"):
    def __init__(self, modin_df: Any):
        self.df = modin_df
        self.qc = modin_df._query_compiler

    def _fallback_get(self, key: Any, attr: str) -> Any:
        return self.df._default_to_pandas(lambda obj: getattr(obj, attr)[key])

    def _fallback_set(self, key: Any, value: Any, attr: str) -> None:
        from modin_tpu.utils import try_cast_to_pandas

        value = try_cast_to_pandas(value)

        def setter(obj):
            obj = obj.copy()
            getattr(obj, attr)[key] = value
            return obj

        result = self.df._default_to_pandas(setter)
        self.df._update_inplace(result._query_compiler)

    def _wrap_row_series(self, row_qc: Any, label: Any) -> Any:
        """One selected row -> Series indexed by columns."""
        from modin_tpu.pandas.series import Series

        pandas_df = row_qc.to_pandas()
        row_series = pandas_df.iloc[0]
        row_series.name = label
        return self.df._wrap_pandas(row_series)


class _iLocIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if callable(key):
            return self.__getitem__(key(self.df))
        ndim = self.df.ndim
        if isinstance(key, tuple) and ndim == 2:
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)
            if isinstance(row_key, tuple) and ndim == 1:
                if len(row_key) > 1:
                    raise pandas.errors.IndexingError("Too many indexers")
                row_key = row_key[0] if row_key else slice(None)

        nrows = len(self.df.index)
        row_scalar = is_integer(row_key)
        col_scalar = is_integer(col_key)

        row_pos = self._positions(row_key, nrows, "row")
        if ndim == 1:
            if row_scalar:
                return self.df._to_pandas().iloc[row_key]
            new_qc = self.qc.take_2d_positional(index=row_pos)
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)

        ncols = len(self.df.columns)
        col_pos = self._positions(col_key, ncols, "column")
        if row_scalar and col_scalar:
            sub = self.qc.take_2d_positional(index=row_pos, columns=col_pos)
            return sub.to_pandas().iloc[0, 0]
        new_qc = self.qc.take_2d_positional(index=row_pos, columns=col_pos)
        if row_scalar:
            return self._wrap_row_series(new_qc, self.df.index[row_key])
        if col_scalar:
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)
        return DataFrame(query_compiler=new_qc)

    def _positions(self, axis_key: Any, length: int, axis_name: str) -> Any:
        if isinstance(axis_key, slice):
            return axis_key
        if is_integer(axis_key):
            if axis_key < -length or axis_key >= length:
                raise IndexError(
                    f"single positional indexer is out-of-bounds"
                )
            pos = axis_key if axis_key >= 0 else length + axis_key
            return [pos]
        if is_boolean_array(axis_key):
            mask = np.asarray(axis_key)
            if len(mask) != length:
                raise IndexError(
                    f"Boolean index has wrong length: {len(mask)} instead of {length}"
                )
            return list(np.nonzero(mask)[0])
        if is_list_like(axis_key):
            arr = np.asarray(axis_key, dtype=np.int64).ravel()
            if len(arr) and (arr.max(initial=-1) >= length or arr.min(initial=0) < -length):
                raise IndexError("positional indexers are out-of-bounds")
            return [int(i) if i >= 0 else length + int(i) for i in arr]
        raise TypeError(f"Cannot index by location index with a key of type {type(axis_key)}")

    def __setitem__(self, key: Any, value: Any) -> None:
        self._fallback_set(key, value, "iloc")


class _LocIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if callable(key):
            return self.__getitem__(key(self.df))
        ndim = self.df.ndim
        index = self.df.index
        if isinstance(index, pandas.MultiIndex):
            return self._fallback_get(key, "loc")
        if isinstance(key, tuple) and ndim == 2:
            if len(key) > 2:
                raise pandas.errors.IndexingError("Too many indexers")
            row_key = key[0]
            col_key = key[1] if len(key) > 1 else slice(None)
        else:
            row_key, col_key = key, slice(None)

        if ndim == 2 and isinstance(self.df.columns, pandas.MultiIndex):
            return self._fallback_get(key, "loc")
        if isinstance(row_key, DataFrame) or (
            ndim == 2 and isinstance(col_key, DataFrame)
        ):
            return self._fallback_get(key, "loc")

        try:
            row_pos, row_scalar, row_label = self._label_positions(row_key, index)
        except _FallbackToPandas:
            return self._fallback_get(key, "loc")

        if ndim == 1:
            if row_scalar:
                sub = self.qc.take_2d_positional(index=row_pos)
                return sub.to_pandas().iloc[0, 0]
            new_qc = self.qc.take_2d_positional(index=row_pos)
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)

        columns = self.df.columns
        try:
            col_pos, col_scalar, col_label = self._label_positions(col_key, columns)
        except _FallbackToPandas:
            return self._fallback_get(key, "loc")

        new_qc = self.qc.take_2d_positional(index=row_pos, columns=col_pos)
        if row_scalar and col_scalar:
            return new_qc.to_pandas().iloc[0, 0]
        if row_scalar:
            return self._wrap_row_series(new_qc, row_label)
        if col_scalar:
            new_qc._shape_hint = "column"
            return Series(query_compiler=new_qc)
        return DataFrame(query_compiler=new_qc)

    def _label_positions(self, axis_key: Any, labels: pandas.Index):
        """Return (positions, is_scalar, scalar_label); raise _FallbackToPandas."""
        from modin_tpu.pandas.series import Series

        if isinstance(axis_key, slice):
            if axis_key == slice(None):
                return axis_key, False, None
            try:
                start, stop = labels.slice_locs(axis_key.start, axis_key.stop, axis_key.step)
            except Exception:
                raise _FallbackToPandas()
            return slice(start, stop, axis_key.step), False, None
        if isinstance(axis_key, Series):
            if is_bool_dtype(axis_key.dtype):
                axis_key = axis_key._to_pandas()
            else:
                axis_key = axis_key.to_numpy()
        if isinstance(axis_key, pandas.Series):
            if is_bool_dtype(axis_key.dtype):
                axis_key = axis_key.reindex(labels).fillna(False).to_numpy()
            else:
                axis_key = axis_key.to_numpy()
        if is_boolean_array(axis_key):
            mask = np.asarray(axis_key)
            if len(mask) != len(labels):
                raise IndexError(
                    f"Boolean index has wrong length: {len(mask)} instead of {len(labels)}"
                )
            return list(np.nonzero(mask)[0]), False, None
        if is_list_like(axis_key) and not isinstance(axis_key, tuple):
            keys = list(axis_key)
            positions = labels.get_indexer_for(keys)
            if (np.asarray(positions) == -1).any():
                missing = [k for k, p in zip(keys, positions) if p == -1]
                raise KeyError(f"{missing} not in index")
            return list(positions), False, None
        # scalar label
        try:
            loc = labels.get_loc(axis_key)
        except (KeyError, TypeError):
            raise KeyError(axis_key)
        if isinstance(loc, slice):
            return loc, False, None
        if isinstance(loc, np.ndarray):
            return list(np.nonzero(loc)[0]) if loc.dtype == bool else list(loc), False, None
        return [int(loc)], True, axis_key

    def __setitem__(self, key: Any, value: Any) -> None:
        self._fallback_set(key, value, "loc")


class _FallbackToPandas(Exception):
    pass


class _AtIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        return self.df.loc[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._fallback_set(key, value, "at")


class _iAtIndexer(_LocationIndexerBase):
    def __getitem__(self, key: Any) -> Any:
        return self.df.iloc[key]

    def __setitem__(self, key: Any, value: Any) -> None:
        self._fallback_set(key, value, "iat")
