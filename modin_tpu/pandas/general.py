"""Free functions: concat, merge, pivot_table, to_datetime, get_dummies, ...

Reference design: /root/reference/modin/pandas/general.py (846 LoC).
"""

from __future__ import annotations

from typing import Any, Iterable, Optional, Union

import numpy as np
import pandas
from pandas._libs.lib import no_default

from modin_tpu.error_message import ErrorMessage
from modin_tpu.logging import enable_logging
from modin_tpu.pandas.dataframe import DataFrame
from modin_tpu.pandas.series import Series
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, try_cast_to_pandas


def _wrap(result: Any) -> Any:
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )

    if isinstance(result, pandas.DataFrame):
        return DataFrame(query_compiler=FactoryDispatcher.from_pandas(result))
    if isinstance(result, pandas.Series):
        frame = result.to_frame(
            result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
        )
        qc = FactoryDispatcher.from_pandas(frame)
        qc._shape_hint = "column"
        return Series(query_compiler=qc)
    return result


@enable_logging
def concat(
    objs: Iterable,
    *,
    axis: Any = 0,
    join: str = "outer",
    ignore_index: bool = False,
    keys: Any = None,
    levels: Any = None,
    names: Any = None,
    verify_integrity: bool = False,
    sort: bool = False,
    copy: Any = None,
) -> Union[DataFrame, Series]:
    if isinstance(objs, (pandas.Series, Series, DataFrame, str, pandas.DataFrame)):
        raise TypeError(
            "first argument must be an iterable of pandas objects, you passed "
            f"an object of type '{type(objs).__name__}'"
        )
    if isinstance(objs, dict):
        input_list_of_objs = list(objs.values())
        if keys is None:
            keys = list(objs.keys())
    else:
        input_list_of_objs = list(objs)
    if len(input_list_of_objs) == 0:
        raise ValueError("No objects to concatenate")
    list_of_objs = [obj for obj in input_list_of_objs if obj is not None]
    if len(list_of_objs) == 0:
        raise ValueError("All objects passed were None")

    axis_num = 0 if axis in (0, "index", None) else 1
    needs_fallback = (
        keys is not None
        or levels is not None
        or names is not None
        or verify_integrity
        or any(
            not isinstance(o, (DataFrame, Series, pandas.DataFrame, pandas.Series))
            for o in list_of_objs
        )
    )
    if needs_fallback:
        return _wrap(
            pandas.concat(
                try_cast_to_pandas(list_of_objs),
                axis=axis,
                join=join,
                ignore_index=ignore_index,
                keys=keys,
                levels=levels,
                names=names,
                verify_integrity=verify_integrity,
                sort=sort,
            )
        )

    all_series = all(isinstance(o, (Series, pandas.Series)) for o in list_of_objs)
    modin_objs = []
    for o in list_of_objs:
        if isinstance(o, pandas.DataFrame):
            o = DataFrame(o)
        elif isinstance(o, pandas.Series):
            o = Series(o)
        modin_objs.append(o)

    if all_series and axis_num == 0:
        return _wrap(
            pandas.concat(
                [o._to_pandas() for o in modin_objs],
                axis=axis, join=join, ignore_index=ignore_index, sort=sort,
            )
        )

    frames = []
    unnamed_counter = 0
    for o in modin_objs:
        if isinstance(o, Series):
            f = o.to_frame()
            if o.name is None and axis_num == 1:
                # pandas numbers only the unnamed series, sequentially
                f.columns = pandas.Index([unnamed_counter])
                unnamed_counter += 1
            frames.append(f)
        else:
            frames.append(o)
    base_qc = frames[0]._query_compiler
    other_qcs = [f._query_compiler for f in frames[1:]]
    if not other_qcs:
        result_qc = base_qc.copy()
        if ignore_index:
            result_qc = result_qc.reset_index(drop=True)
    else:
        result_qc = base_qc.concat(
            axis_num, other_qcs, join=join, ignore_index=ignore_index, sort=sort
        )
    return DataFrame(query_compiler=result_qc)


@enable_logging
def merge(
    left: Any,
    right: Any,
    how: str = "inner",
    on: Any = None,
    left_on: Any = None,
    right_on: Any = None,
    left_index: bool = False,
    right_index: bool = False,
    sort: bool = False,
    suffixes: Any = ("_x", "_y"),
    copy: Any = None,
    indicator: bool = False,
    validate: Any = None,
) -> DataFrame:
    if isinstance(left, (pandas.DataFrame, pandas.Series)):
        left = DataFrame(left) if isinstance(left, pandas.DataFrame) else Series(left)
    if isinstance(left, Series):
        if left.name is None:
            raise ValueError("Cannot merge a Series without a name")
        left = left.to_frame()
    if not isinstance(left, DataFrame):
        raise TypeError(
            f"Can only merge Series or DataFrame objects, a {type(left)} was passed"
        )
    return left.merge(
        right,
        how=how,
        on=on,
        left_on=left_on,
        right_on=right_on,
        left_index=left_index,
        right_index=right_index,
        sort=sort,
        suffixes=suffixes,
        indicator=indicator,
        validate=validate,
    )


@enable_logging
def merge_ordered(left: Any, right: Any, **kwargs: Any) -> DataFrame:
    return _wrap(
        pandas.merge_ordered(
            try_cast_to_pandas(left), try_cast_to_pandas(right), **kwargs
        )
    )


@enable_logging
def merge_asof(left: Any, right: Any, **kwargs: Any) -> DataFrame:
    return _wrap(
        pandas.merge_asof(try_cast_to_pandas(left), try_cast_to_pandas(right), **kwargs)
    )


@enable_logging
def pivot_table(data: Any, **kwargs: Any) -> DataFrame:
    if not isinstance(data, DataFrame):
        raise ValueError(f"can not create pivot table with instance of type {type(data)}")
    return data.pivot_table(**kwargs)


@enable_logging
def pivot(data: Any, **kwargs: Any) -> DataFrame:
    if not isinstance(data, DataFrame):
        raise ValueError(f"can not pivot with instance of type {type(data)}")
    return data.pivot(**kwargs)


@enable_logging
def crosstab(*args: Any, **kwargs: Any) -> DataFrame:
    return _wrap(pandas.crosstab(*try_cast_to_pandas(args), **try_cast_to_pandas(kwargs)))


@enable_logging
def lreshape(data: Any, groups: dict, dropna: bool = True) -> DataFrame:
    return _wrap(pandas.lreshape(try_cast_to_pandas(data), groups, dropna=dropna))


@enable_logging
def wide_to_long(df: Any, *args: Any, **kwargs: Any) -> DataFrame:
    return _wrap(pandas.wide_to_long(try_cast_to_pandas(df), *args, **kwargs))


@enable_logging
def melt(frame: Any, **kwargs: Any) -> DataFrame:
    return frame.melt(**kwargs) if isinstance(frame, DataFrame) else _wrap(
        pandas.melt(try_cast_to_pandas(frame), **kwargs)
    )


@enable_logging
def get_dummies(
    data: Any,
    prefix: Any = None,
    prefix_sep: str = "_",
    dummy_na: bool = False,
    columns: Any = None,
    sparse: bool = False,
    drop_first: bool = False,
    dtype: Any = None,
) -> DataFrame:
    if sparse:
        raise NotImplementedError("SparseDataFrame is not implemented in modin_tpu")
    if not isinstance(data, (DataFrame, Series)):
        return _wrap(
            pandas.get_dummies(
                data, prefix=prefix, prefix_sep=prefix_sep, dummy_na=dummy_na,
                columns=columns, sparse=sparse, drop_first=drop_first, dtype=dtype,
            )
        )
    if isinstance(data, Series):
        # string/categorical series one-hot on device through the dictionary
        # codes (one equality kernel per category)
        fast = getattr(data._query_compiler, "series_get_dummies", None)
        if fast is not None:
            qc = fast(
                prefix=prefix, prefix_sep=prefix_sep, dummy_na=dummy_na,
                drop_first=drop_first, dtype=dtype,
            )
            if qc is not None:
                return DataFrame(query_compiler=qc)
        # pandas encodes a Series regardless of dtype; go through the Series
        # kernel directly so numeric series are one-hot encoded too
        return _wrap(
            pandas.get_dummies(
                data._to_pandas(), prefix=prefix, prefix_sep=prefix_sep,
                dummy_na=dummy_na, drop_first=drop_first, dtype=dtype,
            )
        )
    qc = data._query_compiler.get_dummies(
        columns,
        prefix=prefix, prefix_sep=prefix_sep, dummy_na=dummy_na,
        drop_first=drop_first, dtype=dtype,
    )
    return DataFrame(query_compiler=qc)


@enable_logging
def cut(x: Any, bins: Any, **kwargs: Any):
    return _wrap(pandas.cut(try_cast_to_pandas(x, squeeze=True), bins, **kwargs))


@enable_logging
def qcut(x: Any, q: Any, **kwargs: Any):
    return _wrap(pandas.qcut(try_cast_to_pandas(x, squeeze=True), q, **kwargs))


@enable_logging
def unique(values: Any) -> np.ndarray:
    if isinstance(values, Series):
        return values.unique()
    return pandas.unique(try_cast_to_pandas(values))


@enable_logging
def factorize(values: Any, **kwargs: Any):
    return pandas.factorize(try_cast_to_pandas(values, squeeze=True), **kwargs)


@enable_logging
def value_counts(values: Any, **kwargs: Any) -> Series:
    if isinstance(values, Series):
        return values.value_counts(**kwargs)
    return _wrap(pandas.Series(try_cast_to_pandas(values)).value_counts(**kwargs))


@enable_logging
def to_datetime(arg: Any, **kwargs: Any):
    if isinstance(arg, Series):
        qc = arg._query_compiler.to_datetime(**kwargs)
        if hasattr(qc, "to_pandas"):
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return qc
    if isinstance(arg, DataFrame):
        return _wrap(pandas.to_datetime(arg._to_pandas(), **kwargs))
    return pandas.to_datetime(arg, **kwargs)


@enable_logging
def to_numeric(arg: Any, errors: str = "raise", downcast: Any = None, **kwargs: Any):
    if isinstance(arg, Series):
        qc = arg._query_compiler.to_numeric(errors=errors, downcast=downcast, **kwargs)
        if hasattr(qc, "to_pandas"):
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return qc
    return pandas.to_numeric(try_cast_to_pandas(arg), errors=errors, downcast=downcast, **kwargs)


@enable_logging
def to_timedelta(arg: Any, unit: Any = None, errors: str = "raise"):
    if isinstance(arg, Series):
        qc = arg._query_compiler.to_timedelta(unit=unit, errors=errors)
        if hasattr(qc, "to_pandas"):
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return qc
    return pandas.to_timedelta(try_cast_to_pandas(arg), unit=unit, errors=errors)


@enable_logging
def notna(obj: Any):
    if isinstance(obj, (DataFrame, Series)):
        return obj.notna()
    return pandas.notna(obj)


notnull = notna


@enable_logging
def isna(obj: Any):
    if isinstance(obj, (DataFrame, Series)):
        return obj.isna()
    return pandas.isna(obj)


isnull = isna


@enable_logging
def json_normalize(data: Any, **kwargs: Any) -> DataFrame:
    return _wrap(pandas.json_normalize(try_cast_to_pandas(data), **kwargs))


@enable_logging
def from_dummies(data: Any, **kwargs: Any) -> DataFrame:
    return _wrap(pandas.from_dummies(try_cast_to_pandas(data), **kwargs))


@enable_logging
def bdate_range(*args: Any, **kwargs: Any):
    return pandas.bdate_range(*args, **kwargs)


@enable_logging
def date_range(*args: Any, **kwargs: Any):
    return pandas.date_range(*args, **kwargs)


@enable_logging
def period_range(*args: Any, **kwargs: Any):
    return pandas.period_range(*args, **kwargs)


@enable_logging
def timedelta_range(*args: Any, **kwargs: Any):
    return pandas.timedelta_range(*args, **kwargs)


@enable_logging
def interval_range(*args: Any, **kwargs: Any):
    return pandas.interval_range(*args, **kwargs)
