"""``modin_tpu.pandas.plotting`` — pandas.plotting over materialized frames.

Reference design: /root/reference/modin/pandas/plotting.py.
"""

from __future__ import annotations

from typing import Any

from pandas import plotting as pdplot

from modin_tpu.utils import try_cast_to_pandas


class Plotting:
    """Proxy of pandas.plotting converting modin_tpu args to pandas first."""

    def __dir__(self):
        return dir(pdplot)

    def __getattr__(self, item: str) -> Any:
        target = getattr(pdplot, item)
        if callable(target):
            def wrapper(*args: Any, **kwargs: Any):
                return target(
                    *try_cast_to_pandas(list(args)), **try_cast_to_pandas(kwargs)
                )

            wrapper.__name__ = item
            return wrapper
        return target


Plotting = Plotting()
