"""``.str`` / ``.dt`` / ``.cat`` accessors for Series.

Reference design: /root/reference/modin/pandas/series_utils.py (838 LoC): each
accessor method dispatches to the matching ``str_*``/``dt_*``/``cat_*`` query
compiler method; results that are element-wise maps come back as Series.
"""

from __future__ import annotations

from typing import Any

import numpy as np
import pandas

from modin_tpu.logging import ClassLogger
from modin_tpu.utils import _inherit_docstrings


class _AccessorBase(ClassLogger, modin_layer="PANDAS-API"):
    _prefix = ""

    def __init__(self, series: Any) -> None:
        self._series = series
        self._query_compiler = series._query_compiler

    def _dispatch(self, name: str, *args: Any, **kwargs: Any) -> Any:
        from modin_tpu.pandas.series import Series

        qc_method = getattr(self._query_compiler, f"{self._prefix}{name}")
        result = qc_method(*args, **kwargs)
        if hasattr(result, "to_pandas"):
            result._shape_hint = "column"
            return Series(query_compiler=result)
        return result

    def _fallback(self, name: str, *args: Any, **kwargs: Any) -> Any:
        accessor = self._prefix.rstrip("_")
        return self._series._default_to_pandas(
            lambda s: getattr(getattr(s, accessor), name)(*args, **kwargs)
            if callable(getattr(getattr(s, accessor), name))
            else getattr(getattr(s, accessor), name)
        )


def _make_accessor_method(name: str):
    def method(self, *args: Any, **kwargs: Any):
        return self._dispatch(name, *args, **kwargs)

    method.__name__ = name
    return method


def _make_accessor_property(name: str):
    def getter(self):
        return self._dispatch(name)

    getter.__name__ = name
    return property(getter)


@_inherit_docstrings(pandas.core.strings.accessor.StringMethods)
class StringMethods(_AccessorBase):
    _prefix = "str_"

    def __getitem__(self, key: Any):
        return self._dispatch("__getitem__", key)

    def cat(self, others: Any = None, sep: Any = None, na_rep: Any = None, join: str = "left"):
        from modin_tpu.utils import try_cast_to_pandas

        others = try_cast_to_pandas(others, squeeze=True)
        return self._dispatch("cat", others=others, sep=sep, na_rep=na_rep, join=join)


for _name in [
    "capitalize", "casefold", "center", "contains", "count", "decode",
    "encode", "endswith", "extract", "extractall", "find", "findall",
    "fullmatch", "get", "get_dummies", "index", "join", "len", "ljust",
    "lower", "lstrip", "match", "normalize", "pad", "partition",
    "removeprefix", "removesuffix", "repeat", "replace", "rfind", "rindex",
    "rjust", "rpartition", "rsplit", "rstrip", "slice", "slice_replace",
    "split", "startswith", "strip", "swapcase", "title", "translate",
    "upper", "wrap", "zfill", "isalnum", "isalpha", "isdecimal", "isdigit",
    "islower", "isnumeric", "isspace", "istitle", "isupper",
]:
    setattr(StringMethods, _name, _make_accessor_method(_name))


@_inherit_docstrings(pandas.core.indexes.accessors.CombinedDatetimelikeProperties)
class DatetimeProperties(_AccessorBase):
    _prefix = "dt_"


for _name in [
    "date", "time", "timetz", "year", "month", "day", "hour", "minute",
    "second", "microsecond", "nanosecond", "dayofweek", "day_of_week",
    "weekday", "dayofyear", "day_of_year", "quarter", "is_month_start",
    "is_month_end", "is_quarter_start", "is_quarter_end", "is_year_start",
    "is_year_end", "is_leap_year", "daysinmonth", "days_in_month",
    "days", "seconds", "microseconds", "nanoseconds", "components",
    "start_time", "end_time",
]:
    setattr(DatetimeProperties, _name, _make_accessor_property(_name))

for _name in [
    "to_period", "to_pydatetime", "tz_localize", "tz_convert", "normalize",
    "strftime", "round", "floor", "ceil", "month_name", "day_name",
    "total_seconds", "to_pytimedelta", "asfreq", "isocalendar", "to_timestamp",
]:
    setattr(DatetimeProperties, _name, _make_accessor_method(_name))


def _dt_tz_getter(self):
    return self._series._to_pandas().dt.tz


DatetimeProperties.tz = property(_dt_tz_getter)
DatetimeProperties.freq = property(lambda self: self._series._to_pandas().dt.freq)
DatetimeProperties.unit = property(lambda self: self._series._to_pandas().dt.unit)


@_inherit_docstrings(pandas.core.arrays.categorical.CategoricalAccessor)
class CategoryMethods(_AccessorBase):
    _prefix = "cat_"

    @property
    def categories(self):
        return self._series.dtype.categories

    @property
    def ordered(self) -> bool:
        return self._series.dtype.ordered

    @property
    def codes(self):
        return self._dispatch("codes")


for _name in [
    "add_categories", "remove_categories", "remove_unused_categories",
    "rename_categories", "reorder_categories", "set_categories",
    "as_ordered", "as_unordered",
]:
    setattr(CategoryMethods, _name, _make_accessor_method(_name))

DatetimeProperties.as_unit = _make_accessor_method("as_unit")


class ListAccessor(_AccessorBase):
    """``.list`` accessor for ArrowDtype list columns (ref series_utils.py ListAccessor)."""

    _prefix = "list_"

    def __getitem__(self, key: Any):
        return self._dispatch("__getitem__", key)

    def flatten(self):
        return self._dispatch("flatten")

    def len(self):
        return self._dispatch("len")


class StructAccessor(_AccessorBase):
    """``.struct`` accessor for ArrowDtype struct columns (ref series_utils.py StructAccessor)."""

    _prefix = "struct_"

    @property
    def dtypes(self):
        return self._dispatch("dtypes")

    def explode(self):
        result = self._dispatch("explode")
        from modin_tpu.pandas.dataframe import DataFrame

        if hasattr(result, "_query_compiler"):
            qc = result._query_compiler
            qc._shape_hint = None
            return DataFrame(query_compiler=qc)
        return result

    def field(self, name_or_index: Any):
        return self._dispatch("field", name_or_index)
