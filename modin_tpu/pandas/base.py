"""``BasePandasDataset`` — everything DataFrame and Series share.

Reference design: /root/reference/modin/pandas/base.py:210 (~200 methods).  The
TPU build keeps the same shape: explicit implementations routed through the
query compiler for the hot/structural operations, and generated
default-to-pandas fallbacks (``_install_fallbacks``) for the long tail so the
full pandas surface is available from day one.
"""

from __future__ import annotations

import functools
import inspect
import pickle as pkl
import re
from typing import Any, Hashable, Optional, Sequence, Union

import numpy as np
import pandas
from pandas._libs.lib import no_default
from pandas.api.types import is_bool_dtype, is_list_like, is_numeric_dtype
from pandas.core.dtypes.common import is_integer

from modin_tpu.error_message import ErrorMessage
from modin_tpu.logging import ClassLogger, disable_logging
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, try_cast_to_pandas

_DEFAULT_BEHAVIOUR = {
    "__class__", "__init__", "__init_subclass__", "__new__", "__dict__",
    "__module__", "__qualname__", "__doc__", "__reduce__", "__reduce_ex__",
    "__getstate__", "__setstate__", "__subclasshook__", "__dir__", "__weakref__",
    "__sizeof__", "__delattr__", "__setattr__", "__getattr__", "__getattribute__",
    "__annotations__", "__abstractmethods__", "__slots__",
    "_constructor", "_constructor_sliced", "_constructor_expanddim",
    "_accessors", "_internal_names", "_internal_names_set", "_metadata",
    "_mgr", "_values", "_typ", "_AXIS_ORDERS", "_AXIS_TO_AXIS_NUMBER",
    "_HANDLED_TYPES", "_hidden_attrs", "_info_axis_name", "_info_axis_number",
}


class BasePandasDataset(ClassLogger, modin_layer="PANDAS-API"):
    """Implementation of the operations common to DataFrame and Series."""

    _pandas_class = pandas.DataFrame
    _query_compiler = None
    _siblings: list

    # ------------------------------------------------------------------ #
    # Internal plumbing
    # ------------------------------------------------------------------ #

    @disable_logging
    def _set_query_compiler(self, qc) -> None:
        object.__setattr__(self, "_query_compiler", qc)
        object.__setattr__(self, "_siblings", [])

    @property
    def __constructor__(self):
        return type(self)

    @classmethod
    def _get_axis_number(cls, axis: Any) -> int:
        if axis is no_default or axis is None:
            return 0
        if axis in (0, "index", "rows"):
            return 0
        if axis in (1, "columns"):
            return 1
        raise ValueError(f"No axis named {axis} for object type {cls.__name__}")

    def _create_or_update_from_compiler(self, new_query_compiler, inplace: bool = False):
        """Return a new object from the compiler, or update self in place."""
        assert new_query_compiler is not None
        if not inplace:
            return self.__constructor__(query_compiler=new_query_compiler)
        self._update_inplace(new_query_compiler)
        return None

    def _update_inplace(self, new_query_compiler) -> None:
        # NOTE: the old compiler is NOT freed here — lazy handles (GroupBy,
        # Rolling, Resampler) may still reference it; GC reclaims it.
        object.__setattr__(self, "_query_compiler", new_query_compiler)
        for sib in getattr(self, "_siblings", []):
            object.__setattr__(sib, "_query_compiler", new_query_compiler)

    def _add_sibling(self, sibling) -> None:
        sibling._siblings = self._siblings + [self]
        for sib in self._siblings:
            sib._siblings += [sibling]
        self._siblings += [sibling]

    @disable_logging
    def _wrap_pandas(self, result: Any) -> Any:
        """Wrap a raw pandas result into the matching modin_tpu object."""
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        qc_cls = type(self._query_compiler)
        if isinstance(result, pandas.DataFrame):
            return DataFrame(query_compiler=qc_cls.from_pandas(result))
        if isinstance(result, pandas.Series):
            name = result.name
            frame = result.to_frame(
                name if name is not None else MODIN_UNNAMED_SERIES_LABEL
            )
            qc = qc_cls.from_pandas(frame)
            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        return result

    def _default_to_pandas(self, op: Any, *args: Any, **kwargs: Any) -> Any:
        """Materialize, apply a pandas operation, wrap the result back.

        String ops with a named BaseQueryCompiler counterpart dispatch
        through the QC (``series_<op>`` for Series) so the whole long tail is
        visible to the caster/cost model and per-backend overrides —
        the reference's every-API-method-reaches-a-QC-method invariant
        (ref base/query_compiler.py:162); only the residue (callables, ops
        without a QC name) materializes here at the API layer.
        """
        if isinstance(op, str):
            routed = self._try_qc_dispatch(op, args, kwargs)
            if routed is not NotImplemented:
                return routed
        op_name = op if isinstance(op, str) else getattr(op, "__name__", str(op))
        ErrorMessage.default_to_pandas(f"`{type(self).__name__}.{op_name}`")
        args = try_cast_to_pandas(args)
        kwargs = try_cast_to_pandas(kwargs)
        pandas_obj = self._to_pandas()
        if callable(op):
            result = op(pandas_obj, *args, **kwargs)
        else:
            attr = getattr(pandas_obj, op)
            result = attr(*args, **kwargs) if callable(attr) else attr
        if result is None and kwargs.get("inplace", False):
            # the pandas op mutated pandas_obj in place
            return self._update_inplace_from_pandas(pandas_obj)
        return self._wrap_pandas(result)

    def _try_qc_dispatch(self, op: str, args: tuple, kwargs: dict) -> Any:
        """Dispatch a pandas-signature fallback through a named QC method.

        Returns ``NotImplemented`` when no route exists (caller materializes
        at the API layer instead).
        """
        from modin_tpu.core.storage_formats.base.query_compiler import (
            BaseQueryCompiler,
            DATAFRAME_QC_ROUTES,
            SERIES_QC_ROUTES,
        )

        routes = SERIES_QC_ROUTES if self.ndim == 1 else DATAFRAME_QC_ROUTES
        qc_name = routes.get(op)
        qc = self._query_compiler
        qc_method = getattr(type(qc), qc_name, None) if qc_name else None
        if qc_method is None:
            return NotImplemented
        if not getattr(qc_method, "_pandas_signature_default", False):
            # a backend override with a normalized (non-pandas) signature
            # shadows the generated default — routing pandas-signature args
            # into it would mis-bind, so take the API-layer fallback instead
            return NotImplemented
        args = try_cast_to_pandas(args)
        kwargs = try_cast_to_pandas(kwargs)
        # the QC level is out-of-place (reference invariant): compute a new
        # compiler, then adopt it in place here when the user asked for it
        inplace = bool(kwargs.get("inplace", False))
        if inplace:
            kwargs = {**kwargs, "inplace": False}
        result = qc_method(qc, *args, **kwargs)
        if isinstance(result, BaseQueryCompiler):
            if inplace:
                return self._create_or_update_from_compiler(result, inplace=True)
            return self._wrap_from_qc(result)
        return result

    def _wrap_from_qc(self, result_qc: Any) -> Any:
        """Wrap a result QC as Series/DataFrame based on its shape hint."""
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if result_qc._shape_hint == "column":
            return Series(query_compiler=result_qc)
        return DataFrame(query_compiler=result_qc)

    def _update_inplace_from_pandas(self, pandas_obj: Any) -> None:
        """Replace this object's contents with a mutated pandas object."""
        new_qc = type(self._query_compiler).from_pandas(
            pandas_obj
            if isinstance(pandas_obj, pandas.DataFrame)
            else pandas_obj.to_frame(
                pandas_obj.name
                if pandas_obj.name is not None
                else MODIN_UNNAMED_SERIES_LABEL
            )
        )
        # from_pandas always builds a frame-shaped QC; a Series must keep its
        # column hint or downstream squeezes (binary ops, casts) break
        new_qc._shape_hint = self._query_compiler._shape_hint
        return self._create_or_update_from_compiler(new_qc, inplace=True)

    def _reduce_dimension(self, query_compiler) -> Any:
        """Turn a reduction-result QC into a Series (DataFrame) or scalar (Series)."""
        from modin_tpu.pandas.series import Series

        if not hasattr(query_compiler, "to_pandas"):
            return query_compiler  # already a scalar
        query_compiler._shape_hint = "column"
        return Series(query_compiler=query_compiler)

    def _stat_operation(
        self,
        op_name: str,
        axis: Any = 0,
        skipna: bool = True,
        numeric_only: bool = False,
        **kwargs: Any,
    ) -> Any:
        axis = self._get_axis_number(axis) if axis is not None else None
        result_qc = getattr(self._query_compiler, op_name)(
            axis=axis, skipna=skipna, numeric_only=numeric_only, **kwargs
        )
        return self._reduce_dimension(result_qc)

    def _binary_op(self, op: str, other: Any, **kwargs: Any) -> Any:
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        squeeze_other = kwargs.pop("squeeze_other", isinstance(other, Series))
        if isinstance(other, BasePandasDataset):
            other_arg = other._query_compiler
            if type(other_arg) is not type(self._query_compiler):
                # mixed backends: coerce to the cheapest common one
                # (reference: query_compiler_caster + BackendCostCalculator)
                from modin_tpu.config import AutoSwitchBackend
                from modin_tpu.core.storage_formats.base.query_compiler_calculator import (
                    coerce_to_common_backend,
                )

                if AutoSwitchBackend.get():
                    self_qc, other_arg = coerce_to_common_backend(
                        [self._query_compiler, other_arg], op
                    )
                    if self_qc is not self._query_compiler:
                        self = self.__constructor__(query_compiler=self_qc)
        else:
            other_arg = other
        if squeeze_other and not isinstance(self, Series):
            kwargs["squeeze_other"] = True
        new_qc = getattr(self._query_compiler, op)(other_arg, **kwargs)
        if not hasattr(new_qc, "to_pandas"):
            return new_qc
        if isinstance(self, DataFrame) or isinstance(other, DataFrame):
            result_cls = DataFrame
        else:
            result_cls = Series
            new_qc = new_qc.columnarize()
        return result_cls(query_compiler=new_qc)

    # ------------------------------------------------------------------ #
    # Materialization & repr
    # ------------------------------------------------------------------ #

    def _to_pandas(self) -> Any:
        raise NotImplementedError

    def _build_repr_df(self, num_rows: int, num_cols: Optional[int] = None):
        """Gather only the head+tail window needed for display.

        Reference design: modin/pandas/base.py:282.
        """
        qc = self._query_compiler
        nrows = len(self.index)
        if nrows > num_rows:
            front = num_rows // 2 + 1
            back = num_rows - front + 2
            head = qc.row_slice(None, front)
            tail = qc.row_slice(nrows - back, None)
            qc = head.concat(0, [tail], ignore_index=False)
        if num_cols is not None:
            ncols = qc.get_axis_len(1)
            if ncols > num_cols:
                front = num_cols // 2 + 1
                back = num_cols - front + 2
                left = qc.getitem_column_array(range(front), numeric=True)
                right = qc.getitem_column_array(
                    range(ncols - back, ncols), numeric=True
                )
                qc = left.concat(1, [right])
        return qc.to_pandas()

    # ------------------------------------------------------------------ #
    # Metadata properties
    # ------------------------------------------------------------------ #

    def _get_index(self) -> pandas.Index:
        return self._query_compiler.index

    def _set_index(self, new_index: Any) -> None:
        if not isinstance(new_index, pandas.Index):
            new_index = pandas.Index(new_index)
        self._query_compiler.index = new_index

    index = property(_get_index, _set_index)

    @property
    def dtypes(self) -> Any:
        return self._query_compiler.dtypes

    @property
    def size(self) -> int:
        return np.prod(self.shape, dtype=np.int64)

    @property
    def empty(self) -> bool:
        return 0 in self.shape

    @property
    def values(self) -> np.ndarray:
        return self.to_numpy()

    @property
    def axes(self) -> list:
        if self.ndim == 1:
            return [self.index]
        return [self.index, self.columns]

    def __len__(self) -> int:
        return self._query_compiler.get_axis_len(0)

    # ------------------------------------------------------------------ #
    # Conversion
    # ------------------------------------------------------------------ #

    def to_numpy(self, dtype: Any = None, copy: bool = False, na_value: Any = no_default) -> np.ndarray:
        return self._query_compiler.to_numpy(dtype=dtype, copy=copy, na_value=na_value)

    def __array__(self, dtype: Any = None, copy: Optional[bool] = None) -> np.ndarray:
        arr = self.to_numpy(dtype)
        return arr

    def __array_ufunc__(self, ufunc: np.ufunc, method: str, *inputs: Any, **kwargs: Any) -> Any:
        """Numpy universal-function protocol: materialize, apply, wrap back."""
        pandas_inputs = [
            obj._to_pandas() if isinstance(obj, BasePandasDataset) else obj
            for obj in inputs
        ]
        result = getattr(ufunc, method)(*pandas_inputs, **kwargs)
        return self._wrap_pandas(result)

    def __array_wrap__(self, result: np.ndarray, context: Any = None, return_scalar: bool = False) -> Any:
        return result

    # ------------------------------------------------------------------ #
    # Copies & pickling
    # ------------------------------------------------------------------ #

    def copy(self, deep: bool = True):
        if deep:
            return self._create_or_update_from_compiler(self._query_compiler.copy())
        new_obj = self._create_or_update_from_compiler(self._query_compiler)
        self._add_sibling(new_obj)
        return new_obj

    def __copy__(self, deep: bool = True):
        return self.copy(deep=deep)

    def __deepcopy__(self, memo: Any = None):
        return self.copy(deep=True)

    def __sizeof__(self) -> int:
        return self._default_to_pandas("__sizeof__")

    # ------------------------------------------------------------------ #
    # Arithmetic / comparison operators
    # ------------------------------------------------------------------ #

    def _arith_method_factory(name):  # noqa: N805 — class-body helper
        def op(self, other, axis: Any = "columns", level: Any = None, fill_value: Any = None):
            if self.ndim == 1:
                axis = 0 if axis in (None, no_default, "columns") else self._get_axis_number(axis)
                return self._binary_op(name, other, axis=axis, level=level, fill_value=fill_value)
            return self._binary_op(name, other, axis=axis, level=level, fill_value=fill_value)

        op.__name__ = name
        return op

    add = _arith_method_factory("add")
    radd = _arith_method_factory("radd")
    sub = _arith_method_factory("sub")
    subtract = sub
    rsub = _arith_method_factory("rsub")
    mul = _arith_method_factory("mul")
    multiply = mul
    rmul = _arith_method_factory("rmul")
    truediv = _arith_method_factory("truediv")
    div = truediv
    divide = truediv
    rtruediv = _arith_method_factory("rtruediv")
    rdiv = rtruediv
    floordiv = _arith_method_factory("floordiv")
    rfloordiv = _arith_method_factory("rfloordiv")
    mod = _arith_method_factory("mod")
    rmod = _arith_method_factory("rmod")
    pow = _arith_method_factory("pow")
    rpow = _arith_method_factory("rpow")

    del _arith_method_factory

    def _comparison_method_factory(name):  # noqa: N805
        def op(self, other, axis: Any = "columns", level: Any = None):
            if self.ndim == 1:
                return self._binary_op(name, other, axis=0, level=level)
            return self._binary_op(name, other, axis=axis, level=level)

        op.__name__ = name
        return op

    eq = _comparison_method_factory("eq")
    ne = _comparison_method_factory("ne")
    lt = _comparison_method_factory("lt")
    le = _comparison_method_factory("le")
    gt = _comparison_method_factory("gt")
    ge = _comparison_method_factory("ge")

    del _comparison_method_factory

    def __add__(self, other):
        return self.add(other)

    def __radd__(self, other):
        return self.radd(other)

    def __sub__(self, other):
        return self.sub(other)

    def __rsub__(self, other):
        return self.rsub(other)

    def __mul__(self, other):
        return self.mul(other)

    def __rmul__(self, other):
        return self.rmul(other)

    def __truediv__(self, other):
        return self.truediv(other)

    def __rtruediv__(self, other):
        return self.rtruediv(other)

    def __floordiv__(self, other):
        return self.floordiv(other)

    def __rfloordiv__(self, other):
        return self.rfloordiv(other)

    def __mod__(self, other):
        return self.mod(other)

    def __rmod__(self, other):
        return self.rmod(other)

    def __pow__(self, other):
        return self.pow(other)

    def __rpow__(self, other):
        return self.rpow(other)

    def __eq__(self, other):
        return self.eq(other)

    def __ne__(self, other):
        return self.ne(other)

    def __lt__(self, other):
        return self.lt(other)

    def __le__(self, other):
        return self.le(other)

    def __gt__(self, other):
        return self.gt(other)

    def __ge__(self, other):
        return self.ge(other)

    def __and__(self, other):
        return self._binary_op("__and__", other, axis=0)

    def __rand__(self, other):
        return self._binary_op("__rand__", other, axis=0)

    def __or__(self, other):
        return self._binary_op("__or__", other, axis=0)

    def __ror__(self, other):
        return self._binary_op("__ror__", other, axis=0)

    def __xor__(self, other):
        return self._binary_op("__xor__", other, axis=0)

    def __rxor__(self, other):
        return self._binary_op("__rxor__", other, axis=0)

    def __neg__(self):
        return self._create_or_update_from_compiler(self._query_compiler.negative())

    def __invert__(self):
        return self._create_or_update_from_compiler(self._query_compiler.invert())

    def __abs__(self):
        return self.abs()

    def __round__(self, decimals: int = 0):
        return self.round(decimals)

    def __bool__(self) -> bool:
        raise ValueError(
            f"The truth value of a {type(self).__name__} is ambiguous. Use a.empty, "
            "a.bool(), a.item(), a.any() or a.all()."
        )

    @disable_logging
    def __hash__(self):
        raise TypeError(f"unhashable type: '{type(self).__name__}'")

    # ------------------------------------------------------------------ #
    # Elementwise maps
    # ------------------------------------------------------------------ #

    def abs(self):
        return self._create_or_update_from_compiler(self._query_compiler.abs())

    def round(self, decimals: int = 0, *args: Any, **kwargs: Any):
        return self._create_or_update_from_compiler(
            self._query_compiler.round(decimals=decimals)
        )

    def isna(self):
        return self._create_or_update_from_compiler(self._query_compiler.isna())

    isnull = isna

    def notna(self):
        return self._create_or_update_from_compiler(self._query_compiler.notna())

    notnull = notna

    def convert_dtypes(self, *args: Any, **kwargs: Any):
        return self._create_or_update_from_compiler(
            self._query_compiler.convert_dtypes(*args, **kwargs)
        )

    def infer_objects(self, copy: Any = None):
        return self._create_or_update_from_compiler(self._query_compiler.infer_objects())

    def astype(self, dtype: Any, copy: Any = None, errors: str = "raise"):
        if isinstance(dtype, dict) and self.ndim == 1:
            raise KeyError("Only the Series name can be used for the key in Series dtype mappings.")
        return self._create_or_update_from_compiler(
            self._query_compiler.astype(dtype, errors=errors)
        )

    def clip(self, lower: Any = None, upper: Any = None, *, axis: Any = None, inplace: bool = False, **kwargs: Any):
        axis = self._get_axis_number(axis) if axis is not None else None
        return self._create_or_update_from_compiler(
            self._query_compiler.clip(lower, upper, axis=axis, **kwargs), inplace
        )

    def fillna(
        self,
        value: Any = None,
        *,
        axis: Any = None,
        inplace: bool = False,
        limit: Optional[int] = None,
        downcast: Any = no_default,
    ):
        axis = self._get_axis_number(axis) if axis is not None else 0
        if isinstance(value, BasePandasDataset):
            value = value._query_compiler
        squeeze_value = (
            getattr(value, "_shape_hint", None) == "column"
            if value is not None and hasattr(value, "to_pandas")
            else False
        )
        new_qc = self._query_compiler.fillna(
            squeeze_self=self.ndim == 1,
            squeeze_value=squeeze_value,
            value=value,
            axis=axis,
            limit=limit,
        )
        return self._create_or_update_from_compiler(new_qc, inplace)

    def ffill(self, *, axis: Any = None, inplace: bool = False, limit: Optional[int] = None, limit_area: Any = None):
        return self._create_or_update_from_compiler(
            self._query_compiler.ffill(axis=axis, limit=limit), inplace
        )

    def bfill(self, *, axis: Any = None, inplace: bool = False, limit: Optional[int] = None, limit_area: Any = None):
        return self._create_or_update_from_compiler(
            self._query_compiler.bfill(axis=axis, limit=limit), inplace
        )

    def dropna(self, *, axis: Any = 0, how: Any = no_default, thresh: Any = no_default, subset: Any = None, inplace: bool = False, ignore_index: bool = False):
        axis = self._get_axis_number(axis)
        kwargs = {"axis": axis, "subset": subset, "ignore_index": ignore_index}
        if how is not no_default:
            kwargs["how"] = how
        if thresh is not no_default:
            kwargs["thresh"] = thresh
        if self.ndim == 1:
            kwargs.pop("subset")
            kwargs.pop("ignore_index") if "ignore_index" not in pandas.Series.dropna.__code__.co_varnames else None
        return self._create_or_update_from_compiler(
            self._query_compiler.dropna(**kwargs), inplace
        )

    def replace(self, to_replace: Any = None, value: Any = no_default, *, inplace: bool = False, regex: bool = False):
        kwargs = {"to_replace": to_replace, "regex": regex}
        if value is not no_default:
            kwargs["value"] = value
        return self._create_or_update_from_compiler(
            self._query_compiler.replace(**kwargs), inplace
        )

    # ------------------------------------------------------------------ #
    # Reductions
    # ------------------------------------------------------------------ #

    def _agg_reduce(self, op_name: str, axis: Any, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation(op_name, axis, skipna, numeric_only, **kwargs)

    def sum(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, min_count: int = 0, **kwargs: Any):
        return self._stat_operation("sum", axis, skipna, numeric_only, min_count=min_count, **kwargs)

    def prod(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, min_count: int = 0, **kwargs: Any):
        return self._stat_operation("prod", axis, skipna, numeric_only, min_count=min_count, **kwargs)

    product = prod

    def mean(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("mean", axis, skipna, numeric_only, **kwargs)

    def median(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("median", axis, skipna, numeric_only, **kwargs)

    def std(self, axis: Any = 0, skipna: bool = True, ddof: int = 1, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("std", axis, skipna, numeric_only, ddof=ddof, **kwargs)

    def var(self, axis: Any = 0, skipna: bool = True, ddof: int = 1, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("var", axis, skipna, numeric_only, ddof=ddof, **kwargs)

    def sem(self, axis: Any = 0, skipna: bool = True, ddof: int = 1, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("sem", axis, skipna, numeric_only, ddof=ddof, **kwargs)

    def skew(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("skew", axis, skipna, numeric_only, **kwargs)

    def kurt(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("kurt", axis, skipna, numeric_only, **kwargs)

    kurtosis = kurt

    def min(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("min", axis, skipna, numeric_only, **kwargs)

    def max(self, axis: Any = 0, skipna: bool = True, numeric_only: bool = False, **kwargs: Any):
        return self._stat_operation("max", axis, skipna, numeric_only, **kwargs)

    def count(self, axis: Any = 0, numeric_only: bool = False):
        axis = self._get_axis_number(axis)
        return self._reduce_dimension(
            self._query_compiler.count(axis=axis, numeric_only=numeric_only)
        )

    def any(self, *, axis: Any = 0, bool_only: bool = False, skipna: bool = True, **kwargs: Any):
        axis = self._get_axis_number(axis) if axis is not None else None
        return self._reduce_dimension(
            self._query_compiler.any(axis=axis, bool_only=bool_only, skipna=skipna)
        )

    def all(self, axis: Any = 0, bool_only: bool = False, skipna: bool = True, **kwargs: Any):
        axis = self._get_axis_number(axis) if axis is not None else None
        return self._reduce_dimension(
            self._query_compiler.all(axis=axis, bool_only=bool_only, skipna=skipna)
        )

    def nunique(self, axis: Any = 0, dropna: bool = True):
        axis = self._get_axis_number(axis)
        result = self._query_compiler.nunique(axis=axis, dropna=dropna)
        if self.ndim == 1:
            return result.to_pandas().squeeze() if hasattr(result, "to_pandas") else result
        return self._reduce_dimension(result)

    def memory_usage(self, index: bool = True, deep: bool = False):
        return self._default_to_pandas("memory_usage", index=index, deep=deep)

    # ------------------------------------------------------------------ #
    # Cumulative ops
    # ------------------------------------------------------------------ #

    def _cum_operation(self, op_name: str, axis: Any, skipna: bool, *args: Any, **kwargs: Any):
        axis = self._get_axis_number(axis)
        return self._create_or_update_from_compiler(
            getattr(self._query_compiler, op_name)(axis=axis, skipna=skipna)
        )

    def cumsum(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        return self._cum_operation("cumsum", axis, skipna, *args, **kwargs)

    def cumprod(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        return self._cum_operation("cumprod", axis, skipna, *args, **kwargs)

    def cummax(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        return self._cum_operation("cummax", axis, skipna, *args, **kwargs)

    def cummin(self, axis: Any = 0, skipna: bool = True, *args: Any, **kwargs: Any):
        return self._cum_operation("cummin", axis, skipna, *args, **kwargs)

    # ------------------------------------------------------------------ #
    # Ordering & window
    # ------------------------------------------------------------------ #

    def sort_index(
        self,
        *,
        axis: Any = 0,
        level: Any = None,
        ascending: bool = True,
        inplace: bool = False,
        kind: str = "quicksort",
        na_position: str = "last",
        sort_remaining: bool = True,
        ignore_index: bool = False,
        key: Any = None,
    ):
        axis = self._get_axis_number(axis)
        new_qc = self._query_compiler.sort_index(
            axis=axis,
            level=level,
            ascending=ascending,
            kind=kind,
            na_position=na_position,
            sort_remaining=sort_remaining,
            ignore_index=ignore_index,
            key=key,
        )
        return self._create_or_update_from_compiler(new_qc, inplace)

    def diff(self, periods: int = 1, axis: Any = 0):
        axis = self._get_axis_number(axis)
        kwargs = {"periods": periods}
        if self.ndim == 2:
            kwargs["axis"] = axis
        return self._create_or_update_from_compiler(
            self._query_compiler.diff(**kwargs)
        )

    def shift(self, periods: int = 1, freq: Any = None, axis: Any = 0, fill_value: Any = no_default, suffix: Any = None):
        kwargs = {"periods": periods, "freq": freq}
        if fill_value is not no_default:
            kwargs["fill_value"] = fill_value
        if self.ndim == 2:
            kwargs["axis"] = self._get_axis_number(axis)
        return self._create_or_update_from_compiler(self._query_compiler.shift(**kwargs))

    def rank(
        self,
        axis: Any = 0,
        method: str = "average",
        numeric_only: bool = False,
        na_option: str = "keep",
        ascending: bool = True,
        pct: bool = False,
    ):
        kwargs = dict(
            method=method,
            numeric_only=numeric_only,
            na_option=na_option,
            ascending=ascending,
            pct=pct,
        )
        if self.ndim == 2:
            kwargs["axis"] = self._get_axis_number(axis)
        return self._create_or_update_from_compiler(self._query_compiler.rank(**kwargs))

    def pct_change(self, periods: int = 1, fill_method: Any = no_default, limit: Any = no_default, freq: Any = None, **kwargs: Any):
        return self._default_to_pandas("pct_change", periods=periods, freq=freq, **kwargs)

    def rolling(self, window: Any, min_periods: Any = None, center: bool = False, win_type: Any = None, on: Any = None, closed: Any = None, step: Any = None, method: str = "single"):
        from modin_tpu.pandas.window import Rolling

        return Rolling(
            self,
            window=window,
            min_periods=min_periods,
            center=center,
            win_type=win_type,
            on=on,
            closed=closed,
            step=step,
            method=method,
        )

    def expanding(self, min_periods: int = 1, method: str = "single"):
        from modin_tpu.pandas.window import Expanding

        return Expanding(self, min_periods=min_periods, method=method)

    def ewm(
        self,
        com: Any = None,
        span: Any = None,
        halflife: Any = None,
        alpha: Any = None,
        min_periods: Any = 0,
        adjust: bool = True,
        ignore_na: bool = False,
        times: Any = None,
        method: str = "single",
    ):
        from modin_tpu.pandas.window import Ewm
        from modin_tpu.utils import try_cast_to_pandas

        return Ewm(
            self,
            com=com,
            span=span,
            halflife=halflife,
            alpha=alpha,
            min_periods=min_periods,
            adjust=adjust,
            ignore_na=ignore_na,
            times=try_cast_to_pandas(times, squeeze=True),
            method=method,
        )

    def resample(
        self,
        rule: Any,
        axis: Any = no_default,
        closed: Any = None,
        label: Any = None,
        convention: Any = no_default,
        on: Any = None,
        level: Any = None,
        origin: Any = "start_day",
        offset: Any = None,
        group_keys: bool = False,
    ):
        from modin_tpu.pandas.resample import Resampler

        return Resampler(
            self,
            rule=rule,
            closed=closed,
            label=label,
            on=on,
            level=level,
            origin=origin,
            offset=offset,
            group_keys=group_keys,
        )

    # ------------------------------------------------------------------ #
    # Selection
    # ------------------------------------------------------------------ #

    def head(self, n: int = 5):
        if n == 0:
            return self.iloc[:0]
        return self.iloc[:n]

    def tail(self, n: int = 5):
        if n == 0:
            return self.iloc[len(self) :]
        return self.iloc[-n:]

    def first(self, offset: Any):
        return self._default_to_pandas("first", offset)

    def last(self, offset: Any):
        return self._default_to_pandas("last", offset)

    def take(self, indices: Any, axis: Any = 0, **kwargs: Any):
        axis = self._get_axis_number(axis)
        if axis == 0:
            if isinstance(indices, slice):
                indices = range(*indices.indices(len(self.index)))
            else:
                n = len(self.index)
                indices = [i if i >= 0 else n + i for i in np.asarray(indices)]
            return self._create_or_update_from_compiler(
                self._query_compiler.getitem_row_array(indices)
            )
        n = self._query_compiler.get_axis_len(1)
        indices = [i if i >= 0 else n + i for i in np.asarray(indices)]
        return self._create_or_update_from_compiler(
            self._query_compiler.getitem_column_array(indices, numeric=True)
        )

    def sample(
        self,
        n: Optional[int] = None,
        frac: Optional[float] = None,
        replace: bool = False,
        weights: Any = None,
        random_state: Any = None,
        axis: Any = None,
        ignore_index: bool = False,
    ):
        axis = self._get_axis_number(axis) if axis is not None else 0
        if weights is not None or axis == 1:
            return self._default_to_pandas(
                "sample", n=n, frac=frac, replace=replace, weights=weights,
                random_state=random_state, axis=axis, ignore_index=ignore_index,
            )
        if n is None and frac is None:
            n = 1
        length = len(self.index)
        if n is None:
            n = int(length * frac)
        # pandas resolves seeds through np.random.RandomState (com.random_state),
        # so an int random_state must reproduce pandas' exact draw
        if isinstance(
            random_state, (np.random.RandomState, np.random.Generator)
        ):
            rng = random_state
        elif random_state is None:
            rng = np.random.default_rng()
        else:
            rng = np.random.RandomState(random_state)
        positions = rng.choice(length, n, replace=replace)
        result = self._create_or_update_from_compiler(
            self._query_compiler.getitem_row_array(list(positions))
        )
        if ignore_index:
            result.index = pandas.RangeIndex(len(result.index))
        return result

    def reindex(self, index: Any = None, columns: Any = None, copy: Any = None, **kwargs: Any):
        new_qc = None
        if index is not None:
            if not isinstance(index, pandas.Index):
                index = pandas.Index(index)
            if not index.equals(self.index):
                new_qc = self._query_compiler.reindex(axis=0, labels=index, **kwargs)
        if new_qc is None:
            new_qc = self._query_compiler
        final_qc = new_qc
        if columns is not None and self.ndim == 2:
            if not isinstance(columns, pandas.Index):
                columns = pandas.Index(columns)
            if not columns.equals(new_qc.columns):
                final_qc = new_qc.reindex(axis=1, labels=columns, **kwargs)
        return self._create_or_update_from_compiler(final_qc)

    def reindex_like(
        self,
        other: Any,
        method: Any = None,
        copy: Any = no_default,
        limit: Any = None,
        tolerance: Any = None,
    ):
        kwargs: dict = {}
        if method is not None:
            kwargs["method"] = method
        if limit is not None:
            kwargs["limit"] = limit
        if tolerance is not None:
            kwargs["tolerance"] = tolerance
        return self.reindex(
            index=other.index,
            columns=other.columns if self.ndim == 2 else None,
            **kwargs,
        )

    def rename_axis(
        self,
        mapper: Any = no_default,
        *,
        index: Any = no_default,
        columns: Any = no_default,
        axis: Any = 0,
        copy: Any = None,
        inplace: bool = False,
    ):
        # metadata-only: pandas resolves the mapper semantics against an empty
        # shell carrying our axis labels, then the new names apply in place
        obj = self if inplace else self.copy()
        if self.ndim == 2:
            shell = pandas.DataFrame(index=self.index[:0], columns=self.columns)
            shell.rename_axis(
                mapper, index=index, columns=columns, axis=axis, inplace=True
            )
            if list(shell.index.names) != list(obj.index.names):
                obj.index = obj.index.set_names(shell.index.names)
            if list(shell.columns.names) != list(obj.columns.names):
                obj.columns = obj.columns.set_names(shell.columns.names)
        else:
            shell = pandas.Series(index=self.index[:0], dtype="float64")
            shell.rename_axis(mapper, index=index, axis=axis, inplace=True)
            if list(shell.index.names) != list(obj.index.names):
                obj.index = obj.index.set_names(shell.index.names)
        return None if inplace else obj

    def drop(
        self,
        labels: Any = None,
        *,
        axis: Any = 0,
        index: Any = None,
        columns: Any = None,
        level: Any = None,
        inplace: bool = False,
        errors: str = "raise",
    ):
        if labels is not None:
            if index is not None or columns is not None:
                raise ValueError("Cannot specify both 'labels' and 'index'/'columns'")
            axis_num = self._get_axis_number(axis)
            if axis_num == 0:
                index = labels
            else:
                columns = labels
        if level is not None:
            return self._create_or_update_from_compiler(
                self._default_to_pandas(
                    "drop", index=index, columns=columns, level=level, errors=errors
                )._query_compiler,
                inplace,
            )
        # validate labels exist when errors='raise'
        if errors == "raise":
            if index is not None:
                missing = pandas.Index(np.atleast_1d(np.asarray(index, dtype=object))).difference(self.index)
                if len(missing):
                    raise KeyError(f"{list(missing)} not found in axis")
            if columns is not None and self.ndim == 2:
                missing = pandas.Index(np.atleast_1d(np.asarray(columns, dtype=object))).difference(self.columns)
                if len(missing):
                    raise KeyError(f"{list(missing)} not found in axis")
        new_qc = self._query_compiler.drop(index=index, columns=columns, errors=errors)
        return self._create_or_update_from_compiler(new_qc, inplace)

    def reset_index(
        self,
        level: Any = None,
        *,
        drop: bool = False,
        inplace: bool = False,
        col_level: Any = 0,
        col_fill: Any = "",
        allow_duplicates: Any = no_default,
        names: Any = None,
    ):
        kwargs = {
            "level": level,
            "drop": drop,
            "col_level": col_level,
            "col_fill": col_fill,
            "names": names,
        }
        if self.ndim == 1:
            kwargs = {"level": level, "drop": drop, "names": names}
            if not drop:
                from modin_tpu.pandas.series import Series

                return Series(query_compiler=self._query_compiler)._series_reset_index(
                    level, names, inplace
                )
        new_qc = self._query_compiler.reset_index(**kwargs)
        return self._create_or_update_from_compiler(new_qc, inplace)

    def set_axis(self, labels: Any, *, axis: Any = 0, copy: Any = None):
        obj = self.copy()
        setattr(obj, "index" if self._get_axis_number(axis) == 0 else "columns", labels)
        return obj

    def add_prefix(self, prefix: str, axis: Any = None):
        axis = self._get_axis_number(axis) if axis is not None else (0 if self.ndim == 1 else 1)
        return self._create_or_update_from_compiler(
            self._query_compiler.add_prefix(prefix, axis=axis)
            if self.ndim == 2
            else self._query_compiler.add_prefix(prefix)
        )

    def add_suffix(self, suffix: str, axis: Any = None):
        axis = self._get_axis_number(axis) if axis is not None else (0 if self.ndim == 1 else 1)
        return self._create_or_update_from_compiler(
            self._query_compiler.add_suffix(suffix, axis=axis)
            if self.ndim == 2
            else self._query_compiler.add_suffix(suffix)
        )

    def truncate(self, before: Any = None, after: Any = None, axis: Any = None, copy: Any = None):
        return self._default_to_pandas("truncate", before=before, after=after, axis=axis)

    def droplevel(self, level: Any, axis: Any = 0):
        return self._default_to_pandas("droplevel", level, axis=axis)

    def squeeze(self, axis: Any = None):
        axis = self._get_axis_number(axis) if axis is not None else None
        if self.ndim == 1:
            if len(self.index) == 1 and axis in (None, 0):
                return self._to_pandas().squeeze()
            return self.copy()
        # DataFrame
        nrows, ncols = len(self.index), len(self.columns)
        from modin_tpu.pandas.series import Series

        if axis == 1 or (axis is None and ncols == 1):
            if ncols == 1:
                result_qc = self._query_compiler.columnarize()
                if axis is None and nrows == 1:
                    return self._to_pandas().squeeze()
                return Series(query_compiler=result_qc)
            if axis == 1:
                return self.copy()
        if axis == 0 or (axis is None and nrows == 1):
            if nrows == 1:
                return self._default_to_pandas("squeeze", axis=axis)
            if axis == 0:
                return self.copy()
        return self.copy()

    def between_time(self, start_time: Any, end_time: Any, inclusive: str = "both", axis: Any = None):
        return self._default_to_pandas(
            "between_time", start_time, end_time, inclusive=inclusive, axis=axis
        )

    def at_time(self, time: Any, asof: bool = False, axis: Any = None):
        return self._default_to_pandas("at_time", time, asof=asof, axis=axis)

    def first_valid_index(self):
        return self._query_compiler.first_valid_index()

    def last_valid_index(self):
        return self._query_compiler.last_valid_index()

    # ------------------------------------------------------------------ #
    # Function application
    # ------------------------------------------------------------------ #

    def pipe(self, func: Any, *args: Any, **kwargs: Any):
        if isinstance(func, tuple):
            func, target = func
            if target in kwargs:
                raise ValueError(f"{target} is both the pipe target and a keyword argument")
            kwargs[target] = self
            return func(*args, **kwargs)
        return func(self, *args, **kwargs)

    def transform(self, func: Any, axis: Any = 0, *args: Any, **kwargs: Any):
        return self._default_to_pandas("transform", func, axis, *args, **kwargs)

    # ------------------------------------------------------------------ #
    # Combination
    # ------------------------------------------------------------------ #

    def align(self, other: Any, **kwargs: Any):
        left, right = self._default_to_pandas(
            "align", other._to_pandas() if isinstance(other, BasePandasDataset) else other, **kwargs
        )
        return left, right

    def combine(self, other: Any, func: Any, fill_value: Any = None, **kwargs: Any):
        return self._binary_op("combine", other, func=func, fill_value=fill_value)

    def combine_first(self, other: Any):
        return self._binary_op("combine_first", other)

    def where(self, cond: Any, other: Any = np.nan, *, inplace: bool = False, axis: Any = None, level: Any = None):
        if callable(cond) or callable(other):
            return self._create_or_update_from_compiler(
                self._default_to_pandas(
                    "where", cond, other, axis=axis, level=level
                )._query_compiler,
                inplace,
            )
        if isinstance(cond, BasePandasDataset):
            cond = cond._query_compiler
        if isinstance(other, BasePandasDataset):
            other = other._query_compiler
        return self._create_or_update_from_compiler(
            self._query_compiler.where(cond, other, axis=axis, level=level), inplace
        )

    def mask(self, cond: Any, other: Any = np.nan, *, inplace: bool = False, axis: Any = None, level: Any = None):
        if callable(cond) or callable(other):
            return self._create_or_update_from_compiler(
                self._default_to_pandas(
                    "mask", cond, other, axis=axis, level=level
                )._query_compiler,
                inplace,
            )
        if isinstance(cond, BasePandasDataset):
            inverted = ~cond
        else:
            inverted = ~np.asarray(cond)
        return self.where(inverted, other, inplace=inplace, axis=axis, level=level)

    def isin(self, values: Any):
        ignore_indices = isinstance(values, BasePandasDataset) and values.ndim == 1
        if isinstance(values, BasePandasDataset):
            values = values._query_compiler
        return self._create_or_update_from_compiler(
            self._query_compiler.isin(values, ignore_indices=ignore_indices)
        )

    # ------------------------------------------------------------------ #
    # IO / export
    # ------------------------------------------------------------------ #

    def to_csv(self, path_or_buf: Any = None, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_csv(self._query_compiler, path_or_buf=path_or_buf, **kwargs)

    def to_json(self, path_or_buf: Any = None, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_json(self._query_compiler, path_or_buf=path_or_buf, **kwargs)

    def to_sql(self, name: str, con: Any, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_sql(self._query_compiler, name=name, con=con, **kwargs)

    def to_pickle(self, path: Any, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_pickle(self._query_compiler, path=path, **kwargs)

    def to_dict(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_dict", *args, **kwargs)

    def to_string(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_string", *args, **kwargs)

    def to_latex(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_latex", *args, **kwargs)

    def to_markdown(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_markdown", *args, **kwargs)

    def to_clipboard(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_clipboard", *args, **kwargs)

    def to_xarray(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_xarray", *args, **kwargs)

    def to_hdf(self, path_or_buf: Any, *, key: str, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.to_hdf(
            self._query_compiler, path_or_buf=path_or_buf, key=key, **kwargs
        )

    def to_excel(self, excel_writer: Any, *args: Any, **kwargs: Any):
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        if args:
            # re-bind positionals (sheet_name, na_rep, ...) onto names
            sig = inspect.signature(pandas.DataFrame.to_excel)
            bound = sig.bind(self, excel_writer, *args, **kwargs)
            kwargs = {
                k: v for k, v in bound.arguments.items()
                if k not in ("self", "excel_writer")
            }
        return FactoryDispatcher.to_excel(
            self._query_compiler, excel_writer=excel_writer, **kwargs
        )

    # ------------------------------------------------------------------ #
    # Pickle support (by value)
    # ------------------------------------------------------------------ #

    def __getstate__(self) -> dict:
        from modin_tpu.config import PersistentPickle

        state = {"_pandas_obj": self._to_pandas()}
        return state

    def __setstate__(self, state: dict) -> None:
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        pandas_obj = state["_pandas_obj"]
        if isinstance(pandas_obj, pandas.Series):
            pandas_obj = pandas_obj.to_frame(
                pandas_obj.name if pandas_obj.name is not None else MODIN_UNNAMED_SERIES_LABEL
            )
            qc = FactoryDispatcher.from_pandas(pandas_obj)
            qc._shape_hint = "column"
        else:
            qc = FactoryDispatcher.from_pandas(pandas_obj)
        self._set_query_compiler(qc)

    # ------------------------------------------------------------------ #
    # Indexer properties (shared)
    # ------------------------------------------------------------------ #

    @property
    def loc(self):
        from modin_tpu.pandas.indexing import _LocIndexer

        return _LocIndexer(self)

    @property
    def iloc(self):
        from modin_tpu.pandas.indexing import _iLocIndexer

        return _iLocIndexer(self)

    @property
    def at(self):
        from modin_tpu.pandas.indexing import _AtIndexer

        return _AtIndexer(self)

    @property
    def iat(self):
        from modin_tpu.pandas.indexing import _iAtIndexer

        return _iAtIndexer(self)

    # ------------------------------------------------------------------ #
    # Misc
    # ------------------------------------------------------------------ #

    @property
    def flags(self):
        return self._default_to_pandas(lambda df: df.flags)

    @property
    def attrs(self) -> dict:
        if not hasattr(self, "_attrs"):
            object.__setattr__(self, "_attrs", {})
        return self._attrs

    @attrs.setter
    def attrs(self, value: dict) -> None:
        object.__setattr__(self, "_attrs", dict(value))

    def set_flags(self, *, copy: Any = None, allows_duplicate_labels: Any = None):
        return self._default_to_pandas(
            "set_flags", allows_duplicate_labels=allows_duplicate_labels
        )

    def get(self, key: Any, default: Any = None):
        try:
            return self.__getitem__(key)
        except (KeyError, ValueError, IndexError):
            return default

    def asof(self, where: Any, subset: Any = None):
        return self._default_to_pandas("asof", where, subset=subset)

    def interpolate(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("interpolate", *args, **kwargs)

    def xs(self, key: Any, axis: Any = 0, level: Any = None, drop_level: bool = True):
        return self._default_to_pandas("xs", key, axis=axis, level=level, drop_level=drop_level)

    def swaplevel(self, i: Any = -2, j: Any = -1, axis: Any = 0):
        return self._default_to_pandas("swaplevel", i=i, j=j, axis=axis)

    def reorder_levels(self, order: Any, axis: Any = 0):
        return self._default_to_pandas("reorder_levels", order, axis=axis)

    def tz_convert(self, tz: Any, axis: Any = 0, level: Any = None, copy: Any = None):
        return self._default_to_pandas("tz_convert", tz, axis=axis, level=level)

    def tz_localize(self, tz: Any, axis: Any = 0, level: Any = None, copy: Any = None, ambiguous: Any = "raise", nonexistent: Any = "raise"):
        return self._default_to_pandas(
            "tz_localize", tz, axis=axis, level=level, ambiguous=ambiguous, nonexistent=nonexistent
        )

    def to_period(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_period", *args, **kwargs)

    def to_timestamp(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("to_timestamp", *args, **kwargs)

    def asfreq(self, *args: Any, **kwargs: Any):
        return self._default_to_pandas("asfreq", *args, **kwargs)

    def filter(self, items: Any = None, like: Any = None, regex: Any = None, axis: Any = None):
        nkw = sum(x is not None for x in (items, like, regex))
        if nkw > 1:
            raise TypeError("Keyword arguments `items`, `like`, or `regex` are mutually exclusive")
        if axis is None:
            axis = 1 if self.ndim == 2 else 0
        axis = self._get_axis_number(axis)
        labels = self.columns if axis == 1 else self.index
        if items is not None:
            keep = [label for label in items if label in labels]
        elif like is not None:
            keep = [label for label in labels if like in str(label)]
        else:
            matcher = re.compile(regex)
            keep = [label for label in labels if matcher.search(str(label))]
        if axis == 1:
            return self[keep] if self.ndim == 2 else self
        return self.loc[keep]

    def __finalize__(self, other: Any, method: Any = None, **kwargs: Any):
        return self

    def __nonzero__(self):
        raise ValueError(
            f"The truth value of a {type(self).__name__} is ambiguous. Use a.empty, "
            "a.bool(), a.item(), a.any() or a.all()."
        )


def _install_fallbacks(modin_cls: type, pandas_cls: type) -> None:
    """Generate default-to-pandas wrappers for every pandas API member the
    modin_tpu class doesn't implement explicitly.

    This is how the full pandas surface is available from day one (the
    reference reaches the same end state by enumerating ~200 methods per class
    against the defaulting query compiler; we generate the long tail).
    """

    def make_method(name: str, pandas_method: Any):
        @functools.wraps(pandas_method)
        def fallback(self, *args: Any, **kwargs: Any):
            return self._default_to_pandas(name, *args, **kwargs)

        fallback.__name__ = name
        return fallback

    def make_property(name: str):
        def getter(self):
            result = self._default_to_pandas(
                lambda pandas_obj: getattr(pandas_obj, name)
            )
            return result

        def setter(self, value):
            # materialize, delegate the assignment to pandas (so non-settable
            # properties raise pandas' own error), and resync in place
            pandas_obj = self._to_pandas()
            setattr(pandas_obj, name, value)
            self._update_inplace_from_pandas(pandas_obj)

        return property(getter, setter)

    def make_classmethod(name: str):
        def cm(cls, *args: Any, **kwargs: Any):
            result = getattr(pandas_cls, name)(*args, **kwargs)
            if isinstance(result, (pandas.DataFrame, pandas.Series)):
                return cls(result)
            return result

        cm.__name__ = name
        return classmethod(cm)

    defined = set()
    for klass in modin_cls.__mro__:
        if klass in (object,):
            continue
        if klass.__module__.startswith("modin_tpu"):
            defined.update(vars(klass).keys())

    for name in dir(pandas_cls):
        if name in defined or name in _DEFAULT_BEHAVIOUR:
            continue
        if name.startswith("_") and not name.startswith("__"):
            continue
        try:
            attr = getattr(pandas_cls, name)
        except Exception:
            continue
        raw = inspect.getattr_static(pandas_cls, name)
        if isinstance(raw, (classmethod, staticmethod)):
            setattr(modin_cls, name, make_classmethod(name))
        elif isinstance(attr, property):
            setattr(modin_cls, name, make_property(name))
        elif isinstance(attr, functools.cached_property):
            setattr(modin_cls, name, make_property(name))
        elif callable(attr):
            setattr(modin_cls, name, make_method(name, attr))
        else:
            # plain class attribute (e.g. dtype sentinel) — copy the value
            try:
                setattr(modin_cls, name, attr)
            except Exception:
                pass
