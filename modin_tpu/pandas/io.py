"""All ``read_*`` entry points, dispatched through the factory system.

Reference design: /root/reference/modin/pandas/io.py (1,272 LoC; the ``_read``
indirection at io.py:106-134).
"""

from __future__ import annotations

import inspect
import pickle
from typing import Any

import pandas

from modin_tpu.error_message import ErrorMessage
from modin_tpu.logging import enable_logging
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, expanduser_path_arg


def _read(reader_name: str, **kwargs: Any) -> Any:
    """Route a read_* call through the current factory and wrap the result."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )
    from modin_tpu.pandas.dataframe import DataFrame
    from modin_tpu.pandas.series import Series

    result = getattr(FactoryDispatcher, reader_name)(**kwargs)

    def wrap(qc: Any) -> Any:
        if hasattr(qc, "to_pandas"):
            if qc._shape_hint == "column":
                return Series(query_compiler=qc)
            return DataFrame(query_compiler=qc)
        return qc

    if isinstance(result, dict):
        return {k: wrap(v) for k, v in result.items()}
    if isinstance(result, list):
        return [wrap(v) for v in result]
    return wrap(result)


def _make_reader(name: str):
    pandas_fn = getattr(pandas, name)
    sig = inspect.signature(pandas_fn)

    def reader(*args: Any, **kwargs: Any) -> Any:
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
        params: dict = {}
        for arg_name, value in bound.arguments.items():
            kind = sig.parameters[arg_name].kind
            if kind == inspect.Parameter.VAR_KEYWORD:
                params.update(value)
            elif kind == inspect.Parameter.VAR_POSITIONAL:
                raise TypeError(
                    f"{name} does not support extra positional arguments in modin_tpu"
                )
            else:
                params[arg_name] = value
        return _read(name, **params)

    reader.__name__ = name
    reader.__qualname__ = name
    reader.__doc__ = pandas_fn.__doc__
    reader = enable_logging(reader)
    try:
        reader.__signature__ = sig
    except (ValueError, TypeError):
        pass
    return reader


read_csv = _make_reader("read_csv")
read_table = _make_reader("read_table")
read_parquet = _make_reader("read_parquet")
read_json = _make_reader("read_json")
read_fwf = _make_reader("read_fwf")
read_excel = _make_reader("read_excel")
read_feather = _make_reader("read_feather")
read_stata = _make_reader("read_stata")
read_sas = _make_reader("read_sas")
read_pickle = _make_reader("read_pickle")
read_sql = _make_reader("read_sql")
read_sql_query = _make_reader("read_sql_query")
read_sql_table = _make_reader("read_sql_table")
read_html = _make_reader("read_html")
read_xml = _make_reader("read_xml")
read_clipboard = _make_reader("read_clipboard")
read_hdf = _make_reader("read_hdf")
read_spss = _make_reader("read_spss")
read_orc = _make_reader("read_orc")


@enable_logging
def to_pickle(obj: Any, filepath_or_buffer: Any, **kwargs: Any) -> None:
    from modin_tpu.pandas.base import BasePandasDataset

    if isinstance(obj, BasePandasDataset):
        obj.to_pickle(filepath_or_buffer, **kwargs)
        return
    pandas.to_pickle(obj, filepath_or_buffer, **kwargs)


@enable_logging
def json_normalize(*args: Any, **kwargs: Any):
    from modin_tpu.pandas.general import json_normalize as _json_normalize

    return _json_normalize(*args, **kwargs)


class ExcelFile(pandas.ExcelFile):
    """Wrapper of pandas.ExcelFile whose ``parse`` returns modin_tpu frames."""

    def parse(self, *args: Any, **kwargs: Any):
        from modin_tpu.pandas.dataframe import DataFrame

        result = super().parse(*args, **kwargs)
        if isinstance(result, dict):
            return {k: DataFrame(v) for k, v in result.items()}
        return DataFrame(result)


class HDFStore(pandas.HDFStore):
    """Wrapper of pandas.HDFStore returning modin_tpu frames from get/select."""

    def __getitem__(self, key: Any):
        from modin_tpu.pandas.dataframe import DataFrame

        result = super().__getitem__(key)
        if isinstance(result, pandas.DataFrame):
            return DataFrame(result)
        return result
