"""``Rolling`` / ``Expanding`` window objects.

Reference design: /root/reference/modin/pandas/window.py (526 LoC): a lazy
handle (object, window kwargs) dispatching to ``rolling_*``/``expanding_*``
query-compiler methods.
"""

from __future__ import annotations

from typing import Any, Optional

import pandas

from modin_tpu.logging import ClassLogger
from modin_tpu.utils import _inherit_docstrings

_ROLLING_AGGS = [
    "count", "sum", "mean", "median", "var", "std", "min", "max", "skew",
    "kurt", "sem", "quantile", "rank",
]


@_inherit_docstrings(pandas.core.window.rolling.Rolling)
class Rolling(ClassLogger, modin_layer="PANDAS-API"):
    def __init__(self, dataframe: Any, **rolling_kwargs: Any) -> None:
        self._dataframe = dataframe
        self.rolling_kwargs = rolling_kwargs

    @property
    def _query_compiler(self):
        return self._dataframe._query_compiler

    def _agg(self, name: str, *args: Any, **kwargs: Any):
        qc_method = getattr(self._query_compiler, f"rolling_{name}")
        new_qc = qc_method(self.rolling_kwargs, *args, **kwargs)
        return self._wrap(new_qc)

    def _wrap(self, qc: Any):
        if not hasattr(qc, "to_pandas"):
            return qc
        if self._dataframe.ndim == 1:
            from modin_tpu.pandas.series import Series

            qc._shape_hint = "column"
            return Series(query_compiler=qc)
        from modin_tpu.pandas.dataframe import DataFrame

        return DataFrame(query_compiler=qc)

    def aggregate(self, func: Any, *args: Any, **kwargs: Any):
        return self._wrap(
            self._query_compiler.rolling_aggregate(0, self.rolling_kwargs, func, *args, **kwargs)
        )

    agg = aggregate

    def apply(self, func: Any, raw: bool = False, engine: Any = None, engine_kwargs: Any = None, args: Any = None, kwargs: Any = None):
        return self._agg("apply", func=func, raw=raw, args=args or (), kwargs=kwargs or {})

    def corr(self, other: Any = None, pairwise: Any = None, ddof: int = 1, **kwargs: Any):
        from modin_tpu.utils import try_cast_to_pandas

        return self._agg("corr", other=try_cast_to_pandas(other, squeeze=True), pairwise=pairwise, ddof=ddof, **kwargs)

    def cov(self, other: Any = None, pairwise: Any = None, ddof: int = 1, **kwargs: Any):
        from modin_tpu.utils import try_cast_to_pandas

        return self._agg("cov", other=try_cast_to_pandas(other, squeeze=True), pairwise=pairwise, ddof=ddof, **kwargs)


for _name in _ROLLING_AGGS:
    if _name in ("corr", "cov"):
        continue

    def _make(name):
        def method(self, *args: Any, **kwargs: Any):
            return self._agg(name, *args, **kwargs)

        method.__name__ = name
        return method

    setattr(Rolling, _name, _make(_name))


@_inherit_docstrings(pandas.core.window.expanding.Expanding)
class Expanding(ClassLogger, modin_layer="PANDAS-API"):
    def __init__(self, dataframe: Any, min_periods: int = 1, method: str = "single") -> None:
        self._dataframe = dataframe
        self.expanding_args = [min_periods, method]

    @property
    def _query_compiler(self):
        return self._dataframe._query_compiler

    def _agg(self, name: str, *args: Any, **kwargs: Any):
        qc_method = getattr(self._query_compiler, f"expanding_{name}")
        new_qc = qc_method(self.expanding_args, *args, **kwargs)
        return self._wrap(new_qc)

    _wrap = Rolling._wrap

    def aggregate(self, func: Any, *args: Any, **kwargs: Any):
        return self._wrap(
            self._query_compiler.expanding_aggregate(0, self.expanding_args, func, *args, **kwargs)
        )

    agg = aggregate


for _name in [
    "count", "sum", "mean", "median", "var", "std", "min", "max", "skew",
    "kurt", "sem", "quantile", "rank", "apply", "corr", "cov",
]:

    def _make_exp(name):
        def method(self, *args: Any, **kwargs: Any):
            return self._agg(name, *args, **kwargs)

        method.__name__ = name
        return method

    setattr(Expanding, _name, _make_exp(_name))


@_inherit_docstrings(pandas.core.window.ewm.ExponentialMovingWindow)
class Ewm(ClassLogger, modin_layer="PANDAS-API"):
    """Lazy exponentially-weighted-window handle dispatching to ``ewm_*``
    query-compiler methods (reference modin/pandas/window.py
    ExponentialMovingWindow)."""

    def __init__(self, dataframe: Any, **ewm_kwargs: Any) -> None:
        self._dataframe = dataframe
        self.ewm_kwargs = ewm_kwargs

    @property
    def _query_compiler(self):
        return self._dataframe._query_compiler

    _wrap = Rolling._wrap

    def _agg(self, name: str, *args: Any, **kwargs: Any):
        qc_method = getattr(self._query_compiler, f"ewm_{name}")
        new_qc = qc_method(self.ewm_kwargs, *args, **kwargs)
        return self._wrap(new_qc)

    def mean(self, numeric_only: bool = False, engine: Any = None, engine_kwargs: Any = None):
        return self._agg("mean", numeric_only=numeric_only, engine=engine, engine_kwargs=engine_kwargs)

    def sum(self, numeric_only: bool = False, engine: Any = None, engine_kwargs: Any = None):
        return self._agg("sum", numeric_only=numeric_only, engine=engine, engine_kwargs=engine_kwargs)

    def var(self, bias: bool = False, numeric_only: bool = False):
        return self._agg("var", bias=bias, numeric_only=numeric_only)

    def std(self, bias: bool = False, numeric_only: bool = False):
        return self._agg("std", bias=bias, numeric_only=numeric_only)

    @staticmethod
    def _other_qc(other: Any) -> Any:
        # hand the raw compiler to the QC (device pair path); the pandas
        # fallback casts it (EwmDefault try_cast_to_pandas)
        from modin_tpu.pandas.base import BasePandasDataset

        return other._query_compiler if isinstance(other, BasePandasDataset) else other

    def corr(self, other: Any = None, pairwise: Any = None, numeric_only: bool = False):
        return self._agg(
            "corr", other=self._other_qc(other),
            pairwise=pairwise, numeric_only=numeric_only,
        )

    def cov(self, other: Any = None, pairwise: Any = None, bias: bool = False, numeric_only: bool = False):
        return self._agg(
            "cov", other=self._other_qc(other),
            pairwise=pairwise, bias=bias, numeric_only=numeric_only,
        )

    def aggregate(self, func: Any, *args: Any, **kwargs: Any):
        return self._agg("aggregate", func, *args, **kwargs)

    agg = aggregate

    def __getattr__(self, name: str):
        # anything beyond the implemented surface (online(), attribute
        # introspection, future pandas additions) defaults to pandas; missing
        # names raise like pandas would
        if name.startswith("_") or not hasattr(
            pandas.core.window.ewm.ExponentialMovingWindow, name
        ):
            raise AttributeError(name)
        df = self._dataframe
        ewm_kwargs = self.ewm_kwargs

        def fallback(*args: Any, **kwargs: Any):
            return df._default_to_pandas(
                lambda obj: getattr(obj.ewm(**ewm_kwargs), name)(*args, **kwargs)
            )

        fallback.__name__ = name
        return fallback


class _GroupByWindow(ClassLogger, modin_layer="PANDAS-API"):
    """Windowed aggregation over groupby groups: the lazy handle for
    ``df.groupby(...).{rolling,expanding,ewm}(...)`` (reference
    modin/pandas/window.py RollingGroupby).  The full pandas surface
    resolves through ``__getattr__`` against the matching pandas groupby
    window class, so every aggregation it supports dispatches (and missing
    names raise like pandas)."""

    _kind: str = ""
    _pandas_cls: Any = None

    def __init__(self, groupby: Any, **window_kwargs: Any) -> None:
        self._groupby = groupby
        self._window_kwargs = window_kwargs

    def _agg(self, name: str, *args: Any, **kwargs: Any):
        import pandas.core.groupby as pg

        gb = self._groupby
        by, drop = gb._resolve_by()
        qc = gb._query_compiler.groupby_window(
            by=by,
            kind=self._kind,
            window_kwargs=self._window_kwargs,
            agg_func=name,
            groupby_kwargs=gb._kwargs,
            agg_args=args,
            agg_kwargs=kwargs,
            drop=drop,
            selection=gb._selection,
            series_groupby=gb._pandas_class is pg.SeriesGroupBy,
        )
        from modin_tpu.pandas.dataframe import DataFrame
        from modin_tpu.pandas.series import Series

        if getattr(qc, "_shape_hint", None) == "column":
            return Series(query_compiler=qc)
        return DataFrame(query_compiler=qc)

    def __getattr__(self, name: str):
        if name.startswith("_") or not callable(
            getattr(self._pandas_cls, name, None)
        ):
            raise AttributeError(name)

        def method(*args: Any, **kwargs: Any):
            return self._agg(name, *args, **kwargs)

        method.__name__ = name
        return method


class GroupByRolling(_GroupByWindow):
    _kind = "rolling"
    _pandas_cls = pandas.core.window.rolling.RollingGroupby

    def __init__(
        self,
        groupby: Any,
        window: Any = None,
        min_periods: Any = None,
        center: bool = False,
        win_type: Any = None,
        on: Any = None,
        closed: Any = None,
        method: str = "single",
    ) -> None:
        super().__init__(
            groupby, window=window, min_periods=min_periods, center=center,
            win_type=win_type, on=on, closed=closed, method=method,
        )


class GroupByExpanding(_GroupByWindow):
    _kind = "expanding"
    _pandas_cls = pandas.core.window.expanding.ExpandingGroupby


class GroupByEwm(_GroupByWindow):
    _kind = "ewm"
    _pandas_cls = pandas.core.window.ewm.ExponentialMovingWindowGroupby
