"""Range-partitioning shuffle over ICI collectives.

TPU-native re-design of the reference's shuffle machinery
(modin/core/dataframe/pandas/partitioning/partition_manager.py:1937
``shuffle_partitions`` + modin/core/dataframe/pandas/dataframe/utils.py:111
``ShuffleSortFunctions``): the same sample -> quantile-pivots -> split ->
recombine algorithm, but the "split every partition into bins + re-concat"
step is a single ``lax.all_to_all`` over the mesh rows axis inside
``shard_map`` instead of a task fan-out through an object store.

Steps (for ``sort_by``-style redistribution of rows by a key):
1. sample the key column on device, compute S-1 quantile pivots on host;
2. inside shard_map: bucketize each local row (searchsorted on pivots),
   scatter rows into a [S, C] send buffer (C = per-destination capacity with
   slack), ``all_to_all`` so shard s receives every sender's bucket-s rows,
   then locally move valid rows to a prefix;
3. rebuild the framework's padded column layout with a device gather driven
   only by the S per-shard counts (no full-mask host transfer); overflow of
   any destination capacity is detected on host and retried with more slack.

The result is *range-partitioned*: shard s holds keys within
(pivot[s-1], pivot[s]]; a local per-shard sort then yields a globally sorted
frame — exactly the reference's recipe, compiled onto the interconnect.
"""

from __future__ import annotations

import functools
from typing import Any, List, Tuple

import numpy as np


from modin_tpu.observability import spans as graftscope
from modin_tpu.parallel.engine import materialize as _engine_materialize


class ShuffleSkewError(RuntimeError):
    """Capacity-slack retries exhausted by pathologically skewed keys.

    Callers catch this specifically (not bare RuntimeError, which would also
    swallow jax XlaRuntimeError device failures) and fall back to a
    non-shuffle path.
    """


@functools.lru_cache(maxsize=None)
def _jit_sample(step: int):
    import jax

    def fn(key):
        return key[::step]

    return jax.jit(fn)


def sample_pivots(key: Any, n: int, num_partitions: int, num_samples: int = 4096) -> np.ndarray:
    """Quantile pivots from a strided device sample (one small fetch)."""
    with graftscope.span(
        "shuffle.sample_pivots", layer="SHUFFLE", rows=int(n), shards=num_partitions
    ):
        step = max(1, key.shape[0] // num_samples)
        sample = np.asarray(_engine_materialize(_jit_sample(step)(key)))
        positions = np.arange(0, key.shape[0], step)
        sample = sample[positions[: len(sample)] < n]
        if sample.dtype.kind == "f":
            sample = sample[~np.isnan(sample)]
        if len(sample) == 0:
            return np.zeros(max(num_partitions - 1, 1), dtype=sample.dtype)
        qs = np.linspace(0, 1, num_partitions + 1)[1:-1]
        pivots = np.quantile(sample, qs, method="inverted_cdf")
        return np.asarray(pivots, dtype=sample.dtype)


@functools.lru_cache(maxsize=None)
def _jit_shuffle(
    n_cols: int,
    capacity: int,
    n: int,
    descending: bool,
    local_sort: bool = False,
    mesh_key: str = "",
):
    """shard_map kernel: local bucketize+pack, all_to_all, local compaction.

    ``mesh_key`` participates in the cache key only: the compiled program
    closes over the mesh captured at trace time, so a mesh reshape (the
    parity grid reconfigures MeshShape in-process) must never reuse a
    program traced for a different topology.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from modin_tpu.parallel.jax_compat import shard_map

    from modin_tpu.parallel.mesh import get_mesh

    mesh = get_mesh()
    S = mesh.shape["rows"]

    def local_fn(pivots, key_shard, row_valid, *col_shards):
        L = key_shard.shape[0]
        if jnp.issubdtype(key_shard.dtype, jnp.floating):
            k = jnp.where(jnp.isnan(key_shard), jnp.inf, key_shard)
        else:
            k = key_shard
        side = "left" if descending else "right"
        bucket = jnp.searchsorted(pivots, k, side=side)
        if descending:
            bucket = (S - 1) - bucket
            if jnp.issubdtype(key_shard.dtype, jnp.floating):
                # NaN stays last globally (na_position='last') even though
                # the value order is reversed
                bucket = jnp.where(jnp.isnan(key_shard), S - 1, bucket)
        bucket = jnp.where(row_valid[:, 0], bucket, S)  # pads route nowhere
        # stable grouping of local rows by destination
        order = jnp.argsort(bucket, stable=True)
        sorted_bucket = jnp.take(bucket, order)
        ranks = jnp.arange(L) - jnp.searchsorted(
            sorted_bucket, sorted_bucket, side="left"
        )
        ok = (sorted_bucket < S) & (ranks < capacity)
        slot = sorted_bucket * capacity + jnp.minimum(ranks, capacity - 1)
        send_idx = jnp.full((S * capacity,), -1, jnp.int64)
        send_idx = send_idx.at[jnp.where(ok, slot, S * capacity)].set(
            jnp.where(ok, order, -1), mode="drop"
        )
        send_idx = send_idx.reshape(S, capacity)
        overflow = jnp.sum(jnp.where((sorted_bucket < S) & ~ok, 1, 0))

        def route(col):
            safe = jnp.where(send_idx >= 0, send_idx, 0)
            vals = jnp.take(col, safe.reshape(-1), axis=0).reshape(S, capacity)
            recv = jax.lax.all_to_all(
                vals, "rows", split_axis=0, concat_axis=0, tiled=True
            )
            return recv.reshape(-1)  # [S*capacity] rows destined here

        valid_recv = jax.lax.all_to_all(
            send_idx >= 0, "rows", split_axis=0, concat_axis=0, tiled=True
        ).reshape(-1)
        # compact valid rows to a local prefix (stable keeps arrival order)
        payload = [route(key_shard)] + [route(c) for c in col_shards]
        if local_sort:
            # composed stable argsorts: value, then NaN-last, then valid-first.
            # No value sentinels — real +/-inf and NaN keys order exactly like
            # pandas (na_position='last'), and invalid slack slots sort after
            # every valid row regardless of their garbage payload.
            kk = payload[0]
            if jnp.issubdtype(kk.dtype, jnp.floating):
                value_key = jnp.where(jnp.isnan(kk), 0, kk)
                nan_flag = jnp.isnan(kk)
            else:
                value_key = kk
                nan_flag = None
            order = jnp.argsort(value_key, stable=True, descending=descending)
            if nan_flag is not None:
                order = jnp.take(order, jnp.argsort(jnp.take(nan_flag, order), stable=True))
            invalid_sorted = jnp.take(~valid_recv, order)
            local_order = jnp.take(order, jnp.argsort(invalid_sorted, stable=True))
        else:
            local_order = jnp.argsort(~valid_recv, stable=True)
        payload = [jnp.take(p, local_order, axis=0) for p in payload]
        count = jnp.sum(valid_recv).astype(jnp.int64)
        return (
            count[None],
            overflow[None].astype(jnp.int64),
            *payload,
        )

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(), P("rows"), P("rows", None))
            + tuple(P("rows") for _ in range(n_cols)),
            out_specs=(P("rows"), P("rows"))
            + tuple(P("rows") for _ in range(n_cols + 1)),
            check_vma=False,
        )
    )


def range_shuffle(
    key: Any,
    cols: List[Any],
    n: int,
    descending: bool = False,
    slack: float = 1.6,
    local_sort: bool = False,
    max_slack: float = 64.0,
) -> Tuple[Any, List[Any], np.ndarray, np.ndarray]:
    """Redistribute rows so shard s holds the s-th key range.

    Returns (key_out, cols_out, shard_counts, pivots): padded device columns
    in the framework layout (logical length n), range-partitioned over the
    mesh; rows within a shard keep arrival order (callers sort locally).

    Capacity slack doubles on overflow up to ``max_slack``; past that the
    keys are pathologically skewed and ShuffleSkewError tells the caller to
    take its non-shuffle path (a semantic fallback signal, NOT a device
    failure — see modin_tpu/core/execution/resilience.py's taxonomy).
    """
    import jax.numpy as jnp

    from modin_tpu.logging.metrics import emit_metric
    from modin_tpu.observability import costs as _costs
    from modin_tpu.ops.structural import gather_columns
    from modin_tpu.parallel.mesh import mesh_shape_key, num_row_shards

    with graftscope.span(
        "shuffle.range_shuffle",
        layer="SHUFFLE",
        rows=int(n),
        n_cols=len(cols),
        local_sort=bool(local_sort),
    ) as _sp:
        S = num_row_shards()
        mesh_key = mesh_shape_key()
        P_len = key.shape[0]
        L = P_len // S
        pivots = sample_pivots(key, n, S)
        pivots_dev = jnp.asarray(pivots)
        row_valid = (jnp.arange(P_len) < n)[:, None]

        slack_retries = 0
        while True:
            capacity = int(max(8, int(L / max(S, 1) * slack)))
            fn = _jit_shuffle(
                len(cols), capacity, n, bool(descending), bool(local_sort),
                mesh_key,
            )
            out = fn(pivots_dev, key, row_valid, *cols)
            counts_r, overflow_r = out[0], out[1]
            payload = list(out[2:])
            overflow = int(np.sum(np.asarray(_engine_materialize(overflow_r))))
            if overflow == 0:
                counts = np.asarray(_engine_materialize(counts_r))
                break
            slack *= 2.0
            slack_retries += 1
            emit_metric("resilience.shuffle.slack_retry", 1)
            if slack > max_slack:
                emit_metric("resilience.shuffle.skew_fallback", 1)
                raise ShuffleSkewError("range_shuffle: pathological key skew")

        if _costs.COST_ON:
            # graftcost collective accounting: every routed column moves a
            # [S, capacity] block per shard through the all_to_all (S*S*cap
            # rows total), plus the validity mask (1 byte/slot).  This is
            # the ``engine.cost.collective_bytes`` term the router's
            # sharded-vs-local crossover model is calibrated against.
            slots = S * S * capacity
            payload_bytes = sum(
                slots * c.dtype.itemsize for c in (key, *cols)
            ) + slots
            _costs.note_collective("shuffle.all_to_all", payload_bytes)
        if _sp is not None:
            _sp.attrs["shards"] = S
            _sp.attrs["capacity"] = capacity
            _sp.attrs["slack_retries"] = slack_retries

        assert int(counts.sum()) == n, (counts, n)
        # positions of each shard's valid prefix within the [S * S*capacity] layout
        block = S * capacity
        positions = np.concatenate(
            [s * block + np.arange(c, dtype=np.int64) for s, c in enumerate(counts)]
        ) if len(counts) else np.zeros(0, np.int64)
        compacted, _ = gather_columns(payload, positions)
        return compacted[0], compacted[1:], counts, pivots
