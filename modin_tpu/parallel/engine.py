"""The JAX engine wrapper — the four-function engine contract.

Reference design: the reference's entire engine abstraction is
``XYWrapper.{deploy,put,materialize,wait}`` (SURVEY.md §2.3; e.g. RayWrapper at
modin/core/execution/ray/common/engine_wrapper.py:59).  The TPU-native
equivalents (SURVEY.md §5 "Distributed communication backend"):

- ``deploy``      -> dispatch a jit-compiled computation (async by default;
                     XLA queues the work on the device stream)
- ``put``         -> ``jax.device_put`` with a target sharding
- ``materialize`` -> ``jax.device_get`` (device -> host numpy)
- ``wait``        -> ``block_until_ready``

Collectives (psum/all_gather/ppermute/all_to_all over ICI) are emitted by XLA
from sharded jnp programs; the shuffle subsystem uses them explicitly via
shard_map (modin_tpu/parallel/shuffle.py).

Every method runs under the resilience policy
(modin_tpu/core/execution/resilience.py): raw runtime errors are classified
into the DeviceOOM / DeviceLost / TransientDeviceError taxonomy, transient
ones retry with exponential backoff, and the blocking fetches
(materialize/wait) are bounded by the configurable wall-clock watchdog.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Iterable, Optional

from modin_tpu.config import BenchmarkMode, DeviceCount
from modin_tpu.core import memory as _memory
from modin_tpu.core.execution import recovery as _recovery
from modin_tpu.core.execution.resilience import engine_call
from modin_tpu.logging import ClassLogger
from modin_tpu.observability import costs as _costs


def _estimate_deploy_bytes(f_args: tuple) -> tuple:
    """(projected output bytes, {id(buffer)} of the op's own inputs).

    The admission controller needs a pre-dispatch size estimate; without
    tracing the program we take the conservative elementwise bound — the
    output is at most the size of the device inputs combined (reductions
    come in far under it, which only makes admission spill early, never
    late).  The input ids let the spill pass skip buffers the dispatch
    closure pins anyway.
    """
    import jax

    total = 0
    ids = set()
    stack = list(f_args)
    while stack:
        item = stack.pop()
        if isinstance(item, (tuple, list)):
            stack.extend(item)
        elif isinstance(item, jax.Array):
            total += int(item.nbytes)
            ids.add(id(item))
    return total, ids


def initialize_jax() -> None:
    """One-time engine startup: enable x64, warm the backend, build the mesh."""
    import jax

    # pandas semantics are 64-bit; TPUs prefer 32-bit.  We enable x64 so
    # int64/float64 frames round-trip exactly; hot kernels can downcast
    # explicitly where the Float64Policy config allows it.
    jax.config.update("jax_enable_x64", True)

    from modin_tpu.parallel.mesh import get_mesh

    get_mesh()

    # compile observability: count every backend compile from process start
    # (the listener is idle-free; recompile storms are invisible otherwise)
    from modin_tpu.observability.compile_ledger import ensure_listener

    ensure_listener()

    from modin_tpu.config import CompilationCacheDir

    cache_dir = CompilationCacheDir.get()
    # TPU/accelerator only: every fresh compile over the tunnel is a 20-40s
    # remote round-trip, so persist all of them.  XLA:CPU AOT artifacts are
    # not portable across host feature detection (SIGILL warnings), and CPU
    # compiles are fast — skip the cache there.
    if cache_dir and jax.default_backend() != "cpu":
        try:
            import os

            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:  # pragma: no cover - cache is best-effort  # graftlint: disable=EXC-HYGIENE -- persistent-compile-cache setup is best-effort; failure = no cache
            pass


class JaxWrapper(ClassLogger, modin_layer="JAX-ENGINE"):
    """Uniform engine API over jax dispatch and device buffers."""

    @classmethod
    def deploy(cls, func: Callable, f_args: tuple = (), f_kwargs: Optional[dict] = None, num_returns: int = 1, donated: bool = False) -> Any:
        """Run ``func`` (usually jit-compiled); returns device buffers (futures:
        jax arrays are async until materialized).

        graftguard wraps the dispatch three ways: pre-flight **admission**
        (when ``MODIN_TPU_DEVICE_MEMORY_BUDGET`` is set, cold columns are
        spilled to host *before* a dispatch projected to overflow the
        budget), post-hoc **provenance** (the (func, args) of every
        successful deploy is recorded weakly so op-replay lineage can
        rebuild the outputs after a device loss), and a **rebind retry**:
        when the seam's own post-re-seat retry still fails with DeviceLost
        — on real hardware the retried thunk closes over the dead input
        buffers — the argument tree is rebuilt against the re-seated
        columns and dispatched once more over live buffers.
        """
        from modin_tpu.core.execution.resilience import DeviceLost
        from modin_tpu.logging.metrics import emit_metric

        input_ids = None
        if _memory._DEVICE_BUDGET is not None or _recovery.RECOVERY_ON:
            estimate, input_ids = _estimate_deploy_bytes(f_args)
            if _memory._DEVICE_BUDGET is not None:
                _memory.device_ledger.admit(estimate, exclude_ids=input_ids)
        # graftcost: one attribute check when off; while on, the recorder
        # captures static flops/bytes on a billed compile (re-billing the
        # memoized costs on cache hits) and joins the attempt wall
        cost_cb = (
            _costs.dispatch_recorder(func, f_args, f_kwargs)
            if _costs.COST_ON
            else None
        )
        try:
            result = engine_call(
                "deploy",
                lambda: func(*f_args, **(f_kwargs or {})),
                protect_ids=input_ids,
                cost_cb=cost_cb,
            )
        except DeviceLost:
            fresh_args = _recovery.recover_args(f_args)
            if fresh_args is None:
                raise
            emit_metric("recovery.retry.rebind", 1)
            # a fresh recorder over the REBOUND args: the original closure
            # would fingerprint (and AOT-lower over) the dead buffers
            rebind_cb = (
                _costs.dispatch_recorder(func, fresh_args, f_kwargs)
                if _costs.COST_ON
                else None
            )
            result = engine_call(
                "deploy",
                lambda: func(*fresh_args, **(f_kwargs or {})),
                cost_cb=rebind_cb,
            )
            f_args = fresh_args  # provenance must describe the live inputs
        if _recovery.RECOVERY_ON and not donated:
            # a donated dispatch consumes its input buffers: replaying it
            # from op-replay provenance would re-donate the restored
            # incarnations under their columns (use-after-donate).  The
            # fused caller materializes the outputs to host immediately,
            # so they recover via host lineage, never via replay.
            _recovery.record_deploy(func, f_args, f_kwargs, result)
        if BenchmarkMode.get():
            cls.wait(result)
        return result

    @classmethod
    def put(cls, data: Any, sharding: Any = None) -> Any:
        """Host -> device transfer with an optional target sharding."""
        import jax

        if sharding is None:
            from modin_tpu.parallel.mesh import row_sharding

            sharding = row_sharding()
        result = engine_call("put", lambda: jax.device_put(data, sharding))
        if _recovery.RECOVERY_ON:
            _recovery.record_put(data, result)
        return result

    @classmethod
    def materialize(cls, obj_refs: Any) -> Any:
        """Device -> host (blocks until the value is computed and fetched)."""
        import jax

        return engine_call(
            "materialize", lambda: jax.device_get(obj_refs), watchdog=True
        )

    @classmethod
    def wait(cls, obj_refs: Any) -> None:
        """Block until all given device computations complete.

        One ``jax.block_until_ready`` over the whole tree: per-leaf loops cost
        one tunnel round-trip each on remote devices (measured 6x68ms vs 68ms).
        """
        import jax

        engine_call("wait", lambda: jax.block_until_ready(obj_refs), watchdog=True)

    @classmethod
    def is_future(cls, item: Any) -> bool:
        import jax

        return isinstance(item, jax.Array)


def materialize(obj_refs: Any) -> Any:
    """Engine-seam host fetch as a free function.

    Kernel modules fetch scalars/counts through this instead of raw
    ``jax.device_get`` so every host sync traverses the resilience policy
    (classification, retry, watchdog) exactly once, defined in one place.
    """
    return JaxWrapper.materialize(obj_refs)
