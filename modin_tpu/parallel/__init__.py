"""modin_tpu subpackage."""
