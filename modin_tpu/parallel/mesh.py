"""Global device-mesh management.

The TPU-native analogue of the reference's partition grid sizing
(NPartitions/CpuCount, modin/config/envvars.py:767-884): instead of a 2-D grid
of pandas-block partitions on worker processes, data lives in jax.Arrays
sharded over a ``jax.sharding.Mesh`` whose "rows" axis spans devices connected
by ICI.  Row-partitioning is a sharding spec, not a Python object
(SURVEY.md §7 design translation).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from modin_tpu.concurrency import named_lock
from modin_tpu.config import MeshShape

_MESH_AXES = ("rows", "cols")
_lock = named_lock("parallel.mesh")
_mesh = None
_mesh_shape: Optional[tuple] = None

# hot-path caches: num_row_shards / mesh_shape_key run per sorted-rep
# lookup and per buffer registration, where get_mesh()'s config update +
# lock would serialize.  Filled whenever the mesh is (re)built; cleared by
# reset_mesh and the MeshShape subscription (every mutation point).
_cached_row_shards: Optional[int] = None
_cached_shape_key: Optional[str] = None


def _fill_cache(mesh) -> None:
    global _cached_row_shards, _cached_shape_key
    _cached_row_shards = int(mesh.shape["rows"])
    _cached_shape_key = "x".join(str(int(s)) for s in mesh.devices.shape)


def get_mesh():
    """Get (building on first use) the global device mesh."""
    global _mesh, _mesh_shape
    import jax

    # pandas semantics are 64-bit; ensure x64 regardless of which layer
    # touched jax first (idempotent)
    jax.config.update("jax_enable_x64", True)
    from jax.sharding import Mesh

    shape = tuple(MeshShape.get())
    with _lock:
        if _mesh is None or _mesh_shape != shape:
            devices = jax.devices()
            n = int(np.prod(shape))
            if n > len(devices):
                # fall back to all available devices on the row axis
                shape = (len(devices), 1)
            mesh_devices = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
            _mesh = Mesh(mesh_devices, _MESH_AXES)
            _mesh_shape = shape
        _fill_cache(_mesh)
    return _mesh


def set_mesh(mesh) -> None:
    """Install an externally-constructed mesh (used by multi-chip dry runs)."""
    global _mesh, _mesh_shape
    with _lock:
        _mesh = mesh
        _mesh_shape = tuple(mesh.devices.shape)
        _fill_cache(mesh)


def reset_mesh() -> None:
    global _mesh, _mesh_shape, _cached_row_shards, _cached_shape_key
    with _lock:
        _mesh = None
        _mesh_shape = None
        _cached_row_shards = None
        _cached_shape_key = None


def row_sharding():
    """NamedSharding partitioning axis 0 over the mesh's "rows" axis."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(get_mesh(), PartitionSpec("rows"))


def replicated_sharding():
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(get_mesh(), PartitionSpec())


def num_row_shards() -> int:
    cached = _cached_row_shards
    if cached is not None:
        return cached
    return int(get_mesh().shape["rows"])


def mesh_shape_key() -> str:
    """Stable string identity of the live mesh shape, e.g. ``"8x1"``.

    Keys everything whose validity is tied to the mesh topology: the
    kernel-router calibration cache, sorted-representation reps (a rep
    built under one shard count has a different padded layout than the
    next), and the SPMD perf-history scale keys (1-dev and 8-dev walls
    must never gate against each other).
    """
    cached = _cached_shape_key
    if cached is not None:
        return cached
    get_mesh()  # fills the cache under the lock
    return _cached_shape_key


def _on_mesh_shape(_param) -> None:
    """MeshShape changed (put / context): drop the hot-path caches so the
    next consumer rebuilds the mesh at the new shape — exactly the rebuild
    get_mesh() itself performs on a shape change."""
    global _cached_row_shards, _cached_shape_key
    _cached_row_shards = None
    _cached_shape_key = None


MeshShape.subscribe(_on_mesh_shape)
