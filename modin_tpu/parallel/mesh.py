"""Global device-mesh management.

The TPU-native analogue of the reference's partition grid sizing
(NPartitions/CpuCount, modin/config/envvars.py:767-884): instead of a 2-D grid
of pandas-block partitions on worker processes, data lives in jax.Arrays
sharded over a ``jax.sharding.Mesh`` whose "rows" axis spans devices connected
by ICI.  Row-partitioning is a sharding spec, not a Python object
(SURVEY.md §7 design translation).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from modin_tpu.config import MeshShape

_MESH_AXES = ("rows", "cols")
_lock = threading.Lock()
_mesh = None
_mesh_shape: Optional[tuple] = None


def get_mesh():
    """Get (building on first use) the global device mesh."""
    global _mesh, _mesh_shape
    import jax

    # pandas semantics are 64-bit; ensure x64 regardless of which layer
    # touched jax first (idempotent)
    jax.config.update("jax_enable_x64", True)
    from jax.sharding import Mesh

    shape = tuple(MeshShape.get())
    with _lock:
        if _mesh is None or _mesh_shape != shape:
            devices = jax.devices()
            n = int(np.prod(shape))
            if n > len(devices):
                # fall back to all available devices on the row axis
                shape = (len(devices), 1)
            mesh_devices = np.asarray(devices[: int(np.prod(shape))]).reshape(shape)
            _mesh = Mesh(mesh_devices, _MESH_AXES)
            _mesh_shape = shape
    return _mesh


def set_mesh(mesh) -> None:
    """Install an externally-constructed mesh (used by multi-chip dry runs)."""
    global _mesh, _mesh_shape
    with _lock:
        _mesh = mesh
        _mesh_shape = tuple(mesh.devices.shape)


def reset_mesh() -> None:
    global _mesh, _mesh_shape
    with _lock:
        _mesh = None
        _mesh_shape = None


def row_sharding():
    """NamedSharding partitioning axis 0 over the mesh's "rows" axis."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(get_mesh(), PartitionSpec("rows"))


def replicated_sharding():
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(get_mesh(), PartitionSpec())


def num_row_shards() -> int:
    return get_mesh().shape["rows"]
