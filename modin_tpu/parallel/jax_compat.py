"""Version-portable imports for jax APIs that moved between releases.

The framework is written against the current jax surface; hosts pinned to an
older jaxlib still carry the same functionality under earlier names.  Keep
every such rename in this one module so kernel code imports a stable name.
"""

from __future__ import annotations

from typing import Any, Callable


def shard_map(
    f: Callable,
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = True,
) -> Callable:
    """``jax.shard_map``, falling back to ``jax.experimental.shard_map``.

    The experimental form (jax < 0.5) spells the replication-check flag
    ``check_rep`` instead of ``check_vma``; semantics are the same.
    """
    try:
        from jax import shard_map as _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
        )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
    )
