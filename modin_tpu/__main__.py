"""``python -m modin_tpu`` — print versions (reference: modin/__main__.py:19)."""

import sys


def main() -> None:
    if "--versions" in sys.argv or len(sys.argv) == 1:
        from modin_tpu.utils import show_versions

        show_versions()
        return
    print("usage: python -m modin_tpu [--versions]")  # noqa: T201


if __name__ == "__main__":
    main()
