"""train_test_split for modin_tpu frames.

Reference design: modin/experimental/sklearn/model_selection/train_test_split.py:18.
The split is a device gather per side (no host materialization of the data).
"""

from __future__ import annotations

from typing import Any

import numpy as np


def train_test_split(
    df: Any,
    *others: Any,
    test_size: Any = None,
    train_size: Any = None,
    random_state: Any = None,
    shuffle: bool = True,
    **kwargs: Any,
):
    n = len(df)
    if test_size is None and train_size is None:
        test_size = 0.25
    if test_size is None:
        test_size = 1.0 - (train_size if train_size <= 1 else train_size / n)
    n_test = int(round(test_size * n)) if test_size <= 1 else int(test_size)
    rng = np.random.default_rng(random_state)
    positions = rng.permutation(n) if shuffle else np.arange(n)
    test_positions = np.sort(positions[:n_test]) if not shuffle else positions[:n_test]
    train_positions = positions[n_test:]
    results = []
    for obj in (df, *others):
        results.append(obj.take(train_positions))
        results.append(obj.take(test_positions))
    return results if len(results) > 2 else (results[0], results[1])
