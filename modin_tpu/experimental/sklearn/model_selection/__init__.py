"""Model-selection helpers."""

from modin_tpu.experimental.sklearn.model_selection.train_test_split import (  # noqa: F401
    train_test_split,
)
