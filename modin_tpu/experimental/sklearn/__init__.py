"""sklearn helpers (reference: modin/experimental/sklearn/)."""
