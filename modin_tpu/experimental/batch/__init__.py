"""Batch pipeline (reference: modin/experimental/batch/)."""

from modin_tpu.experimental.batch.pipeline import (  # noqa: F401
    PandasQuery,
    PandasQueryPipeline,
    TpuQuery,
    TpuQueryPipeline,
)
