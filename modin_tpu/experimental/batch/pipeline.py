"""Batch query pipeline — a user-composed DAG executed stage-by-stage.

Reference design: modin/experimental/batch/pipeline.py:30,88
(PandasQuery/PandasQueryPipeline): the user registers a chain of frame->frame
functions; the pipeline fuses and executes them batch-wise with optional
repartitioning and per-stage output handlers.  On the TPU backend consecutive
queries execute back-to-back on device without host round-trips (jax's async
dispatch pipelines the stages).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from modin_tpu.logging import ClassLogger


class TpuQuery:
    """One node of the pipeline: a DataFrame -> DataFrame function."""

    def __init__(
        self,
        func: Callable,
        is_output: bool = False,
        repartition_after: bool = False,
        fan_out: bool = False,
        pass_partition_id: bool = False,
        reduce_fn: Optional[Callable] = None,
        output_id: Optional[int] = None,
    ):
        self.func = func
        self.is_output = is_output
        self.repartition_after = repartition_after
        self.fan_out = fan_out
        self.pass_partition_id = pass_partition_id
        self.reduce_fn = reduce_fn
        self.output_id = output_id


class TpuQueryPipeline(ClassLogger, modin_layer="BATCH-PIPELINE"):
    """Batch pipeline over a modin_tpu DataFrame."""

    def __init__(self, df: Any, num_partitions: Optional[int] = None):
        self.df = df
        self.num_partitions = num_partitions
        self.queries: List[TpuQuery] = []
        self.outputs: List[TpuQuery] = []

    def add_query(
        self,
        func: Callable,
        is_output: bool = False,
        repartition_after: bool = False,
        fan_out: bool = False,
        pass_partition_id: bool = False,
        reduce_fn: Optional[Callable] = None,
        output_id: Optional[int] = None,
    ) -> None:
        query = TpuQuery(
            func, is_output, repartition_after, fan_out, pass_partition_id,
            reduce_fn, output_id,
        )
        self.queries.append(query)
        if is_output:
            self.outputs.append(query)

    def compute_batch(
        self,
        postprocessor: Optional[Callable] = None,
        pass_partition_id: bool = False,
        pass_output_id: bool = False,
    ) -> Any:
        """Run the pipeline; returns outputs (dict by output_id or list)."""
        current = self.df
        results: List[Any] = []
        output_ids: List[Optional[int]] = []
        for query in self.queries:
            if query.fan_out:
                partials = [
                    query.func(current, pid) if query.pass_partition_id else query.func(current)
                    for pid in range(self.num_partitions or 1)
                ]
                if query.reduce_fn is not None:
                    current = query.reduce_fn(partials)
                else:
                    current = partials[-1]
            else:
                current = query.func(current)
            if query.repartition_after and hasattr(current, "_query_compiler"):
                current = current._create_or_update_from_compiler(
                    current._query_compiler.repartition()
                )
            if query.is_output:
                out = current
                if postprocessor is not None:
                    args = []
                    if pass_output_id:
                        args.append(query.output_id)
                    out = postprocessor(out, *args)
                results.append(out)
                output_ids.append(query.output_id)
        if any(oid is not None for oid in output_ids):
            return {oid: res for oid, res in zip(output_ids, results)}
        return results


# reference-compatible aliases
PandasQuery = TpuQuery
PandasQueryPipeline = TpuQueryPipeline
