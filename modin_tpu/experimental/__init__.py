"""Experimental integrations (reference: modin/experimental/)."""
