"""SQL-on-dataframe entry point (reference: modin/experimental/sql/).

``query(sql, **frames)`` evaluates a SQL query against modin_tpu frames.
Uses duckdb when available; otherwise raises with guidance.
"""

from typing import Any


def query(sql: str, **frames: Any):
    """Run a SQL query over named modin_tpu DataFrames."""
    from modin_tpu.utils import try_cast_to_pandas

    try:
        import duckdb
    except ImportError as err:
        raise ImportError(
            "modin_tpu.experimental.sql requires 'duckdb' (not bundled in this "
            "environment)"
        ) from err
    con = duckdb.connect()
    for name, frame in frames.items():
        con.register(name, try_cast_to_pandas(frame))
    result = con.execute(sql).df()
    import modin_tpu.pandas as pd

    return pd.DataFrame(result)
