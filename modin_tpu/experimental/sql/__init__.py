"""SQL-on-dataframe entry point (reference analogue: modin/experimental/sql,
present in earlier reference releases; removed upstream but kept here as a
working surface).

``query(sql, **frames)`` evaluates a SQL query against modin_tpu frames.
Engine preference: duckdb when importable (full analytic SQL), else the
stdlib ``sqlite3`` (zero extra dependencies — pandas speaks DBAPI2 directly),
so the API works out of the box in this environment.
"""

from typing import Any


def query(sql: str, **frames: Any):
    """Run a SQL query over named modin_tpu DataFrames.

    Each keyword argument becomes a table with that name.  Returns a
    modin_tpu DataFrame.
    """
    from modin_tpu.utils import try_cast_to_pandas

    import modin_tpu.pandas as pd

    try:
        import duckdb
    except ImportError:
        duckdb = None

    if duckdb is not None:
        con = duckdb.connect()
        for name, frame in frames.items():
            con.register(name, try_cast_to_pandas(frame))
        return pd.DataFrame(con.execute(sql).df())

    import sqlite3

    import pandas

    con = sqlite3.connect(":memory:")
    try:
        for name, frame in frames.items():
            try_cast_to_pandas(frame).to_sql(name, con, index=False)
        result = pandas.read_sql_query(sql, con)
    finally:
        con.close()
    return pd.DataFrame(result)
