"""Torch integration (reference: modin/experimental/torch/)."""

from modin_tpu.experimental.torch.datasets import (  # noqa: F401
    ModinDataLoader,
    ModinTpuDataset,
    to_dataloader,
)
