"""PyTorch DataLoader over a modin_tpu frame.

Reference design: modin/experimental/torch/datasets.py:24 (ModinDataLoader).
Batches are sliced from the device-backed frame (a padded device gather per
batch) and converted to torch tensors on the host.
"""

from __future__ import annotations

from typing import Any, List, Optional


class ModinTpuDataset:
    """torch-style Dataset over a modin_tpu DataFrame."""

    def __init__(self, df: Any, features: Optional[List] = None, labels: Optional[List] = None):
        self._df = df
        self._features = list(features) if features is not None else list(df.columns)
        self._labels = list(labels) if labels is not None else []

    def __len__(self) -> int:
        return len(self._df)

    def __getitem__(self, index: int):
        import torch

        row = self._df.iloc[index]
        x = torch.tensor(
            row[self._features].to_numpy(dtype="float32")
            if hasattr(row[self._features], "to_numpy")
            else row[self._features]
        )
        if self._labels:
            y = torch.tensor(row[self._labels].to_numpy(dtype="float32"))
            return x, y
        return x


def to_dataloader(
    df: Any,
    batch_size: int = 32,
    features: Optional[List] = None,
    labels: Optional[List] = None,
    shuffle: bool = False,
    **kwargs: Any,
):
    """Build a ``torch.utils.data.DataLoader`` over a modin_tpu DataFrame."""
    from torch.utils.data import DataLoader

    return DataLoader(
        ModinTpuDataset(df, features=features, labels=labels),
        batch_size=batch_size,
        shuffle=shuffle,
        **kwargs,
    )


ModinDataLoader = to_dataloader
