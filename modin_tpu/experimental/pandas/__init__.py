"""``modin_tpu.experimental.pandas`` — pandas namespace + experimental IO.

Reference design: modin/experimental/pandas/__init__.py (re-export the whole
pandas namespace plus glob readers).
"""

from modin_tpu.pandas import *  # noqa: F401,F403
from modin_tpu.pandas import __all__ as _base_all
from modin_tpu.experimental.pandas.io import (  # noqa: F401
    read_csv_glob,
    read_custom_text,
    read_json_glob,
    read_parquet_glob,
    read_pickle_glob,
    read_sql,
    read_xml_glob,
    to_csv_glob,
    to_json_glob,
    to_parquet_glob,
    to_pickle_glob,
)

__all__ = _base_all + [
    "read_csv_glob", "read_custom_text", "read_json_glob",
    "read_parquet_glob", "read_pickle_glob", "read_sql", "read_xml_glob",
    "to_csv_glob", "to_json_glob", "to_parquet_glob", "to_pickle_glob",
]
