"""Experimental IO: glob readers/writers and custom-text ingestion.

Reference design: modin/experimental/pandas/io.py (716 LoC: read_sql at :33,
read_custom_text at :124, glob functions at :306-558) and
modin/experimental/core/io/glob/glob_dispatcher.py.  Multiple files matching
a glob parse concurrently and concatenate into one device-backed frame.
"""

from __future__ import annotations

import glob as _glob
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional

import pandas

from modin_tpu.config import CpuCount


def _expand(filepath_or_buffer: Any) -> List[str]:
    if not isinstance(filepath_or_buffer, str):
        return [filepath_or_buffer]
    matches = sorted(_glob.glob(filepath_or_buffer))
    return matches if matches else [filepath_or_buffer]


def _read_many(paths: List[str], read_one: Callable) -> Any:
    import modin_tpu.pandas as mpd

    if len(paths) == 1:
        return read_one(paths[0])
    with ThreadPoolExecutor(max_workers=min(len(paths), CpuCount.get() * 2)) as pool:
        frames = list(pool.map(read_one, paths))
    return mpd.concat(frames, ignore_index=True)


def read_csv_glob(filepath_or_buffer: Any, **kwargs: Any):
    """read_csv over a glob of files, concatenated (reference: io.py:306)."""
    import modin_tpu.pandas as mpd

    return _read_many(_expand(filepath_or_buffer), lambda p: mpd.read_csv(p, **kwargs))


def read_parquet_glob(path: Any, **kwargs: Any):
    import modin_tpu.pandas as mpd

    return _read_many(_expand(path), lambda p: mpd.read_parquet(p, **kwargs))


def read_json_glob(path_or_buf: Any, **kwargs: Any):
    import modin_tpu.pandas as mpd

    return _read_many(_expand(path_or_buf), lambda p: mpd.read_json(p, **kwargs))


def read_pickle_glob(filepath_or_buffer: Any, **kwargs: Any):
    import modin_tpu.pandas as mpd

    return _read_many(
        _expand(filepath_or_buffer), lambda p: mpd.read_pickle(p, **kwargs)
    )


def read_xml_glob(path_or_buffer: Any, **kwargs: Any):
    import modin_tpu.pandas as mpd

    return _read_many(_expand(path_or_buffer), lambda p: mpd.read_xml(p, **kwargs))


def read_custom_text(
    filepath_or_buffer: Any,
    columns: Any,
    custom_parser: Callable,
    compression: str = "infer",
    nrows: Optional[int] = None,
    is_quoting: bool = True,
):
    """Parse a text file with a user-supplied line parser (reference: io.py:124)."""
    import modin_tpu.pandas as mpd

    frames = []
    for path in _expand(filepath_or_buffer):
        with pandas.io.common.get_handle(
            path, "r", compression=compression
        ) as handles:
            parsed = custom_parser(handles.handle)
            frame = pandas.DataFrame(parsed)
            if columns is not None:
                frame.columns = columns
            frames.append(mpd.DataFrame(frame))
    result = mpd.concat(frames, ignore_index=True) if len(frames) > 1 else frames[0]
    if nrows is not None:
        result = result.head(nrows)
    return result


def read_sql(sql: Any, con: Any, partition_column: Optional[str] = None, lower_bound: Optional[int] = None, upper_bound: Optional[int] = None, max_sessions: Optional[int] = None, **kwargs: Any):
    """Distributed-partitioned read_sql (reference: experimental io.py:33).

    With ``partition_column``+bounds and a ``ModinDatabaseConnection``, the
    query splits into per-range WHERE clauses fetched concurrently.
    """
    import modin_tpu.pandas as mpd
    from modin_tpu.db_conn import ModinDatabaseConnection

    if (
        partition_column is None
        or lower_bound is None
        or upper_bound is None
        or not isinstance(con, ModinDatabaseConnection)
    ):
        if partition_column is not None:
            import warnings

            warnings.warn(
                "read_sql partition bounds need a ModinDatabaseConnection and "
                "both lower_bound/upper_bound; reading unpartitioned"
            )
        return mpd.read_sql(sql, con, **kwargs)

    query = sql if isinstance(sql, str) else str(sql)
    if not query.lstrip().lower().startswith("select"):
        query = f"SELECT * FROM {query}"
    n_parts = max_sessions or max(CpuCount.get(), 2)
    span = upper_bound - lower_bound
    chunk = -(-span // n_parts) if span > 0 else 1

    def fetch(lo: int):
        hi = min(lo + chunk, upper_bound)
        # reference semantics are INCLUSIVE bounds (sql/utils.py:255) — the
        # final range keeps rows equal to upper_bound
        op = "<=" if hi == upper_bound else "<"
        bounded = (
            f"SELECT * FROM ({query}) AS _MODIN_RANGE_QUERY WHERE "
            f"{partition_column} >= {lo} AND {partition_column} {op} {hi}"
        )
        conn = con.get_connection()
        try:
            return pandas.read_sql(bounded, conn, **kwargs)
        finally:
            try:
                conn.close()
            except Exception:
                pass

    lows = list(range(lower_bound, upper_bound, chunk))
    with ThreadPoolExecutor(max_workers=min(len(lows), CpuCount.get() * 2)) as pool:
        frames = list(pool.map(fetch, lows))
    return mpd.DataFrame(pandas.concat(frames, ignore_index=True))


def _glob_writer(method: str):
    def writer(obj: Any, path: str, **kwargs: Any) -> None:
        """Partitioned writer: '*' in the path becomes the shard id."""
        if "*" not in path:
            getattr(obj, method)(path, **kwargs)
            return
        n_parts = max(CpuCount.get(), 2)
        n = len(obj)
        chunk = -(-n // n_parts) if n else 1
        for i, start in enumerate(range(0, max(n, 1), chunk)):
            piece = obj.iloc[start : start + chunk]
            # zero-padded ids keep the lexicographic glob order == write order
            getattr(piece, method)(path.replace("*", f"{i:05d}"), **kwargs)

    writer.__name__ = f"{method}_glob"
    return writer


to_pickle_glob = _glob_writer("to_pickle")
to_csv_glob = _glob_writer("to_csv")
to_json_glob = _glob_writer("to_json")
to_parquet_glob = _glob_writer("to_parquet")
