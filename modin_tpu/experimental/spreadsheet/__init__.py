"""Spreadsheet bridge (reference: modin/experimental/spreadsheet/).

modin_spreadsheet is not available in this environment; provided for API
parity, raising a clear error on use.
"""

from typing import Any


def from_dataframe(dataframe: Any, **kwargs: Any):
    try:
        import modin_spreadsheet  # noqa: F401
    except ImportError as err:
        raise ImportError(
            "modin_tpu.experimental.spreadsheet requires 'modin_spreadsheet'"
        ) from err
    return modin_spreadsheet.show_grid(dataframe._to_pandas(), **kwargs)


def to_dataframe(grid: Any):
    import modin_tpu.pandas as pd

    return pd.DataFrame(grid.get_changed_df())
