"""TPU-native histogram gradient-boosted trees.

Reference component: modin/experimental/xgboost/xgboost_ray.py:43 (1,219 LoC)
— the reference distributes xgboost's C++ training over Ray actors and merges
gradients with rabit allreduce.  The TPU redesign keeps the same role
(boosted trees over a distributed frame) but implements the trainer itself as
jit-compiled XLA programs over the frame's device columns:

- features are quantile-binned once (uint8 codes, ``max_bin`` buckets);
- each boosting round grows one level-wise tree of depth ``max_depth``:
  per-level (node, feature, bin) gradient/hessian histograms are ONE
  ``segment_sum`` — over row-sharded columns XLA lowers this to per-shard
  partial histograms + a psum over the mesh, exactly the role rabit's
  allreduce plays in the reference;
- split gains, leaf weights, and predictions are dense jnp programs (no
  Python per-node loops at runtime — one jit per tree level).

Supported params (xgboost names): objective ("reg:squarederror",
"binary:logistic"), max_depth, eta/learning_rate, lambda/reg_lambda, gamma,
min_child_weight, base_score, max_bin.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


_DEFAULTS = {
    "objective": "reg:squarederror",
    "max_depth": 6,
    "eta": 0.3,
    "lambda": 1.0,
    "gamma": 0.0,
    "min_child_weight": 1.0,
    "base_score": 0.5,
    "max_bin": 64,
}


def _resolve_params(params: Optional[dict]) -> dict:
    p = dict(_DEFAULTS)
    for key, value in (params or {}).items():
        if key == "learning_rate":
            key = "eta"
        elif key == "reg_lambda":
            key = "lambda"
        p[key] = value
    if p["objective"] not in ("reg:squarederror", "binary:logistic"):
        raise ValueError(
            f"unsupported objective {p['objective']!r}; use reg:squarederror "
            "or binary:logistic"
        )
    return p


def _quantile_edges(column: np.ndarray, max_bin: int) -> np.ndarray:
    """Interior bin edges (len <= max_bin - 1), deduplicated."""
    qs = np.linspace(0.0, 1.0, max_bin + 1)[1:-1]
    finite = column[np.isfinite(column)]
    if finite.size == 0:
        return np.zeros(0, dtype=np.float64)
    return np.unique(np.quantile(finite, qs))


@functools.lru_cache(maxsize=None)
def _jit_level_step(
    n_features: int, max_bin: int, level_nodes: int, lam: float, gamma: float,
    min_child_weight: float,
):
    """One tree level: histograms -> best split per node -> new assignments.

    Inputs: bins [n, F] int32, node [n] int32 (position within the level,
    ``level_nodes`` = 2**depth slots; dead rows carry ``level_nodes``),
    g/h [n] f32.  Returns (best_feature, best_bin, gain, GL, HL, G, H) per
    node plus the updated within-next-level node ids.
    """
    import jax
    import jax.numpy as jnp

    F, B, N = n_features, max_bin, level_nodes

    def step(bins, node, g, h):
        # (node, feature, bin) histogram in ONE scatter: key layout n*F*B
        feat_ids = jnp.arange(F, dtype=jnp.int32)
        keys = (
            node[:, None] * (F * B) + feat_ids[None, :] * B + bins
        )  # [n, F]
        dead = node >= N
        keys = jnp.where(dead[:, None], N * F * B, keys)
        flat_keys = keys.reshape(-1)
        seg = N * F * B + 1
        hist_g = jax.ops.segment_sum(
            jnp.broadcast_to(g[:, None], keys.shape).reshape(-1),
            flat_keys, num_segments=seg,
        )[:-1].reshape(N, F, B)
        hist_h = jax.ops.segment_sum(
            jnp.broadcast_to(h[:, None], keys.shape).reshape(-1),
            flat_keys, num_segments=seg,
        )[:-1].reshape(N, F, B)

        # candidate split after bin b: left = bins <= b
        GL = jnp.cumsum(hist_g, axis=2)
        HL = jnp.cumsum(hist_h, axis=2)
        G = GL[:, 0, -1]  # totals are feature-independent
        H = HL[:, 0, -1]
        GR = G[:, None, None] - GL
        HR = H[:, None, None] - HL

        def score(gg, hh):
            return (gg * gg) / (hh + lam)

        gain = 0.5 * (
            score(GL, HL) + score(GR, HR) - score(G, H)[:, None, None]
        ) - gamma
        valid = (HL >= min_child_weight) & (HR >= min_child_weight)
        # the last bin of each feature is "no split" (empty right side)
        valid = valid & (jnp.arange(B)[None, None, :] < B - 1)
        gain = jnp.where(valid, gain, -jnp.inf)

        flat_gain = gain.reshape(N, F * B)
        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
        best_feature = (best // B).astype(jnp.int32)
        best_bin = (best % B).astype(jnp.int32)
        do_split = best_gain > 0.0

        idx = jnp.arange(N)
        GLb = GL[idx, best_feature, best_bin]
        HLb = HL[idx, best_feature, best_bin]

        # route rows: within-next-level id = 2*node + (right ? 1 : 0)
        row_feature = best_feature[jnp.clip(node, 0, N - 1)]
        row_bin = best_bin[jnp.clip(node, 0, N - 1)]
        row_split = do_split[jnp.clip(node, 0, N - 1)]
        goes_right = (
            jnp.take_along_axis(bins, row_feature[:, None], axis=1)[:, 0]
            > row_bin
        )
        new_node = jnp.where(
            dead | ~row_split, 2 * N, 2 * node + goes_right.astype(jnp.int32)
        ).astype(jnp.int32)
        return best_feature, best_bin, do_split, best_gain, GLb, HLb, G, H, new_node

    return jax.jit(step)


@functools.lru_cache(maxsize=None)
def _jit_predict_tree(max_depth: int):
    """Walk one complete binary tree for every row (no data-dependent flow)."""
    import jax
    import jax.numpy as jnp

    def predict(bins, feature, threshold, is_split, leaf_value, base):
        n = bins.shape[0]
        # heap addressing: node 0 is the root, children 2i+1 / 2i+2
        pos = jnp.zeros(n, dtype=jnp.int32)
        for _ in range(max_depth):
            f = feature[pos]
            t = threshold[pos]
            split = is_split[pos]
            go_right = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0] > t
            child = 2 * pos + 1 + go_right.astype(jnp.int32)
            pos = jnp.where(split, child, pos)
        return base + leaf_value[pos]

    return jax.jit(predict)


class _Tree:
    """Heap-layout arrays for one trained tree."""

    __slots__ = ("feature", "threshold", "is_split", "leaf_value", "max_depth")

    def __init__(self, feature, threshold, is_split, leaf_value, max_depth):
        self.feature = feature
        self.threshold = threshold
        self.is_split = is_split
        self.leaf_value = leaf_value
        self.max_depth = max_depth


class NativeBooster:
    """A trained TPU-native boosted-tree model."""

    def __init__(self, params: dict, edges: List[np.ndarray], trees: List[_Tree], base_score: float):
        self.params = params
        self._edges = edges
        self._trees = trees
        self._base_score = base_score
        self.best_iteration = len(trees) - 1

    # -- binning -------------------------------------------------------- #

    @staticmethod
    def _bin_features(features: np.ndarray, edges: List[np.ndarray], max_bin: int):
        import jax.numpy as jnp

        cols = []
        for j, e in enumerate(edges):
            x = features[:, j]
            code = np.searchsorted(e, x, side="left") if e.size else np.zeros(len(x), np.int64)
            # NaN goes to the last bin (xgboost's default-right behavior)
            code = np.where(np.isnan(x), max_bin - 1, code)
            cols.append(code.astype(np.int32))
        return jnp.asarray(np.stack(cols, axis=1))

    # -- prediction ----------------------------------------------------- #

    def _raw_predict(self, features: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        max_bin = self.params["max_bin"]
        bins = self._bin_features(features, self._edges, max_bin)
        out = jnp.full(features.shape[0], self._base_score, dtype=jnp.float32)
        for tree in self._trees:
            step = _jit_predict_tree(tree.max_depth)
            out = out + step(
                bins, tree.feature, tree.threshold, tree.is_split,
                tree.leaf_value, jnp.float32(0.0),
            )
        from modin_tpu.parallel.engine import materialize as _engine_materialize

        return np.asarray(_engine_materialize(out), dtype=np.float64)

    def predict(self, data: Any, **kwargs: Any):
        from modin_tpu.experimental.xgboost import DMatrix

        if isinstance(data, DMatrix):
            features = data._features
            index = data._index
        else:
            from modin_tpu.utils import try_cast_to_pandas

            pdf = try_cast_to_pandas(data)
            features = pdf.to_numpy(dtype=np.float64)
            index = pdf.index
        raw = self._raw_predict(features)
        if self.params["objective"] == "binary:logistic":
            raw = 1.0 / (1.0 + np.exp(-raw))
        import modin_tpu.pandas as mpd

        import pandas

        return mpd.Series(pandas.Series(raw, index=index, name="predict"))


def _train_native(
    params: dict,
    features: np.ndarray,
    label: np.ndarray,
    num_boost_round: int,
    evals_result: Optional[Dict[str, Any]] = None,
    evals: Any = (),
) -> NativeBooster:
    import jax
    import jax.numpy as jnp

    p = _resolve_params(params)
    max_bin = int(p["max_bin"])
    max_depth = int(p["max_depth"])
    eta = float(p["eta"])
    logistic = p["objective"] == "binary:logistic"
    base_score = float(p["base_score"])
    # raw (margin) space: log-odds for logistic, identity for regression
    base_margin = math.log(base_score / (1 - base_score)) if logistic else base_score

    edges = [_quantile_edges(features[:, j], max_bin) for j in range(features.shape[1])]
    bins = NativeBooster._bin_features(features, edges, max_bin)
    y = jnp.asarray(label, dtype=jnp.float32)
    n, F = bins.shape

    pred = jnp.full(n, base_margin, dtype=jnp.float32)
    trees: List[_Tree] = []
    history: List[float] = []
    # eval sets: pre-bin with the training quantile edges, keep a running
    # margin per set so each round's metric is one tree-predict + loss
    eval_sets = []
    for feats_e, label_e, name in evals:
        feats_e = np.asarray(feats_e, dtype=np.float64)
        if feats_e.shape[1] != features.shape[1]:
            raise ValueError(
                f"eval set {name!r} has {feats_e.shape[1]} features, "
                f"training data has {features.shape[1]}"
            )
        bins_e = NativeBooster._bin_features(feats_e, edges, max_bin)
        eval_sets.append(
            {
                "name": str(name),
                "bins": bins_e,
                "y": jnp.asarray(label_e, dtype=jnp.float32),
                "pred": jnp.full(bins_e.shape[0], base_margin, dtype=jnp.float32),
                "history": [],
            }
        )

    grad_fn = jax.jit(
        (lambda pr, yy: (jax.nn.sigmoid(pr) - yy, jax.nn.sigmoid(pr) * (1 - jax.nn.sigmoid(pr))))
        if logistic
        else (lambda pr, yy: (pr - yy, jnp.ones_like(pr)))
    )
    loss_fn = jax.jit(
        (lambda pr, yy: -jnp.mean(
            yy * jax.nn.log_sigmoid(pr) + (1 - yy) * jax.nn.log_sigmoid(-pr)
        ))
        if logistic
        else (lambda pr, yy: jnp.sqrt(jnp.mean((pr - yy) ** 2)))
    )

    lam = float(p["lambda"])
    for _round in range(num_boost_round):
        g, h = grad_fn(pred, y)
        node = jnp.zeros(n, dtype=jnp.int32)

        # heap arrays over the complete tree (2**(d+1)-1 nodes)
        total_nodes = 2 ** (max_depth + 1) - 1
        feature_arr = np.zeros(total_nodes, dtype=np.int32)
        threshold_arr = np.zeros(total_nodes, dtype=np.int32)
        split_arr = np.zeros(total_nodes, dtype=bool)
        # per-node (G, H) accumulated as we descend, for leaf weights
        node_G = np.zeros(total_nodes, dtype=np.float64)
        node_H = np.zeros(total_nodes, dtype=np.float64)

        heap_base = 0
        for depth in range(max_depth):
            N = 2**depth
            step = _jit_level_step(
                F, max_bin, N, lam, float(p["gamma"]), float(p["min_child_weight"])
            )
            bf, bb, do_split, _gain, GLb, HLb, G, H, node = step(bins, node, g, h)
            bf_np, bb_np = np.asarray(bf), np.asarray(bb)
            split_np = np.asarray(do_split)
            G_np, H_np = np.asarray(G, np.float64), np.asarray(H, np.float64)
            GL_np, HL_np = np.asarray(GLb, np.float64), np.asarray(HLb, np.float64)
            heap = heap_base + np.arange(N)
            feature_arr[heap] = bf_np
            threshold_arr[heap] = bb_np
            split_arr[heap] = split_np
            node_G[heap] = G_np
            node_H[heap] = H_np
            # children totals (only meaningful under a split)
            child_base = heap_base + N  # == 2*heap_base + 1 for heap layout
            left = 2 * heap + 1
            right = 2 * heap + 2
            node_G[left] = GL_np
            node_H[left] = HL_np
            node_G[right] = G_np - GL_np
            node_H[right] = H_np - HL_np
            heap_base = 2 * heap_base + 1
            if not split_np.any():
                break

        leaf_value = (-node_G / (node_H + lam) * eta).astype(np.float32)
        tree = _Tree(
            jnp.asarray(feature_arr),
            jnp.asarray(threshold_arr),
            jnp.asarray(split_arr),
            jnp.asarray(leaf_value),
            max_depth,
        )
        trees.append(tree)
        pred = pred + _jit_predict_tree(max_depth)(
            bins, tree.feature, tree.threshold, tree.is_split, tree.leaf_value,
            jnp.float32(0.0),
        )
        history.append(float(loss_fn(pred, y)))
        for ev in eval_sets:
            ev["pred"] = ev["pred"] + _jit_predict_tree(max_depth)(
                ev["bins"], tree.feature, tree.threshold, tree.is_split,
                tree.leaf_value, jnp.float32(0.0),
            )
            ev["history"].append(float(loss_fn(ev["pred"], ev["y"])))

    if evals_result is not None:
        metric = "logloss" if logistic else "rmse"
        evals_result.setdefault("train", {})[metric] = history
        for ev in eval_sets:
            evals_result.setdefault(ev["name"], {})[metric] = ev["history"]
    return NativeBooster(p, edges, trees, base_margin)
