"""XGBoost integration (reference: modin/experimental/xgboost/, 1,219 LoC).

xgboost is not available in this environment; the API surface is provided and
raises a clear error on use.  With xgboost installed, DMatrix feeds the
device-backed columns through the exported raw buffers
(modin_tpu.distributed.dataframe.pandas.unwrap_partitions).
"""

from typing import Any


def _require_xgboost():
    try:
        import xgboost  # noqa: F401

        return xgboost
    except ImportError as err:
        raise ImportError(
            "modin_tpu.experimental.xgboost requires the 'xgboost' package"
        ) from err


class DMatrix:
    """xgboost.DMatrix built from a modin_tpu DataFrame."""

    def __init__(self, data: Any, label: Any = None, **kwargs: Any):
        xgb = _require_xgboost()
        from modin_tpu.utils import try_cast_to_pandas

        self._dmatrix = xgb.DMatrix(
            try_cast_to_pandas(data), label=try_cast_to_pandas(label), **kwargs
        )

    def __getattr__(self, item: str) -> Any:
        return getattr(self._dmatrix, item)


def train(params: dict, dtrain: "DMatrix", *args: Any, **kwargs: Any):
    """xgboost.train over a modin_tpu-backed DMatrix."""
    xgb = _require_xgboost()
    inner = dtrain._dmatrix if isinstance(dtrain, DMatrix) else dtrain
    return xgb.train(params, inner, *args, **kwargs)


class Booster:
    def __init__(self, *args: Any, **kwargs: Any):
        xgb = _require_xgboost()
        self._booster = xgb.Booster(*args, **kwargs)

    def predict(self, data: Any, **kwargs: Any):
        from modin_tpu.utils import try_cast_to_pandas

        xgb = _require_xgboost()
        inner = data._dmatrix if isinstance(data, DMatrix) else xgb.DMatrix(try_cast_to_pandas(data))
        return self._booster.predict(inner, **kwargs)

    def __getattr__(self, item: str) -> Any:
        return getattr(self._booster, item)
