"""Distributed gradient-boosted trees over modin_tpu frames.

Reference component: modin/experimental/xgboost/ (xgboost_ray.py:43, 1,219
LoC) — Ray actors each train on their partitions and merge gradient
statistics through rabit allreduce.  This environment has no xgboost
package, so the TPU build ships its own trainer (``native.py``): the same
histogram-GBT algorithm expressed as jit-compiled XLA programs, where the
per-level (node, feature, bin) gradient histogram is one ``segment_sum`` —
over row-sharded columns that lowers to per-shard partials + a mesh psum,
the role rabit's allreduce plays in the reference.

When the real ``xgboost`` package is importable it is preferred (exact
parity with the reference's semantics); otherwise the native trainer runs.
"""

from typing import Any, Dict, Optional

import numpy as np

from modin_tpu.experimental.xgboost.native import NativeBooster, _train_native


def _optional_xgboost():
    try:
        import xgboost

        return xgboost
    except ImportError:
        return None


class DMatrix:
    """Training matrix built from modin_tpu frames (features + label)."""

    def __init__(self, data: Any, label: Any = None, **kwargs: Any):
        from modin_tpu.utils import try_cast_to_pandas

        pdf = try_cast_to_pandas(data)
        self._index = pdf.index
        self.feature_names = list(map(str, pdf.columns))
        self._features = pdf.to_numpy(dtype=np.float64)
        self._label = (
            None
            if label is None
            else np.asarray(try_cast_to_pandas(label, squeeze=True), dtype=np.float64)
        )
        xgb = _optional_xgboost()
        self._dmatrix = (
            xgb.DMatrix(pdf, label=self._label, **kwargs) if xgb else None
        )

    def num_row(self) -> int:
        return self._features.shape[0]

    def num_col(self) -> int:
        return self._features.shape[1]

    def get_label(self):
        return self._label


def train(
    params: dict,
    dtrain: DMatrix,
    num_boost_round: int = 10,
    *,
    evals: Any = (),
    evals_result: Optional[Dict[str, Any]] = None,
    **kwargs: Any,
):
    """Train a boosted-tree model; returns a Booster with ``.predict``."""
    xgb = _optional_xgboost()
    if xgb is not None and dtrain._dmatrix is not None:
        return xgb.train(
            params,
            dtrain._dmatrix,
            num_boost_round=num_boost_round,
            evals=[(dm._dmatrix, name) for dm, name in evals],
            evals_result=evals_result,
            **kwargs,
        )
    if dtrain._label is None:
        raise ValueError("train requires a DMatrix built with a label")
    for dm, _name in evals:
        if dm._label is None:
            raise ValueError("every eval DMatrix must be built with a label")
    return _train_native(
        params, dtrain._features, dtrain._label, num_boost_round,
        evals_result=evals_result,
        evals=[(dm._features, dm._label, name) for dm, name in evals],
    )


Booster = NativeBooster

__all__ = ["DMatrix", "train", "Booster", "NativeBooster"]
