"""Random-workflow fuzzing harness.

Reference design: modin/experimental/fuzzydata/ — a generator of random
dataframe workflows used to fuzz the implementation against pandas
(CI: fuzzydata-test.yml).  ``run_workflow`` builds a random op chain, applies
it to both implementations, and asserts equality after every step.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np
import pandas


def _ops() -> List[Tuple[str, Callable]]:
    return [
        ("head", lambda df, rng: df.head(max(1, len(df) // 2))),
        ("filter", lambda df, rng: df[df[df.columns[0]] > df[df.columns[0]].mean()]
            if len(df) and df.dtypes.iloc[0].kind in "if" else df),
        ("sort", lambda df, rng: df.sort_values(df.columns[-1], kind="stable")),
        ("fillna", lambda df, rng: df.fillna(0)),
        ("arith", lambda df, rng: df * 2 + 1
            if all(d.kind in "if" for d in df.dtypes) else df),
        ("abs", lambda df, rng: df.abs()
            if all(d.kind in "if" for d in df.dtypes) else df),
        ("reset", lambda df, rng: df.reset_index(drop=True)),
        ("project", lambda df, rng: df[
            list(rng.choice(df.columns, size=max(1, len(df.columns) - 1), replace=False))
        ]),
        ("groupby_sum", lambda df, rng: df.groupby(df.columns[0]).sum().reset_index()
            if df.dtypes.iloc[0].kind in "ib" else df),
        ("rename", lambda df, rng: df.rename(columns={df.columns[0]: "c_renamed"})),
        ("drop_dup", lambda df, rng: df.drop_duplicates(ignore_index=True)),
    ]


def generate_frame(rng: np.random.Generator, n: int = 200) -> dict:
    """Random mixed-dtype source data."""
    return {
        "i0": rng.integers(-50, 50, n),
        "f0": np.where(rng.random(n) < 0.1, np.nan, rng.uniform(-5, 5, n)),
        "f1": rng.uniform(0, 1, n),
    }


def run_workflow(seed: int = 0, steps: int = 10, on_divergence: str = "raise") -> List[str]:
    """Run one random workflow against modin_tpu and pandas; returns the trace."""
    import modin_tpu.pandas as mpd
    from pandas.testing import assert_frame_equal

    rng = np.random.default_rng(seed)
    data = generate_frame(rng)
    md = mpd.DataFrame(data)
    pdf = pandas.DataFrame(data)
    ops = _ops()
    trace: List[str] = []
    for _ in range(steps):
        name, op = ops[int(rng.integers(0, len(ops)))]
        trace.append(name)
        op_seed = int(rng.integers(0, 2**32))
        # matching exceptions are AGREEMENT (e.g. both reject sorting by a
        # duplicated label); only a one-sided or mismatched raise diverges
        try:
            pdf_next = op(pdf, np.random.default_rng(op_seed))
            pdf_exc = None
        except Exception as e:  # noqa: BLE001 - differential harness
            pdf_next, pdf_exc = None, e
        try:
            md_next = op(md, np.random.default_rng(op_seed))
            md_exc = None
        except Exception as e:  # noqa: BLE001
            md_next, md_exc = None, e
        if pdf_exc is not None or md_exc is not None:
            agree = (
                pdf_exc is not None
                and md_exc is not None
                and (
                    isinstance(md_exc, type(pdf_exc))
                    or isinstance(pdf_exc, type(md_exc))
                )
            )
            if agree:
                continue  # the op never applied on either side
            if on_divergence == "raise":
                raise AssertionError(
                    f"one-sided exception after {trace}: "
                    f"pandas={pdf_exc!r} modin_tpu={md_exc!r}"
                )
            return trace
        md, pdf = md_next, pdf_next
        try:
            assert_frame_equal(md._to_pandas(), pdf)
        except AssertionError:
            if on_divergence == "raise":
                raise AssertionError(f"workflow diverged after {trace}")
            return trace
    return trace
