"""Crash-safe file writes: ONE temp-file + fsync + atomic-rename helper.

Every on-disk artifact the package folds across process lifetimes —
router calibration tables, cached substrate peaks, PERF_HISTORY.json,
flight-recorder dumps, graftwal checkpoints — used to hand-roll its own
write path, and most of them were plain ``open(path, "w")`` writes: a
crash (or ENOSPC) mid-write leaves truncated JSON that poisons every
future run that loads it.  The fix is the classic three-step dance, done
once, here:

1. write the full payload to a same-directory temp file (same filesystem,
   so the rename below is atomic);
2. ``flush`` + ``os.fsync`` the temp file so the *data* is on disk before
   the name is;
3. ``os.replace`` onto the destination — readers see the old complete
   file or the new complete file, never a prefix.

``fsync_dir=True`` additionally fsyncs the parent directory so the rename
itself survives power loss — graftwal checkpoints need that promise;
cache artifacts (recomputable) default to skipping it.

Deliberate leaf: stdlib only, importable from scripts/ and anywhere in
the package without cycles.
"""

from __future__ import annotations

import json
import os
from typing import Any


def fsync_dir(path: str) -> None:
    """fsync the directory containing ``path`` (rename durability)."""
    dirpath = os.path.dirname(os.path.abspath(path)) or "."
    fd = os.open(dirpath, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_bytes(
    path: str, data: bytes, durable_rename: bool = False
) -> None:
    """Write ``data`` to ``path`` atomically (temp + fsync + rename).

    On ANY failure the temp file is removed and the destination is
    untouched — a reader never observes a partial payload under ``path``.
    ``durable_rename=True`` also fsyncs the parent directory so the new
    name survives power loss (graftwal checkpoints); leave it off for
    recomputable cache artifacts.
    """
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if durable_rename:
        fsync_dir(path)


def atomic_write_text(
    path: str, text: str, durable_rename: bool = False
) -> None:
    """:func:`atomic_write_bytes` for UTF-8 text."""
    atomic_write_bytes(
        path, text.encode("utf-8"), durable_rename=durable_rename
    )


def atomic_write_json(
    path: str, obj: Any, durable_rename: bool = False, **dumps_kwargs: Any
) -> None:
    """:func:`atomic_write_bytes` for a JSON document (serialized FIRST,
    so a non-serializable object fails before any disk state changes)."""
    text = json.dumps(obj, **dumps_kwargs)
    atomic_write_bytes(
        path, text.encode("utf-8"), durable_rename=durable_rename
    )
