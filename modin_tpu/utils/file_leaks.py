"""File-descriptor leak tracking for the IO layer.

TPU-native analogue of the reference's ``TrackFileLeaks`` test guard
(reference: modin/config/envvars.py:893 and its use in modin/tests/pandas
conftest): when the config is enabled, every dispatcher ``read`` snapshots
the process's open regular-file descriptors before and after and raises
``ResourceWarning`` on anything left behind.  Uses ``/proc/self/fd`` (no
psutil in the image); on platforms without procfs the tracker is a no-op.
"""

from __future__ import annotations

import contextlib
import os
import warnings
from typing import Dict, Iterator

_FD_DIR = "/proc/self/fd"


def open_file_fds() -> Dict[int, str]:
    """Open fds resolving to regular files (pipes/sockets/devices excluded)."""
    out: Dict[int, str] = {}
    try:
        fds = os.listdir(_FD_DIR)
    except OSError:  # no procfs
        return out
    for name in fds:
        try:
            target = os.readlink(os.path.join(_FD_DIR, name))
        except OSError:
            continue  # fd closed while listing (e.g. the listdir handle)
        if target.startswith("/") and not target.startswith(("/dev", "/proc", "/sys")):
            with contextlib.suppress(ValueError):
                out[int(name)] = target
    return out


@contextlib.contextmanager
def track_file_leaks() -> Iterator[None]:
    """Raise ``ResourceWarning`` if the block leaks regular-file descriptors.

    Gated on the ``TrackFileLeaks`` config; zero overhead when disabled.
    """
    from modin_tpu.config import TrackFileLeaks

    if not TrackFileLeaks.get():
        yield
        return
    before = open_file_fds()
    yield
    leaked = {
        fd: path
        for fd, path in open_file_fds().items()
        if before.get(fd) != path
    }
    if leaked:
        warnings.warn(
            "file descriptors leaked by IO operation: "
            + ", ".join(f"{fd}->{path}" for fd, path in sorted(leaked.items())),
            ResourceWarning,
            stacklevel=3,
        )
