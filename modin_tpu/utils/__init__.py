"""Shared utilities: docstring inheritance, pandas casting helpers, versions.

Reference design: /root/reference/modin/utils.py (notably ``_inherit_docstrings``
at :544 and ``show_versions`` at :901).
"""

from __future__ import annotations

import functools
import importlib
import json
import platform
import re
import sys
import types
from typing import Any, Callable, Iterable, List, Optional, TypeVar, Union

import numpy as np
import pandas
from pandas.util._decorators import Appender

MODIN_UNNAMED_SERIES_LABEL = "__reduced__"
PANDAS_API_URL_TEMPLATE = (
    "https://pandas.pydata.org/pandas-docs/stable/reference/api/{}.html"
)

Fn = TypeVar("Fn", bound=Any)


def _make_api_url(token: str) -> str:
    return PANDAS_API_URL_TEMPLATE.format(token)


def _replace_doc_urls(doc: Optional[str]) -> Optional[str]:
    return doc


def _inherit_docstrings_in_place(
    cls_or_func: Fn,
    parent: object,
    excluded: List[object],
    overwrite_existing: bool = False,
    apilink: Optional[Union[str, List[str]]] = None,
    record: Optional[List[tuple]] = None,
    only: Optional[set] = None,
) -> None:
    """Copy docs from ``parent`` onto ``cls_or_func`` (class walks its MRO).

    ``record`` collects a key for every docstring actually written, so a
    later ``DocModule`` re-source can restrict itself (via ``only``) to
    exactly the inheritance-managed docs — hand-written docstrings that the
    decoration-time pass preserved stay untouched forever.
    """
    if parent in excluded:
        return
    _CLS_DOC = ("cls",)
    if parent not in _docstring_inheritance_calls and (only is None or _CLS_DOC in only):
        doc = getattr(parent, "__doc__", None)
        if doc and (not cls_or_func.__doc__ or overwrite_existing or only):
            try:
                cls_or_func.__doc__ = doc
                if record is not None:
                    record.append(_CLS_DOC)
            except AttributeError:
                pass
    if not isinstance(cls_or_func, types.FunctionType):
        seen = set()
        for base in getattr(cls_or_func, "__mro__", [cls_or_func]):
            if base is object:
                continue
            for attr, obj in base.__dict__.items():
                if attr in seen or attr.startswith("__"):
                    continue
                seen.add(attr)
                if only is not None and (base, attr) not in only:
                    continue
                parent_obj = getattr(parent, attr, None)
                if parent_obj is None:
                    continue
                parent_doc = getattr(parent_obj, "__doc__", None)
                if not parent_doc:
                    continue
                if isinstance(obj, property):
                    if obj.__doc__ is None or overwrite_existing or only:
                        try:
                            setattr(
                                base,
                                attr,
                                property(obj.fget, obj.fset, obj.fdel, parent_doc),
                            )
                            if record is not None:
                                record.append((base, attr))
                        except (AttributeError, TypeError):
                            pass
                elif callable(obj) or isinstance(obj, (classmethod, staticmethod)):
                    target = obj.__func__ if isinstance(obj, (classmethod, staticmethod)) else obj
                    if getattr(target, "__doc__", None) is None or overwrite_existing or only:
                        try:
                            target.__doc__ = parent_doc
                            if record is not None:
                                record.append((base, attr))
                        except AttributeError:
                            pass


_docstring_inheritance_calls: set = set()

# every _inherit_docstrings application, so DocModule can re-source docs later
_DOC_CALLS: List[tuple] = []
# the module object docs are currently sourced from (None = plain pandas)
_ACTIVE_DOC_MODULE: Optional[types.ModuleType] = None


def _resolve_doc_counterpart(parent: object, doc_module: types.ModuleType) -> object:
    """The object in ``doc_module`` matching ``parent``'s qualified name.

    Falls back to ``parent`` itself (keeping pandas docs) when the custom
    module has no counterpart — DocModule overrides are partial by design
    (reference behavior: envvars.py DocModule + utils.py doc re-sourcing).
    """
    if isinstance(parent, types.ModuleType):
        return doc_module
    path = getattr(parent, "__qualname__", getattr(parent, "__name__", None))
    if not path:
        return parent
    obj: object = doc_module
    for part in path.split("."):
        obj = getattr(obj, part, None)
        if obj is None:
            return parent
    return obj


def _apply_doc_module(param) -> None:
    """DocModule subscriber: re-source registered docstrings from the module.

    Only docstrings the decoration-time pass itself wrote (each call's
    ``written`` record) are ever re-sourced; reverting to ``"pandas"``
    restores the originals from each call's own parent.
    """
    global _ACTIVE_DOC_MODULE
    name = param.get()
    if name == "pandas":
        if _ACTIVE_DOC_MODULE is not None:
            # restore the decoration-time docs from each original parent
            _ACTIVE_DOC_MODULE = None
            for cls_or_func, parent, excluded, apilink, written in list(_DOC_CALLS):
                _inherit_docstrings_in_place(
                    cls_or_func, parent, excluded,
                    apilink=apilink, only=set(written),
                )
        return
    try:
        mod = importlib.import_module(name)
    except ImportError:
        import warnings

        previous = getattr(_ACTIVE_DOC_MODULE, "__name__", "pandas")
        warnings.warn(
            f"DocModule {name!r} is not importable; keeping docs from {previous!r}"
        )
        return
    _ACTIVE_DOC_MODULE = mod
    for cls_or_func, parent, excluded, apilink, written in list(_DOC_CALLS):
        # restore the decoration-time docs first: when switching from custom
        # module A to B, attrs that A documented but B lacks must fall back to
        # the pandas parent, not keep A's text
        _inherit_docstrings_in_place(
            cls_or_func, parent, excluded, apilink=apilink, only=set(written)
        )
        # then overlay the custom module's counterparts; the ``written``
        # filter means hand-written docstrings are never touched
        _inherit_docstrings_in_place(
            cls_or_func,
            _resolve_doc_counterpart(parent, mod),
            excluded,
            apilink=apilink,
            only=set(written),
        )


def _inherit_docstrings(
    parent: object,
    excluded: Optional[List[object]] = None,
    overwrite_existing: bool = False,
    apilink: Optional[Union[str, List[str]]] = None,
) -> Callable[[Fn], Fn]:
    """Class/function decorator copying docstrings from a pandas counterpart.

    Reference: modin/utils.py:544 — keeps the public API self-documenting
    without duplicating pandas' docs in-repo.  Applications are recorded so a
    ``DocModule`` change re-sources every registered docstring from the
    user's module (reference: envvars.py:1338).
    """
    excluded = excluded or []

    def decorator(cls_or_func: Fn) -> Fn:
        written: List[tuple] = []
        _inherit_docstrings_in_place(
            cls_or_func, parent, excluded, overwrite_existing, apilink,
            record=written,
        )
        _DOC_CALLS.append((cls_or_func, parent, excluded, apilink, written))
        if _ACTIVE_DOC_MODULE is not None:
            # DocModule was set before this class was imported: apply now
            counterpart = _resolve_doc_counterpart(parent, _ACTIVE_DOC_MODULE)
            if counterpart is not parent:
                _inherit_docstrings_in_place(
                    cls_or_func,
                    counterpart,
                    excluded,
                    apilink=apilink,
                    only=set(written),
                )
        return cls_or_func

    return decorator


def _subscribe_doc_module() -> None:
    from modin_tpu.config import DocModule

    DocModule.subscribe(_apply_doc_module)


def expanduser_path_arg(argname: str) -> Callable[[Fn], Fn]:
    """Decorator expanding ``~`` in the named path argument."""
    import inspect
    import os

    def decorator(func: Fn) -> Fn:
        sig = inspect.signature(func)

        @functools.wraps(func)
        def wrapped(*args: Any, **kwargs: Any) -> Any:
            try:
                bound = sig.bind(*args, **kwargs)
            except TypeError:
                return func(*args, **kwargs)
            value = bound.arguments.get(argname)
            if isinstance(value, str) and value.startswith("~"):
                bound.arguments[argname] = os.path.expanduser(value)
            elif isinstance(value, os.PathLike):
                str_value = os.fspath(value)
                if str_value.startswith("~"):
                    bound.arguments[argname] = os.path.expanduser(str_value)
            return func(*bound.args, **bound.kwargs)

        return wrapped

    return decorator


def hashable(obj: Any) -> bool:
    """Whether ``obj`` can be hashed (list/dict/set cannot)."""
    try:
        hash(obj)
    except TypeError:
        return False
    return True


def is_scalar(obj: Any) -> bool:
    from pandas.api.types import is_scalar as pandas_is_scalar

    from modin_tpu.pandas.base import BasePandasDataset

    return not isinstance(obj, BasePandasDataset) and pandas_is_scalar(obj)


def wrap_into_list(*args: Any, skipna: bool = True) -> List[Any]:
    """Flatten the passed positional args into a single flat list."""

    def isnan(o: Any) -> bool:
        return o is None or (isinstance(o, float) and np.isnan(o))

    res = []
    for o in args:
        if skipna and isnan(o):
            continue
        if isinstance(o, (list, tuple)):
            res.extend(o)
        else:
            res.append(o)
    return res


def qc_to_pandas_for_write(qc: Any) -> Any:
    """Materialize a query compiler for a writer: Series-shaped compilers
    squeeze and shed the internal unnamed-column sentinel (pandas would
    otherwise emit ``__reduced__`` as the column/header name)."""
    df = qc.to_pandas()
    if getattr(qc, "_shape_hint", None) == "column":
        obj = df.squeeze(axis=1)
        if isinstance(obj, pandas.Series) and obj.name == MODIN_UNNAMED_SERIES_LABEL:
            obj.name = None
        return obj
    return df


def try_cast_to_pandas(obj: Any, squeeze: bool = False) -> Any:
    """Recursively convert modin_tpu objects inside ``obj`` to plain pandas."""
    if hasattr(obj, "_to_pandas"):
        result = obj._to_pandas()
        if squeeze and isinstance(result, pandas.DataFrame):
            result = result.squeeze(axis=1)
        return result
    if hasattr(obj, "to_pandas") and hasattr(obj, "_shape_hint"):
        # a raw query compiler
        result = obj.to_pandas()
        if squeeze or obj._shape_hint == "column":
            result = result.squeeze(axis=1)
            if (
                isinstance(result, pandas.Series)
                and result.name == MODIN_UNNAMED_SERIES_LABEL
            ):
                result.name = None
        return result
    if isinstance(obj, (list, tuple)):
        return type(obj)([try_cast_to_pandas(o, squeeze=squeeze) for o in obj])
    if isinstance(obj, dict):
        return {k: try_cast_to_pandas(v, squeeze=squeeze) for k, v in obj.items()}
    if callable(obj):
        module_hierarchy = getattr(obj, "__module__", "") or ""
        fn_name = getattr(obj, "__name__", None)
        if fn_name and module_hierarchy.startswith("modin_tpu.pandas"):
            return (
                getattr(pandas.DataFrame, fn_name, obj)
                if not module_hierarchy.endswith("series")
                else getattr(pandas.Series, fn_name, obj)
            )
    return obj


def to_pandas(modin_obj: Any) -> Any:
    """Convert a modin_tpu DataFrame/Series to its pandas counterpart."""
    return try_cast_to_pandas(modin_obj)


def func_from_deprecated_location(
    func_name: str, module: str, deprecation_message: str
) -> Callable:
    def deprecated_func(*args: Any, **kwargs: Any) -> Any:
        import warnings

        func = getattr(importlib.import_module(module), func_name)
        warnings.warn(deprecation_message, FutureWarning)
        return func(*args, **kwargs)

    return deprecated_func


class ModinAssumptionError(Exception):
    """An assumption of an optimized code path did not hold; caller should retry generic path."""


def get_current_execution() -> str:
    """Return the current execution name, e.g. ``TpuOnJax``."""
    from modin_tpu.config import Engine, StorageFormat

    return f"{StorageFormat.get()}On{Engine.get()}"


def show_versions(as_json: Union[str, bool] = False) -> None:
    """Print useful debugging information (reference: modin/utils.py:901)."""
    import modin_tpu

    deps = {
        "python": sys.version.replace("\n", " "),
        "OS": platform.platform(),
        "modin_tpu": modin_tpu.__version__,
        "pandas": pandas.__version__,
        "numpy": np.__version__,
    }
    for mod in ("jax", "jaxlib", "flax", "optax", "pyarrow", "fsspec"):
        try:
            deps[mod] = importlib.import_module(mod).__version__
        except Exception:
            deps[mod] = None
    import queue
    import threading

    try:
        import jax
    except ImportError:
        jax = None
    if jax is not None:
        # device discovery can hang if a remote accelerator tunnel is down;
        # bound it with a daemon thread (NOT ThreadPoolExecutor: its atexit
        # hook would join a wedged worker and hang interpreter shutdown)
        result_queue: "queue.Queue" = queue.Queue()

        def probe() -> None:
            try:
                result_queue.put([str(d) for d in jax.devices()])
            except Exception as err:  # pragma: no cover
                result_queue.put(err)

        thread = threading.Thread(  # graftlint: disable=THREAD-HYGIENE -- pure-stdlib build probe: deliberately imports no observability so a diagnostics dump works when the package is half-broken
            target=probe, name="modin-tpu-version-probe", daemon=True
        )
        thread.start()
        try:
            devices = result_queue.get(timeout=10)
        except queue.Empty:
            deps["jax.devices"] = "unavailable (device discovery timed out)"
        else:
            if isinstance(devices, Exception):
                deps["jax.devices"] = (
                    f"unavailable ({type(devices).__name__}: {devices})"
                )
            else:
                deps["jax.devices"] = ", ".join(devices)
                deps["jax.default_backend"] = jax.default_backend()

    if as_json:
        if as_json is True:
            print(json.dumps(deps, indent=2))  # noqa: T201
        else:
            with open(as_json, "w") as f:
                json.dump(deps, f, indent=2)
        return
    print("\nINSTALLED VERSIONS")  # noqa: T201
    print("------------------")  # noqa: T201
    for k, v in deps.items():
        print(f"{k:20}: {v}")  # noqa: T201


def import_optional_dependency(name: str, extra: str = ""):
    """Import a soft dependency, raising a helpful error when missing."""
    try:
        return importlib.import_module(name)
    except ImportError as err:
        raise ImportError(
            f"Missing optional dependency '{name}'. {extra} "
            f"Use pip or conda to install {name}."
        ) from err


def sentinel(name: str) -> object:
    """Create a unique named sentinel object (repr-friendly)."""
    return type(name, (), {"__repr__": lambda self: name})()


no_default = pandas.api.extensions.no_default


_subscribe_doc_module()
