"""graftlint — AST-based invariant checkers for the device/host seam.

PR 1 established three codebase-wide invariants by hand review: every host
sync goes through the ``JaxWrapper.materialize`` seam, every ``_try_*``
device family has a pandas fallback behind a named circuit breaker, and no
broad ``except Exception`` may mask a device fault as a semantic fallback.
This package turns those (and two registry-drift invariants that grew out of
them) into permanent static tooling, in the spirit of Dias
(arXiv:2303.16146): pandas-style code is regular enough for precise AST-level
analysis, and the lazy/eager (device/host) boundary a dataframe system lives
or dies by ("Towards Scalable Dataframe Systems", arXiv:2001.00888) is
exactly the kind of seam a checker can pin down.

Usage::

    python -m modin_tpu.lint modin_tpu/            # CLI; exit 1 on findings
    python -m modin_tpu.lint --list-rules

    from modin_tpu.lint import run_lint
    result = run_lint(["modin_tpu/"], root=repo_root)
    assert not result.findings

Rules live in ``modin_tpu/lint/rules/``; the framework (finding objects,
pragma + baseline suppression, per-file AST contexts with parent/scope
tracking) in ``modin_tpu/lint/framework.py``.  See docs/linting.md for the
rule catalog and the baseline burn-down workflow.
"""

from modin_tpu.lint.framework import (  # noqa: F401
    Finding,
    FileContext,
    LintResult,
    Project,
    Rule,
    all_rules,
    register_rule,
    run_lint,
)

# importing the package registers every built-in rule
import modin_tpu.lint.rules  # noqa: E402,F401

__all__ = [
    "Finding",
    "FileContext",
    "LintResult",
    "Project",
    "Rule",
    "all_rules",
    "register_rule",
    "run_lint",
]
