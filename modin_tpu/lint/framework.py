"""graftlint framework: findings, file contexts, pragmas, baseline, registry.

Design:

- a ``Rule`` walks parsed files and yields ``Finding`` objects carrying
  ``path:line``, the rule id, a message, and a fix hint;
- ``FileContext`` owns one file's AST with parent links, dotted scope names
  (``Class.method.inner``), and the ``# graftlint: disable=RULE`` pragma map;
- ``Project`` owns the scanned file set plus cross-file lookups (rules like
  FALLBACK-PARITY and REGISTRY-DRIFT check one file against a registry
  declared in another);
- suppression is two-layer: inline pragmas for *vetted* violations (the
  reason lives next to the code), and a baseline file for *pre-existing*
  violations being burned down incrementally.  Baseline keys deliberately
  contain no line numbers (``path::rule::scope::symbol``) so they survive
  unrelated edits; a key that no longer matches any finding is *stale* and
  fails the run — dead suppressions hide future violations, same rationale
  as the old allowlist-pruning test this framework subsumes.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)

#: rule id the framework itself emits for disable-pragmas that suppressed
#: nothing (the inline analogue of a dead allowlist entry)
UNUSED_PRAGMA_RULE = "GL-PRAGMA-UNUSED"


@dataclass
class Finding:
    """One rule violation at a source location."""

    path: str  # root-relative, posix separators
    line: int
    rule: str
    message: str
    fix_hint: str = ""
    scope: str = "<module>"  # dotted enclosing Class.function chain
    symbol: str = ""  # stable token distinguishing findings within a scope

    @property
    def baseline_key(self) -> str:
        """Line-number-free identity used by the baseline file."""
        return f"{self.path}::{self.rule}::{self.scope}::{self.symbol}"

    def render(self) -> str:
        """``path:line: RULE message`` — clickable in editors/CI logs."""
        text = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.fix_hint:
            text += f" (fix: {self.fix_hint})"
        return text


class FileContext:
    """One parsed source file with parent links, scopes, and pragmas."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = ast.parse(source, filename=rel)
        self.parents: Dict[ast.AST, ast.AST] = {}
        self.scopes: Dict[ast.AST, str] = {self.tree: "<module>"}
        self._build_maps()
        self.pragmas: Dict[int, Set[str]] = self._parse_pragmas(source)
        self._used_pragma_lines: Set[int] = set()

    def _build_maps(self) -> None:
        def visit(node: ast.AST, scope: str) -> None:
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
                child_scope = scope
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    child_scope = (
                        child.name if scope == "<module>" else f"{scope}.{child.name}"
                    )
                self.scopes[child] = child_scope
                visit(child, child_scope)

        visit(self.tree, "<module>")

    @staticmethod
    def _parse_pragmas(source: str) -> Dict[int, Set[str]]:
        """{lineno: {rule ids}} from ``# graftlint: disable=A,B`` comments.

        Tokenized (not regex-over-lines) so pragma text inside string
        literals can't masquerade as a suppression.
        """
        pragmas: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                    pragmas.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenError:  # unterminated strings etc.: no pragmas
            pass
        return pragmas

    # -- queries rules use --------------------------------------------- #

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def scope_of(self, node: ast.AST) -> str:
        return self.scopes.get(node, "<module>")

    def enclosing_function_name(self, node: ast.AST) -> str:
        """Nearest enclosing function's bare name ('<module>' at top level)."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self.parents.get(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur.name
        return "<module>"

    def is_suppressed(self, finding: Finding) -> bool:
        """Pragma on the finding's line, or on the line directly above it."""
        for line in (finding.line, finding.line - 1):
            rules = self.pragmas.get(line)
            if rules and (finding.rule in rules or "all" in rules):
                self._used_pragma_lines.add(line)
                return True
        return False


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # actionable
    suppressed: List[Finding] = field(default_factory=list)  # pragma'd
    baselined: List[Finding] = field(default_factory=list)  # baseline hits
    stale_baseline: List[str] = field(default_factory=list)  # dead entries

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.stale_baseline) else 0


class Project:
    """The scanned file set plus cross-file lookups and repo-level text."""

    def __init__(self, root: Path, files: List[FileContext]):
        self.root = root
        self.files = files
        self._by_rel = {f.rel: f for f in files}

    def file(self, rel: str) -> Optional[FileContext]:
        return self._by_rel.get(rel)

    def files_matching(self, suffix: str) -> List[FileContext]:
        """Scanned files whose root-relative path ends with ``suffix``.

        Rules reference registry files this way (e.g.
        ``core/execution/resilience.py``) so unit tests can mirror the layout
        under a tmp root without the real package.
        """
        return [f for f in self.files if f.rel.endswith(suffix)]

    def docs_text(self) -> str:
        """Concatenated ``docs/*.md`` under the root ('' when absent)."""
        docs_dir = self.root / "docs"
        if not docs_dir.is_dir():
            return ""
        return "\n".join(
            p.read_text(encoding="utf-8", errors="replace")
            for p in sorted(docs_dir.glob("*.md"))
        )

    def has_docs(self) -> bool:
        return (self.root / "docs").is_dir()


class Rule:
    """Base class: subclass, set ``id``/``description``, implement a check.

    Override ``check_file`` for per-file rules; override ``check_project``
    when the rule needs cross-file context (registries, call graphs).
    """

    id: str = ""
    description: str = ""

    def check_project(self, project: Project) -> Iterator[Finding]:
        for ctx in project.files:
            yield from self.check_file(ctx, project)

    def check_file(self, ctx: FileContext, project: Project) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and register a Rule by its id."""
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------- #
# baseline file
# ---------------------------------------------------------------------- #


def load_baseline(path: Path) -> Set[str]:
    """Baseline keys, one per line; '#' comments and blanks ignored."""
    if not path.exists():
        return set()
    keys = set()
    for line in path.read_text().splitlines():
        line = line.strip()
        if line and not line.startswith("#"):
            keys.add(line)
    return keys


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    keys = sorted({f.baseline_key for f in findings})
    lines = [
        "# graftlint baseline — pre-existing violations being burned down.",
        "# One key per line: path::RULE::scope::symbol (no line numbers, so",
        "# keys survive unrelated edits).  Remove entries as you fix them;",
        "# stale entries fail the lint.  Regenerate: python -m modin_tpu.lint",
        "# --baseline-write <paths>.  Prefer fixing over baselining; prefer a",
        "# reasoned '# graftlint: disable=RULE' pragma for vetted exceptions.",
    ]
    lines += keys
    path.write_text("\n".join(lines) + "\n")


# ---------------------------------------------------------------------- #
# driver
# ---------------------------------------------------------------------- #


def _collect_py_files(root: Path, paths: Sequence[Path]) -> List[Tuple[Path, str]]:
    seen: Set[Path] = set()
    out: List[Tuple[Path, str]] = []
    for p in paths:
        p = p if p.is_absolute() else root / p
        candidates = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for c in candidates:
            c = c.resolve()
            if c in seen:
                continue
            seen.add(c)
            try:
                rel = c.relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = c.as_posix()
            out.append((c, rel))
    return out


def build_project(
    paths: Sequence, root: Optional[Path] = None
) -> Tuple[Project, List[Finding]]:
    """Parse every .py under ``paths`` into a Project.

    Returns (project, parse_failures): a file that doesn't parse becomes a
    GL-PARSE finding instead of crashing the whole run.
    """
    paths = [Path(p) for p in paths]
    if root is None:
        root = _detect_root(paths)
    root = Path(root)
    files: List[FileContext] = []
    failures: List[Finding] = []
    for path, rel in _collect_py_files(root, paths):
        source = path.read_text(encoding="utf-8", errors="replace")
        try:
            ctx = FileContext(path, rel, source)
        except SyntaxError as err:
            failures.append(
                Finding(
                    path=rel,
                    line=err.lineno or 1,
                    rule="GL-PARSE",
                    message=f"file does not parse: {err.msg}",
                    symbol="parse",
                )
            )
            continue
        files.append(ctx)
    return Project(root, files), failures


def _detect_root(paths: Sequence[Path]) -> Path:
    """Walk up from the first path looking for pyproject.toml; else cwd."""
    start = paths[0] if paths else Path.cwd()
    start = start if start.is_absolute() else Path.cwd() / start
    cur = start if start.is_dir() else start.parent
    for candidate in [cur, *cur.parents]:
        if (candidate / "pyproject.toml").exists():
            return candidate
    return Path.cwd()


def run_lint(
    paths: Sequence,
    root: Optional[Path] = None,
    baseline: Optional[Path] = None,
    select: Optional[Iterable[str]] = None,
) -> LintResult:
    """Run the registered rules (or the ``select`` subset) over ``paths``."""
    project, failures = build_project(paths, root=root)
    rules = all_rules()
    if select is not None:
        select = set(select)
        unknown = select - set(rules)
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = {rid: r for rid, r in rules.items() if rid in select}

    raw: List[Finding] = list(failures)
    for rule in rules.values():
        raw.extend(rule.check_project(project))

    # pass 1 — pragma suppression (also marks which pragma lines earned
    # their keep, which the unused-pragma sweep below needs)
    result = LintResult()
    unsuppressed: List[Finding] = []
    for finding in raw:
        ctx = project.file(finding.path)
        if ctx is not None and ctx.is_suppressed(finding):
            result.suppressed.append(finding)
        else:
            unsuppressed.append(finding)

    # pass 2 — a disable-pragma that suppressed nothing is itself a finding:
    # dead suppressions hide the next real violation.  Only on full runs (a
    # --select run legitimately skips other rules' pragmas), and BEFORE the
    # baseline filter so these findings baseline like any other.
    if select is None:
        known = set(all_rules()) | {"all"}
        for ctx in project.files:
            for line, prules in sorted(ctx.pragmas.items()):
                if line in ctx._used_pragma_lines:
                    continue
                if not (prules & known):
                    continue  # pragma for a rule this build doesn't know
                unsuppressed.append(
                    Finding(
                        path=ctx.rel,
                        line=line,
                        rule=UNUSED_PRAGMA_RULE,
                        message=(
                            "disable pragma suppresses nothing "
                            f"({', '.join(sorted(prules))}) — remove it"
                        ),
                        scope="<module>",
                        symbol=f"pragma-{'-'.join(sorted(prules))}",
                    )
                )

    # pass 3 — baseline filter
    baseline_keys = load_baseline(baseline) if baseline else set()
    matched_keys: Set[str] = set()
    for finding in unsuppressed:
        if finding.baseline_key in baseline_keys:
            matched_keys.add(finding.baseline_key)
            result.baselined.append(finding)
        else:
            result.findings.append(finding)

    # stale-entry detection is only sound when the run could have matched
    # the entry: all rules active AND the entry's file inside the scanned
    # set.  A --select or subset-path run must not cry stale over entries
    # it never had a chance to regenerate.
    if select is None:
        scanned = {ctx.rel for ctx in project.files}
        result.stale_baseline = sorted(
            key
            for key in baseline_keys - matched_keys
            if key.split("::", 1)[0] in scanned
        )

    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
