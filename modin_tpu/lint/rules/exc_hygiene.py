"""EXC-HYGIENE: no broad exception handlers around device dispatch.

Port of (and replacement for) the standalone ``scripts/
check_exception_hygiene.py`` from PR 1.  A bare ``except:`` or ``except
Exception:`` in the audited trees swallows jax ``XlaRuntimeError`` device
failures and misreads them as semantic "not supported on device" fallbacks —
the exact bug class the resilience layer exists to eliminate.  Handlers must
name the semantic exception types they mean (``TypeError``, ``ValueError``,
``ShuffleSkewError``, ...) so infrastructure failures propagate to the
classify/retry/breaker machinery.

Vetted broad handlers (host-only work where the library surface raises too
many types to enumerate, or the resilience layer itself — the one place
whose JOB is to catch broadly, classify, and re-raise) carry an inline
``# graftlint: disable=EXC-HYGIENE -- <reason>`` pragma on the handler line,
replacing the old script's central allowlist: the justification now lives
next to the code it excuses, and the framework flags any pragma whose
handler has been fixed or deleted (GL-PRAGMA-UNUSED) the way the old
``test_allowlist_entries_still_exist`` pruned dead allowlist entries.
"""

from __future__ import annotations

import ast
from typing import Iterator

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule

#: trees where device dispatch lives; the pandas API layer and experimental
#: integrations legitimately wrap third-party surfaces broadly
AUDITED_PREFIXES = (
    "modin_tpu/core/",
    "modin_tpu/parallel/",
    "modin_tpu/ops/",
)


def is_broad(handler: ast.ExceptHandler) -> bool:
    """bare ``except:`` or any clause naming Exception/BaseException."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception", "BaseException"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in ("Exception", "BaseException"):
            return True
    return False


@register_rule
class ExcHygieneRule(Rule):
    id = "EXC-HYGIENE"
    description = (
        "no bare except / except Exception in device-dispatch trees — name "
        "the semantic types so device failures reach the resilience layer"
    )

    def check_file(self, ctx: FileContext, project: Project) -> Iterator[Finding]:
        if not ctx.rel.startswith(AUDITED_PREFIXES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler) or not is_broad(node):
                continue
            func = ctx.enclosing_function_name(node)
            yield Finding(
                path=ctx.rel,
                line=node.lineno,
                rule=self.id,
                message=f"broad exception handler in {func}() swallows "
                "device failures as semantic fallbacks",
                fix_hint="name the semantic exception types (TypeError, "
                "ValueError, ShuffleSkewError, ...); if genuinely vetted, "
                "add `# graftlint: disable=EXC-HYGIENE -- <reason>` on the "
                "handler line",
                scope=ctx.scope_of(node),
                symbol=f"broad-except-{func}",
            )
