"""HOST-SYNC: every device->host sync goes through the materialize seam.

The PR-1 invariant: ``JaxWrapper.materialize``/``wait`` (modin_tpu/parallel/
engine.py) is the ONE place a device value crosses to the host, because the
crossing is where the resilience policy lives — classification, bounded
retry, and the wall-clock watchdog.  A stray ``jax.device_get``, a
``.block_until_ready()``, or an ``np.asarray``/``float``/``int``/``bool``
coercion of a device value performs the identical blocking transfer with
*none* of that machinery: a wedged tunnel hangs the query forever and an
XlaRuntimeError surfaces raw at a random call site.

Detection is a per-function forward pass:

- ``jax.device_get(...)`` / ``x.block_until_ready()`` anywhere outside the
  seam modules is flagged unconditionally;
- names are tracked as *device-valued* when assigned from ``jnp.*`` /
  ``jax.lax.*`` calls or the ``_jit_foo(statics)(args)`` double-call pattern
  (the codebase idiom for compiled kernels), and as *host-valued* when
  assigned from a ``materialize`` call; coercion sinks
  (``np.asarray(x)``, ``float/int/bool(x)``, ``x.item()``) over a
  device-valued expression are flagged.

Host metadata escapes (``x.shape``, ``x.dtype``, ``jnp.issubdtype``) are
recognized, so shape arithmetic and dtype dispatch never trip the rule.

**Streaming leg (graftstream):** a function decorated ``@window_body`` is a
registered window-loop body — it runs once per resident window, and the
out-of-core budget only holds if it touches nothing but the window handed
to it.  Whole-column forces of *captured* (closure) state inside one —
``captured.to_numpy()``, ``materialize(captured)``, ``captured.host_cache``
— would materialize the full frame from inside the loop, so they are
flagged; the same sinks over the body's own parameters/locals (the window)
are the loop's normal work and stay clean.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import (
    STATIC_ATTRS,
    assigned_names,
    dotted_parts,
)

#: modules that ARE the seam (or deliberately below it): the engine wrapper,
#: the resilience policy itself, the version-compat shims, and the
#: fault-injection harness that wraps the seam in tests
SEAM_MODULES = (
    "modin_tpu/parallel/engine.py",
    "modin_tpu/core/execution/resilience.py",
    "modin_tpu/parallel/jax_compat.py",
    "modin_tpu/testing/faults.py",
)

#: jnp/jax functions that return host Python values (metadata), not arrays
_HOST_RETURNING = frozenset(
    {
        "issubdtype",
        "isdtype",
        "result_type",
        "promote_types",
        "can_cast",
        "iinfo",
        "finfo",
        "dtype",
        "devices",
        "device_count",
        "local_device_count",
        "local_devices",
        "default_backend",
        "process_index",
        "process_count",
    }
)

#: names whose call results are host values fetched through the seam
_MATERIALIZE_NAMES = frozenset({"materialize", "_engine_materialize"})

_DEVICE_ROOTS = frozenset({"jnp", "lax"})

_COERCION_BUILTINS = frozenset({"float", "int", "bool", "complex"})


def _is_jit_factory_call(func: ast.AST) -> bool:
    """The ``_jit_foo(...)`` half of the ``_jit_foo(...)(cols)`` idiom."""
    return isinstance(func, ast.Name) and func.id.startswith("_jit_")


def _is_window_body(fn: ast.AST) -> bool:
    """Whether ``fn`` carries the ``@window_body`` registration decorator
    (bare name or any dotted spelling, e.g. ``streaming.window_body``)."""
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        parts = dotted_parts(target)
        if parts and parts[-1] == "window_body":
            return True
    return False


def _window_local_names(fn: ast.AST) -> set:
    """Names bound inside a window-loop body (parameters and every
    assignment/loop/with/comprehension target): reads of these are the
    window; reads of anything else are captured whole-frame state."""
    args = fn.args
    names = {a.arg for a in args.args + args.kwonlyargs + args.posonlyargs}
    for special in (args.vararg, args.kwarg):
        if special is not None:
            names.add(special.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                names.update(assigned_names(target))
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            names.update(assigned_names(node.target))
        elif isinstance(node, ast.For):
            names.update(assigned_names(node.target))
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    names.update(assigned_names(item.optional_vars))
        elif isinstance(node, ast.comprehension):
            names.update(assigned_names(node.target))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                names.add(node.name)
    return names


def _base_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute/subscript chain, or None (a call
    result or literal has no stable identity to classify)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class _FunctionState:
    """Name -> 'device' | 'host' knowledge within one function scope."""

    def __init__(self, inherited: Optional[Dict[str, str]] = None):
        self.names: Dict[str, str] = dict(inherited or {})

    def classify(self, node: ast.AST) -> Optional[str]:
        """'device', 'host', or None (unknown) for an expression."""
        if isinstance(node, ast.Name):
            return self.names.get(node.id)
        if isinstance(node, ast.Call):
            return self._classify_call(node)
        if isinstance(node, ast.Attribute):
            base = self.classify(node.value)
            if base == "device":
                return "host" if node.attr in STATIC_ATTRS else "device"
            return base
        if isinstance(node, ast.Subscript):
            return self.classify(node.value)
        if isinstance(node, ast.UnaryOp):
            return self.classify(node.operand)
        if isinstance(node, (ast.BinOp,)):
            left = self.classify(node.left)
            right = self.classify(node.right)
            if "device" in (left, right):
                return "device"
            if left == "host" and right == "host":
                return "host"
            return None
        if isinstance(node, ast.Compare):
            sides = [self.classify(node.left)] + [
                self.classify(c) for c in node.comparators
            ]
            if "device" in sides:
                return "device"
            return None
        if isinstance(node, (ast.Tuple, ast.List)):
            kinds = {self.classify(e) for e in node.elts}
            if "device" in kinds:
                return "device"
            if kinds == {"host"}:
                return "host"
            return None
        if isinstance(node, ast.IfExp):
            kinds = {self.classify(node.body), self.classify(node.orelse)}
            if "device" in kinds:
                return "device"
            return None
        if isinstance(node, ast.Constant):
            return "host"
        return None

    def _classify_call(self, node: ast.Call) -> Optional[str]:
        func = node.func
        parts = dotted_parts(func)
        if parts:
            leaf = parts[-1]
            root = parts[0]
            if leaf in _MATERIALIZE_NAMES:
                return "host"
            if len(parts) >= 2 and parts[-2] == "JaxWrapper" and leaf == "materialize":
                return "host"
            if root in _DEVICE_ROOTS or parts[:2] == ["jax", "numpy"] or parts[:2] == [
                "jax",
                "lax",
            ]:
                return "host" if leaf in _HOST_RETURNING else "device"
            if root == "jax":
                return "host" if leaf in _HOST_RETURNING else None
            if root in ("np", "numpy"):
                return "host"
            if root == "pandas" or root == "pd":
                return "host"
            if leaf in _COERCION_BUILTINS or leaf in ("len", "str", "repr", "tuple", "list"):
                return "host"
            # method call on a tracked object: device methods stay device,
            # host metadata methods (item/tolist handled as sinks) aside
            if isinstance(func, ast.Attribute):
                base = self.classify(func.value)
                if base == "device":
                    return "host" if func.attr in ("item", "tolist") else "device"
                return None
        if isinstance(func, ast.Call) and _is_jit_factory_call(func.func):
            # _jit_foo(statics)(cols) -> compiled-kernel output: device
            return "device"
        return None

    def bind(self, target: ast.AST, kind: Optional[str]) -> None:
        for name in assigned_names(target):
            if kind is None:
                self.names.pop(name, None)
            else:
                self.names[name] = kind


@register_rule
class HostSyncRule(Rule):
    id = "HOST-SYNC"
    description = (
        "device->host syncs (device_get / block_until_ready / np.asarray / "
        "float/int/bool coercion of device values) must go through "
        "JaxWrapper.materialize so the resilience policy applies"
    )

    def check_file(self, ctx: FileContext, project: Project) -> Iterator[Finding]:
        if ctx.rel in SEAM_MODULES or any(
            ctx.rel.endswith(m) for m in SEAM_MODULES
        ):
            return
        # 1. unconditional: raw seam primitives outside the seam modules
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted_parts(node.func)
            leaf = parts[-1] if parts else None
            if leaf == "device_get":
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message="raw jax.device_get bypasses the resilience seam",
                    fix_hint="route through modin_tpu.parallel.engine."
                    "materialize (JaxWrapper.materialize)",
                    scope=ctx.scope_of(node),
                    symbol="device_get",
                )
            elif leaf == "block_until_ready" and isinstance(node.func, ast.Attribute):
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message="raw block_until_ready bypasses the resilience seam",
                    fix_hint="route through JaxWrapper.wait",
                    scope=ctx.scope_of(node),
                    symbol="block_until_ready",
                )
        # 2. dataflow: device-valued expressions reaching coercion sinks
        yield from self._check_scope(ctx, ctx.tree, _FunctionState())
        # 3. streaming leg: whole-frame forces inside window-loop bodies
        for node in ast.walk(ctx.tree):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and _is_window_body(node):
                yield from self._check_window_body(ctx, node)

    # -- streaming leg ---------------------------------------------------- #

    def _check_window_body(
        self, ctx: FileContext, fn: ast.AST
    ) -> Iterator[Finding]:
        local = _window_local_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                sink = self._window_call_sink(node, local)
                if sink is not None:
                    yield self._window_finding(ctx, fn, node, sink)
            elif (
                isinstance(node, ast.Attribute)
                and node.attr == "host_cache"
                and isinstance(node.ctx, ast.Load)
            ):
                base = _base_name(node.value)
                if base is not None and base not in local:
                    yield self._window_finding(ctx, fn, node, ".host_cache")

    def _window_call_sink(
        self, call: ast.Call, local: set
    ) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in (
            "to_numpy",
            "materialize",
        ):
            base = _base_name(func.value)
            if base is not None and base not in local and base != "JaxWrapper":
                return f".{func.attr}()"
        parts = dotted_parts(func)
        if parts and parts[-1] in _MATERIALIZE_NAMES | {"materialize"}:
            for arg in call.args:
                base = _base_name(arg)
                if base is not None and base not in local:
                    return f"{parts[-1]}()"
        return None

    def _window_finding(
        self, ctx: FileContext, fn: ast.AST, node: ast.AST, sink: str
    ) -> Finding:
        return Finding(
            path=ctx.rel,
            line=node.lineno,
            rule=self.id,
            message=f"{sink} forces whole-frame state captured from outside "
            "the window-loop body (one window must never materialize the "
            "full frame)",
            fix_hint="operate only on the window handed to the body; hoist "
            "whole-column fetches out of the loop or slice them per window",
            scope=ctx.scope_of(node),
            symbol=f"stream-{fn.name}-{sink.strip('().')}",
        )

    # -- dataflow pass -------------------------------------------------- #

    def _check_scope(
        self, ctx: FileContext, scope_node: ast.AST, state: _FunctionState
    ) -> Iterator[Finding]:
        body = getattr(scope_node, "body", [])
        yield from self._check_stmts(ctx, body, state)

    def _check_stmts(
        self, ctx: FileContext, stmts: List[ast.stmt], state: _FunctionState
    ) -> Iterator[Finding]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # inner scope: inherits current knowledge (closures), params
                # are unknown; its bindings don't leak back out
                inner = _FunctionState(state.names)
                for arg in stmt.args.args + stmt.args.kwonlyargs:
                    inner.names.pop(arg.arg, None)
                yield from self._check_scope(ctx, stmt, inner)
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from self._check_scope(ctx, stmt, _FunctionState(state.names))
                continue
            # compound statements: scan only their header expressions for
            # sinks (state before binding), then recurse into the bodies
            if isinstance(stmt, ast.For):
                yield from self._scan_expr(ctx, stmt.iter, state)
                state.bind(stmt.target, state.classify(stmt.iter))
                yield from self._check_stmts(ctx, stmt.body, state)
                yield from self._check_stmts(ctx, stmt.orelse, state)
            elif isinstance(stmt, (ast.While, ast.If)):
                yield from self._scan_expr(ctx, stmt.test, state)
                yield from self._check_stmts(ctx, stmt.body, state)
                yield from self._check_stmts(ctx, stmt.orelse, state)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    yield from self._scan_expr(ctx, item.context_expr, state)
                yield from self._check_stmts(ctx, stmt.body, state)
            elif isinstance(stmt, ast.Try):
                yield from self._check_stmts(ctx, stmt.body, state)
                for handler in stmt.handlers:
                    yield from self._check_stmts(ctx, handler.body, state)
                yield from self._check_stmts(ctx, stmt.orelse, state)
                yield from self._check_stmts(ctx, stmt.finalbody, state)
            else:
                # simple statement: scan the whole thing, then apply bindings
                yield from self._scan_expr(ctx, stmt, state)
                if isinstance(stmt, ast.Assign):
                    kind = state.classify(stmt.value)
                    for target in stmt.targets:
                        state.bind(target, kind)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    state.bind(stmt.target, state.classify(stmt.value))
                elif isinstance(stmt, ast.AugAssign):
                    state.bind(stmt.target, state.classify(stmt.value))

    def _scan_expr(
        self, ctx: FileContext, node: ast.AST, state: _FunctionState
    ) -> Iterator[Finding]:
        for expr in ast.walk(node):
            if isinstance(expr, ast.Call):
                finding = self._check_sink(ctx, expr, state)
                if finding is not None:
                    yield finding

    def _check_sink(
        self, ctx: FileContext, call: ast.Call, state: _FunctionState
    ) -> Optional[Finding]:
        func = call.func
        # float(x) / int(x) / bool(x)
        if (
            isinstance(func, ast.Name)
            and func.id in _COERCION_BUILTINS
            and len(call.args) == 1
            and state.classify(call.args[0]) == "device"
        ):
            return self._coercion_finding(ctx, call, f"{func.id}()")
        # np.asarray(x) / numpy.asarray(x) / np.array(x)
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in ("np", "numpy")
            and call.args
            and state.classify(call.args[0]) == "device"
        ):
            return self._coercion_finding(ctx, call, f"np.{func.attr}()")
        # x.item() / x.tolist()
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("item", "tolist")
            and not call.args
            and state.classify(func.value) == "device"
        ):
            return self._coercion_finding(ctx, call, f".{func.attr}()")
        return None

    def _coercion_finding(
        self, ctx: FileContext, call: ast.Call, sink: str
    ) -> Finding:
        return Finding(
            path=ctx.rel,
            line=call.lineno,
            rule=self.id,
            message=f"{sink} coerces a device value on the host "
            "(implicit blocking transfer outside the resilience seam)",
            fix_hint="fetch through materialize(...) first, then coerce the "
            "host value",
            scope=ctx.scope_of(call),
            symbol=f"coerce-{sink.strip('().')}"
            f"-{ctx.enclosing_function_name(call)}",
        )
