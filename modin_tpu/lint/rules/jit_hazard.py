"""JIT-HAZARD: jitted functions must not trace Python control flow or shapes.

Inside ``jax.jit``, the function runs once over abstract tracers; three
Python-level habits silently break (or silently bake in stale state):

1. **traced value in Python control flow** — ``if``/``while``/``assert`` on
   a traced argument (or a value derived from one) forces a concretization
   error at trace time, or worse, a host sync per call.  The fix is
   ``jnp.where``/``lax.cond``, or marking the argument static.
2. **traced value in shape position** — ``jnp.zeros(m)``, ``x.reshape(k)``,
   ``range(n)`` with a traced ``m``/``k``/``n``: XLA shapes are compile-time
   constants, so the value must be a Python int (closure constant or
   ``static_argnums``), not a tracer.
3. **closure capture of mutable state** — a jitted body reading a
   module-level ``list``/``dict``/``set`` freezes its contents at trace time;
   later mutations are silently ignored (classic stale-cache bug).

The codebase idiom (SNIPPETS-style factory closures:
``def _jit_op(static...): def fn(cols): ...; return jax.jit(fn)``) is the
*endorsed* way to make shapes static — the statics live in the closure and
participate in the ``lru_cache`` key.  This rule recognizes the idiom and
checks the inner function's parameters as traced.

Static escapes: ``x.shape``/``x.dtype``/``x.ndim`` and ``len(x)`` of a
traced array are host metadata, fine anywhere; parameters named by
``static_argnums``/``static_argnames`` at the jit site are exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import STATIC_ATTRS, assigned_names, dotted_parts

#: jnp/lax constructors whose FIRST argument is a shape (or length)
_SHAPE_FIRST_ARG = frozenset(
    {"zeros", "ones", "empty", "full", "arange", "linspace", "eye", "tri"}
)
#: array methods whose arguments are shapes
_SHAPE_METHODS = frozenset({"reshape", "broadcast_to", "resize"})

#: module-level bindings considered mutable when read from a jitted body
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
_MUTABLE_CALLS = frozenset({"list", "dict", "set", "defaultdict", "OrderedDict"})

#: cross-device collective primitives: legal ONLY inside a shard_map body
#: (and never under a traced Python conditional there) — a collective in a
#: plain jit / under data-dependent Python control flow is the SPMD
#: miscompile class documented at ops/reductions.py:57 (a global lax.cond
#: over sharded operands partitions each branch inconsistently per device,
#: and a collective outside shard_map has no named mesh axis to rendezvous
#: on)
_COLLECTIVES = frozenset(
    {"all_to_all", "psum", "all_gather", "ppermute", "pmean", "psum_scatter"}
)


def _jit_static_params(
    call: ast.Call, fn: ast.FunctionDef
) -> Set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    static: Set[str] = set()
    params = [a.arg for a in fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums: List[int] = []
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
            for n in nums:
                if 0 <= n < len(params):
                    static.add(params[n])
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    static.add(e.value)
    return static


def _is_jit_callable(node: ast.AST) -> bool:
    """Is this expression ``jax.jit`` / ``jit`` (possibly under partial)?"""
    parts = dotted_parts(node)
    return parts is not None and parts[-1] == "jit" and (
        len(parts) == 1 or parts[-2] in ("jax", "compat")
    )


class _TracedState:
    """Names known to hold traced (tracer) values in one jitted body."""

    def __init__(self, traced: Set[str]):
        self.traced = set(traced)

    def is_traced_expr(self, node: ast.AST) -> bool:
        """Does this expression carry a traced value (not just metadata)?"""
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False  # x.shape etc: host metadata
            return self.is_traced_expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_traced_expr(node.value) or self.is_traced_expr(node.slice)
        if isinstance(node, ast.Call):
            parts = dotted_parts(node.func)
            if parts and parts[-1] == "len":
                return False  # len(tracer) is its static leading dim
            if parts and parts[-1] in ("issubdtype", "isinstance"):
                return False
            # a call over traced inputs yields a traced output (jnp.sum(x)...)
            return any(self.is_traced_expr(a) for a in node.args) or any(
                self.is_traced_expr(kw.value) for kw in node.keywords
            )
        if isinstance(node, ast.BinOp):
            return self.is_traced_expr(node.left) or self.is_traced_expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced_expr(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` resolves at trace time from the
            # Python structure — identity never concretizes a tracer
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops) and all(
                isinstance(c, ast.Constant) and c.value is None
                for c in node.comparators
            ):
                return False
            return self.is_traced_expr(node.left) or any(
                self.is_traced_expr(c) for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced_expr(v) for v in node.values)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_traced_expr(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return (
                self.is_traced_expr(node.test)
                or self.is_traced_expr(node.body)
                or self.is_traced_expr(node.orelse)
            )
        if isinstance(node, ast.Slice):
            return any(
                part is not None and self.is_traced_expr(part)
                for part in (node.lower, node.upper, node.step)
            )
        return False


@register_rule
class JitHazardRule(Rule):
    id = "JIT-HAZARD"
    description = (
        "jitted functions must not use traced values in Python control flow "
        "or shape positions, and must not close over mutable module state"
    )

    def check_file(self, ctx: FileContext, project: Project) -> Iterator[Finding]:
        module_mutables = self._module_mutables(ctx)
        shard_bodies = self._shard_map_bodies(ctx)
        shard_scopes = {ctx.scope_of(fn) for fn in shard_bodies}
        yield from self._check_collective_placement(ctx, shard_scopes)
        yield from self._check_donation(ctx)
        for fn, static_params in self._jitted_functions(ctx):
            traced = {
                a.arg for a in fn.args.args if a.arg not in static_params
            } - {"self", "cls"}
            yield from self._check_body(
                ctx,
                fn,
                _TracedState(traced),
                module_mutables,
                in_shard_map=fn in shard_bodies,
            )

    # -- donation leg (graftfuse) --------------------------------------- #
    #
    # A buffer passed in a donated jit position is CONSUMED by the
    # dispatch: XLA reuses its memory for the program's outputs and any
    # later read answers garbage or raises "deleted or donated".  The leg
    # flags, within one function scope, any load of a name AFTER it was
    # passed at a donated argument position of a callable built by
    # ``jax.jit(..., donate_argnums=...)`` in the same scope.

    @staticmethod
    def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
        """Literal donate_argnums of a jit call, or None when absent or
        not statically known."""
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            nums = []
            for e in elts:
                if not (isinstance(e, ast.Constant) and isinstance(e.value, int)):
                    return None
                nums.append(e.value)
            return tuple(nums)
        return None

    @staticmethod
    def _own_nodes(fn: ast.FunctionDef) -> Iterator[ast.AST]:
        """Walk ``fn``'s body WITHOUT descending into nested function
        bodies: a nested def's reads execute when IT is called, not at
        definition time, so mixing its positions into the enclosing
        function's timeline flags pre-call reads and double-reports the
        nested function's own hazards (it gets its own walk)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def _branch_path(
        ctx: FileContext, node: ast.AST, stop: ast.AST
    ) -> Tuple[Dict[int, str], bool]:
        """({id(If): branch}, any-enclosing-loop) for ``node`` up to
        ``stop`` — the mutual-exclusion evidence: a load in the OTHER
        branch of an If the consuming call sits in can never execute
        after it in the same pass, unless a loop re-enters."""
        path: Dict[int, str] = {}
        loops = False
        child: ast.AST = node
        cur = ctx.parent_of(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                loops = True
            if isinstance(cur, ast.If):
                path[id(cur)] = "orelse" if child in cur.orelse else "body"
            child = cur
            cur = ctx.parent_of(cur)
        return path, loops

    def _check_donation(self, ctx: FileContext) -> Iterator[Finding]:
        # scope -> {jitted-callable name: donated positions}
        donated_fns: Dict[Tuple[str, str], Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            if not _is_jit_callable(call.func):
                continue
            positions = self._donate_positions(call)
            if not positions:
                continue
            scope = ctx.scope_of(node)
            for target in node.targets:
                for name in assigned_names(target):
                    donated_fns[(scope, name)] = positions
        if not donated_fns:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            scope = ctx.scope_of(fn)
            # donated names -> the source position where the consuming call
            # ENDS.  Positions are (line, col) pairs, not bare lines: in
            # `f(x) + x` the second load is on the call's own line but
            # still runs after the dispatch consumed x's buffer — Python
            # evaluates left to right, so textually-after-the-call is
            # after-the-consumption
            consumed: Dict[str, Tuple[Tuple[int, int], ast.Call]] = {}
            for node in self._own_nodes(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                ):
                    # resolve through the scope chain: a nested body may
                    # call a jitted closure its ENCLOSING function built
                    positions = None
                    chain_scope = scope
                    while positions is None:
                        positions = donated_fns.get(
                            (chain_scope, node.func.id)
                        )
                        if "." not in chain_scope:
                            break
                        chain_scope = chain_scope.rsplit(".", 1)[0]
                    if positions is None:
                        positions = donated_fns.get(
                            ("<module>", node.func.id)
                        )
                    if positions:
                        end = (
                            getattr(node, "end_lineno", node.lineno),
                            getattr(node, "end_col_offset", 0),
                        )
                        for pos in positions:
                            if pos < len(node.args) and isinstance(
                                node.args[pos], ast.Name
                            ):
                                name = node.args[pos].id
                                prev = consumed.get(name)
                                # keep the EARLIEST consuming position —
                                # ast.walk is BFS, so first-seen order is
                                # not source order
                                if prev is None or end < prev[0]:
                                    consumed[name] = (end, node)
            if not consumed:
                continue
            # a rebind AFTER the consuming call makes later reads clean
            # (the name no longer holds the donated buffer).  A rebind's
            # effective position is the END of its statement, not the
            # target Name's own (left-hand) position: in the idiomatic
            # `x = f(x)` the Store is textually before the call but the
            # assignment completes after it — later reads of x hold the
            # program's OUTPUT and are clean.
            rebinds: Dict[str, List[Tuple[int, int]]] = {}
            for node in self._own_nodes(fn):
                if isinstance(
                    node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
                ):
                    end = (
                        getattr(node, "end_lineno", node.lineno),
                        getattr(node, "end_col_offset", 0),
                    )
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for target in targets:
                        for name in assigned_names(target):
                            rebinds.setdefault(name, []).append(end)
                elif isinstance(node, (ast.For, ast.comprehension)):
                    for name in assigned_names(node.target):
                        rebinds.setdefault(name, []).append(
                            (node.target.lineno, node.target.col_offset)
                        )
            for node in self._own_nodes(fn):
                if not (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in consumed
                ):
                    continue
                pos, call = consumed[node.id]
                if (node.lineno, node.col_offset) <= pos:
                    continue
                if any(
                    # <=: a rebind ending exactly at the consuming call's
                    # end IS the assignment that captured its result
                    # (`x = f(x)`)
                    pos <= store < (node.lineno, node.col_offset)
                    for store in rebinds.get(node.id, ())
                ):
                    continue
                # mutual exclusion: a load in the OTHER branch of an If
                # the consuming call sits in never runs after it in the
                # same pass — unless a loop can re-enter the whole shape
                call_path, call_loops = self._branch_path(ctx, call, fn)
                load_path, load_loops = self._branch_path(ctx, node, fn)
                if (
                    not call_loops
                    and not load_loops
                    and any(
                        call_path.get(k) != b
                        for k, b in load_path.items()
                        if k in call_path
                    )
                ):
                    continue
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        f"`{node.id}` read after being passed in a "
                        "donated jit position — the dispatch consumed "
                        "its buffer (use-after-donate); re-read it "
                        "through its owning column's lineage instead"
                    ),
                    fix_hint=(
                        "donated buffers are dead after the call: mark "
                        "the owning DeviceColumn donated (spilled) and "
                        "access via col.raw, or drop donate_argnums"
                    ),
                    scope=ctx.scope_of(node),
                    symbol=f"donated-{node.id}",
                )

    # -- discovery ------------------------------------------------------ #

    def _module_mutables(self, ctx: FileContext) -> Set[str]:
        names: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                value = stmt.value
                mutable = isinstance(value, _MUTABLE_LITERALS) or (
                    isinstance(value, ast.Call)
                    and (p := dotted_parts(value.func)) is not None
                    and p[-1] in _MUTABLE_CALLS
                )
                if mutable:
                    for t in stmt.targets:
                        names.update(assigned_names(t))
        return names

    def _defs_by_scope(
        self, ctx: FileContext
    ) -> Dict[Tuple[str, str], ast.FunctionDef]:
        """(containing scope, name) -> FunctionDef for call-form resolution."""
        defs: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                own = ctx.scope_of(node)
                containing = (
                    own.rsplit(".", 1)[0] if "." in own else "<module>"
                )
                defs[(containing, node.name)] = node
        return defs

    def _resolve_in_chain(
        self,
        ctx: FileContext,
        call: ast.Call,
        defs: Dict[Tuple[str, str], ast.FunctionDef],
    ) -> Optional[ast.FunctionDef]:
        """The same-file FunctionDef a call's first positional arg names,
        resolved through the call site's scope chain (innermost first)."""
        if not call.args or not isinstance(call.args[0], ast.Name):
            return None
        fname = call.args[0].id
        scope = ctx.scope_of(call)
        chain = [scope]
        while "." in scope:
            scope = scope.rsplit(".", 1)[0]
            chain.append(scope)
        chain.append("<module>")
        for s in chain:
            fn = defs.get((s, fname))
            if fn is not None:
                return fn
        return None

    def _shard_map_bodies(self, ctx: FileContext) -> Set[ast.FunctionDef]:
        """Function defs passed to ``shard_map(...)`` in this file — the
        only scopes where a cross-device collective is legal."""
        defs = self._defs_by_scope(ctx)
        bodies: Set[ast.FunctionDef] = set()
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and (p := dotted_parts(node.func)) is not None
                and p[-1] == "shard_map"
            ):
                continue
            fn = self._resolve_in_chain(ctx, node, defs)
            if fn is not None:
                bodies.add(fn)
        return bodies

    @staticmethod
    def _is_collective_call(node: ast.AST) -> Optional[str]:
        """The collective's name when ``node`` is a lax collective call."""
        if not isinstance(node, ast.Call):
            return None
        parts = dotted_parts(node.func)
        if parts is None or parts[-1] not in _COLLECTIVES:
            return None
        # module form (lax.psum / jax.lax.all_to_all) or a bare name
        # imported from lax; dotted access on anything else (obj.psum) is
        # some other API
        if len(parts) == 1 or parts[-2] == "lax":
            return parts[-1]
        return None

    def _check_collective_placement(
        self, ctx: FileContext, shard_scopes: Set[str]
    ) -> Iterator[Finding]:
        """Collectives outside every shard_map body: no named mesh axis to
        rendezvous on — at best a trace error, at worst the per-device
        inconsistent-partitioning miscompile (ops/reductions.py:57)."""
        for node in ast.walk(ctx.tree):
            name = self._is_collective_call(node)
            if name is None:
                continue
            scope = ctx.scope_of(node)
            inside = any(
                scope == s or scope.startswith(s + ".")
                for s in shard_scopes
            )
            if inside:
                continue
            yield Finding(
                path=ctx.rel,
                line=getattr(node, "lineno", 1),
                rule=self.id,
                message=(
                    f"collective `{name}` outside a shard_map body — no "
                    "mesh axis binding; under SPMD partitioning this is "
                    "the miscompile class documented at ops/reductions.py"
                ),
                fix_hint=(
                    "move the collective into a function passed to "
                    "shard_map (parallel/jax_compat.py) with the mesh axis "
                    "in scope, or use a sharded jnp reduction and let XLA "
                    "emit the collective"
                ),
                scope=scope,
                symbol=f"collective-{name}",
            )

    def _jitted_functions(
        self, ctx: FileContext
    ) -> Iterator[Tuple[ast.FunctionDef, Set[str]]]:
        """(function def, static param names) for every jitted function."""
        seen: Set[ast.FunctionDef] = set()
        # decorator forms: @jax.jit, @jit, @partial(jax.jit, ...)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            for dec in node.decorator_list:
                if _is_jit_callable(dec):
                    seen.add(node)
                    yield node, set()
                elif isinstance(dec, ast.Call):
                    if _is_jit_callable(dec.func) or (
                        (p := dotted_parts(dec.func)) is not None
                        and p[-1] == "partial"
                        and dec.args
                        and _is_jit_callable(dec.args[0])
                    ):
                        seen.add(node)
                        yield node, _jit_static_params(dec, node)
        # call form: jax.jit(fn, ...) where fn is a def in the same file.
        # scope_of(def) includes the def's own name; key by the CONTAINING
        # scope so the jit call site's scope chain resolves it.
        defs_by_scope = self._defs_by_scope(ctx)
        for node in ast.walk(ctx.tree):
            is_jit = isinstance(node, ast.Call) and _is_jit_callable(node.func)
            # shard_map(fn, ...) traces fn exactly like jit does
            is_shard_map = (
                isinstance(node, ast.Call)
                and (p := dotted_parts(node.func)) is not None
                and p[-1] == "shard_map"
            )
            if not (is_jit or is_shard_map):
                continue
            fn = self._resolve_in_chain(ctx, node, defs_by_scope)
            if fn is not None and fn not in seen:
                seen.add(fn)
                yield fn, _jit_static_params(node, fn)

    # -- hazard checks -------------------------------------------------- #

    def _check_body(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        state: _TracedState,
        module_mutables: Set[str],
        in_shard_map: bool = False,
    ) -> Iterator[Finding]:
        local_bindings: Set[str] = set()
        for node in ast.walk(fn):
            # propagate tracedness through simple assignments (pre-pass is
            # one-shot; ast.walk is pre-order so defs come before uses in
            # straight-line code, which is what kernels are)
            if isinstance(node, ast.Assign):
                if state.is_traced_expr(node.value):
                    for t in node.targets:
                        state.traced.update(assigned_names(t))
                for t in node.targets:
                    local_bindings.update(assigned_names(t))

        for node in ast.walk(fn):
            # 1. Python control flow on traced values
            if isinstance(node, (ast.If, ast.While)) and state.is_traced_expr(
                node.test
            ):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield self._finding(
                    ctx,
                    node,
                    fn,
                    f"`{kind}` on a traced value concretizes the tracer",
                    "use jnp.where / lax.cond, or make the value static "
                    "(closure constant or static_argnums)",
                    f"branch-{kind}",
                )
            elif isinstance(node, ast.Assert) and state.is_traced_expr(node.test):
                yield self._finding(
                    ctx,
                    node,
                    fn,
                    "`assert` on a traced value concretizes the tracer",
                    "use checkify or drop the assert from the jitted body",
                    "branch-assert",
                )
            elif isinstance(node, ast.IfExp) and state.is_traced_expr(node.test):
                yield self._finding(
                    ctx,
                    node,
                    fn,
                    "conditional expression on a traced value concretizes "
                    "the tracer",
                    "use jnp.where(test, a, b)",
                    "branch-ifexp",
                )
            # 2. traced values in shape positions
            if isinstance(node, ast.Call):
                yield from self._check_shape_call(ctx, node, fn, state)
            # 2b. collective under a traced Python conditional inside a
            # shard_map body: the branch partitions inconsistently per
            # device and the collective rendezvous never lines up — the
            # SPMD miscompile class documented at ops/reductions.py:57
            if (
                in_shard_map
                and isinstance(node, (ast.If, ast.While, ast.IfExp))
                and state.is_traced_expr(node.test)
            ):
                for sub in ast.walk(node):
                    name = self._is_collective_call(sub)
                    if name is not None:
                        yield self._finding(
                            ctx,
                            sub,
                            fn,
                            f"collective `{name}` under a traced Python "
                            "conditional — per-device branch divergence "
                            "deadlocks/miscompiles the rendezvous",
                            "hoist the collective out of the branch; "
                            "select its INPUT with jnp.where instead",
                            f"collective-branch-{name}",
                        )
                        break

        # 3. closure capture of mutable module state
        reported: Set[str] = set()
        params = {a.arg for a in fn.args.args}
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in module_mutables
                and node.id not in params
                and node.id not in local_bindings
                and node.id not in reported
            ):
                reported.add(node.id)
                yield self._finding(
                    ctx,
                    node,
                    fn,
                    f"jitted body reads mutable module state `{node.id}` — "
                    "tracing freezes its current contents",
                    "pass it as an argument, hoist an immutable snapshot "
                    "(tuple/frozenset), or look it up outside the jit",
                    f"closure-{node.id}",
                )

    def _check_shape_call(
        self,
        ctx: FileContext,
        call: ast.Call,
        fn: ast.FunctionDef,
        state: _TracedState,
    ) -> Iterator[Finding]:
        parts = dotted_parts(call.func)
        leaf = parts[-1] if parts else None
        if leaf == "range":
            if any(state.is_traced_expr(a) for a in call.args):
                yield self._finding(
                    ctx,
                    call,
                    fn,
                    "range() over a traced value unrolls at trace time "
                    "(or fails to concretize)",
                    "use lax.fori_loop / lax.scan, or a static bound",
                    "shape-range",
                )
            return
        shape_args: List[ast.AST] = []
        module_form = parts is not None and (
            parts[0] in ("jnp", "np", "numpy", "lax")
            or parts[:2] in (["jax", "numpy"], ["jax", "lax"])
        )
        if leaf in _SHAPE_FIRST_ARG and module_form:
            if call.args:
                shape_args = [call.args[0]]
                if leaf in ("arange", "linspace"):
                    shape_args = list(call.args)  # any bound being traced is the bug
        elif leaf in _SHAPE_METHODS and isinstance(call.func, ast.Attribute):
            # jnp.reshape(arr, shape) / jnp.broadcast_to(arr, shape) carry
            # the data in arg 0; the method form x.reshape(shape) doesn't
            shape_args = list(call.args[1:] if module_form else call.args)
        for arg in shape_args:
            if state.is_traced_expr(arg):
                yield self._finding(
                    ctx,
                    call,
                    fn,
                    f"traced value in the shape position of {leaf}() — XLA "
                    "shapes are compile-time constants",
                    "make the size a Python int: closure constant, "
                    "static_argnums at the jit site, or x.shape metadata",
                    f"shape-{leaf}",
                )
                break

    def _finding(
        self,
        ctx: FileContext,
        node: ast.AST,
        fn: ast.FunctionDef,
        message: str,
        fix_hint: str,
        symbol: str,
    ) -> Finding:
        return Finding(
            path=ctx.rel,
            line=getattr(node, "lineno", fn.lineno),
            rule=self.id,
            message=f"in jitted `{fn.name}`: {message}",
            fix_hint=fix_hint,
            scope=ctx.scope_of(node),
            symbol=f"{fn.name}-{symbol}",
        )
