"""LOCK-ORDER / LOCK-BLOCKING: statically checked lock discipline.

Both rules consume the shared whole-program lock analysis
(:mod:`_lockgraph`): lock bindings resolved across files, an
interprocedural ``held -> acquired`` edge set with one witness site per
edge, and per-function summaries of reachable acquisitions and blocking
operations.

**LOCK-ORDER** flags three things:

1. an acquisition edge that *contradicts the declared partial order*
   (``LOCK_ORDER`` in concurrency/registry.py) — acquiring ``A`` while
   holding ``B`` when the registry declares ``A`` before ``B``.  This is
   the PR-9 dispatch-vs-reseat inversion class, caught at lint time from
   one side alone;
2. a *cycle in the observed graph* — two code paths that nest the same
   pair of locks in opposite orders are an ABBA deadlock waiting for the
   interleaving, whether or not the registry ordered the pair;
3. an acquisition of an *undeclared lock* — a raw
   ``threading.Lock()``/``RLock()`` that never went through
   ``named_lock``/``named_rlock`` is invisible to the registry, the
   declared order, and the runtime lockdep validator.

**LOCK-BLOCKING** flags a blocking operation reachable while a registry
lock is held — directly in the ``with`` body or through any resolved call
chain: ``time.sleep``, ``Thread.join``, ``queue.get()`` with no timeout,
``subprocess`` waits, socket waits, ``pickle`` of arbitrarily large
state, and the engine-seam ``deploy``/``materialize`` entry points (a
device dispatch under a lock serializes every other thread behind a
multi-second wall — the PR-9 "metric fan-out outside the gate lock"
class).

Both rules honor ``# graftlint: disable=...`` pragmas for vetted sites
(e.g. a lock whose entire purpose is serializing one socket's writes).
"""

from __future__ import annotations

from typing import Dict, Iterator, Set, Tuple

from modin_tpu.lint.framework import Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._lockgraph import get_analysis


@register_rule
class LockOrderRule(Rule):
    id = "LOCK-ORDER"
    description = (
        "lock acquisitions must follow the declared partial order in "
        "concurrency/registry.py: no edge contradicting a declared edge, "
        "no cycle in the observed acquisition graph, no acquisition of a "
        "lock that bypassed named_lock/named_rlock"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        analysis = get_analysis(project)

        # leg 3 — undeclared (anonymous) lock acquisitions
        seen_raw: Set[Tuple[str, str, str]] = set()
        for acq in analysis.acquisitions:
            if not acq.raw:
                continue
            scope = acq.ctx.scope_of(acq.node)
            key = (acq.ctx.rel, scope, acq.name)
            if key in seen_raw:
                continue
            seen_raw.add(key)
            yield Finding(
                path=acq.ctx.rel,
                line=acq.node.lineno,
                rule=self.id,
                message=(
                    "acquisition of an undeclared lock (raw threading."
                    "Lock/RLock) — invisible to the declared order and "
                    "the runtime lockdep validator"
                ),
                fix_hint=(
                    "declare it in concurrency/registry.py:LOCKS and "
                    "construct it with named_lock()/named_rlock()"
                ),
                scope=scope,
                symbol="undeclared-lock",
            )

        # observed closure, for cycle detection
        adjacency: Dict[str, Set[str]] = {}
        for before, after in analysis.edges:
            adjacency.setdefault(before, set()).add(after)

        def reaches(start: str, goal: str) -> bool:
            seen: Set[str] = set()
            stack = list(adjacency.get(start, ()))
            while stack:
                node = stack.pop()
                if node == goal:
                    return True
                if node in seen:
                    continue
                seen.add(node)
                stack.extend(adjacency.get(node, ()))
            return False

        for (held, acquired), (ctx, node) in sorted(
            analysis.edges.items(), key=lambda kv: kv[0]
        ):
            # leg 1 — contradiction of the declared order
            if held in analysis.declared_closure.get(acquired, ()):
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        f"acquires '{acquired}' while holding '{held}' — "
                        f"contradicts the declared order {acquired} -> "
                        f"{held} (concurrency/registry.py:LOCK_ORDER)"
                    ),
                    fix_hint=(
                        "restructure to acquire in declared order (snapshot "
                        "under the held lock, act after releasing), or fix "
                        "the declaration if reality is right"
                    ),
                    scope=ctx.scope_of(node),
                    symbol=f"contradicts-{held}-{acquired}",
                )
            # leg 2 — cycle in the observed graph (an opposite-direction
            # path exists for this edge)
            elif reaches(acquired, held):
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        f"acquires '{acquired}' while holding '{held}', "
                        f"but another path acquires '{held}' while "
                        f"'{acquired}' is held — ABBA deadlock cycle in "
                        "the observed acquisition graph"
                    ),
                    fix_hint=(
                        "pick one order, declare it in LOCK_ORDER, and "
                        "restructure the losing side (usually: snapshot "
                        "state under one lock, release, then act)"
                    ),
                    scope=ctx.scope_of(node),
                    symbol=f"cycle-{held}-{acquired}",
                )


@register_rule
class LockBlockingRule(Rule):
    id = "LOCK-BLOCKING"
    description = (
        "no blocking operation (sleep, Thread.join, untimed queue.get, "
        "subprocess/socket waits, pickle of large state, engine-seam "
        "deploy/materialize) may be reachable while a registry lock is "
        "held"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        analysis = get_analysis(project)
        seen: Set[Tuple[str, int, str, Tuple[str, str]]] = set()
        for ctx, node, held, op, via in analysis.blocking_findings:
            key = (ctx.rel, node.lineno, held, op.key())
            if key in seen:
                continue
            seen.add(key)
            via_txt = f" (via {via}())" if via else ""
            yield Finding(
                path=ctx.rel,
                line=node.lineno,
                rule=self.id,
                message=(
                    f"{op.detail} reachable while holding '{held}'"
                    f"{via_txt} — every thread contending the lock waits "
                    "out the blocking call too"
                ),
                fix_hint=(
                    "snapshot state under the lock and perform the "
                    "blocking work after releasing it (the gate's "
                    "shed/metric fan-out pattern)"
                ),
                scope=ctx.scope_of(node),
                symbol=f"blocking-{held}-{op.kind}",
            )
