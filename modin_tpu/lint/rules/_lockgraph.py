"""Shared whole-program lock analysis for LOCK-ORDER / LOCK-BLOCKING.

Builds, once per lint run (cached on the Project):

- **lock bindings** — every place a ``named_lock("x")`` / ``named_rlock``
  / raw ``threading.Lock()`` lands in a name: module globals, class
  attributes assigned through ``self.``, and alias assignments
  (``_CACHE_LOCK = _registry.LOCK``), resolved across files through the
  import graph;
- **acquisition sites** — ``with <lock>:`` blocks and ``<lock>.acquire()``
  calls whose target expression resolves to a binding;
- **function summaries** — for every def, the set of lock names and
  blocking operations reachable from its body (direct + transitive
  through a resolved call graph: same-file scope chain like jit_hazard's,
  ``self.method``, and ``module.function`` through imports), computed to
  fixpoint so recursion converges;
- **the observed edge set** — ``held -> acquired`` with a witness site
  per edge, from each with-block's body effects (nested acquisitions in
  the block itself plus everything its calls reach).

Resolution is deliberately conservative: an expression that does not
resolve to a known binding is not a lock (``with span(...)`` etc.), and
an attribute on an arbitrary receiver resolves only when exactly one
class in the project binds that attribute name to a lock (``rep.lock``
works because only ``_Replica`` has a ``.lock``).  Missed resolution
costs coverage, never false positives — the runtime lockdep validator is
the backstop for what the static half cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from modin_tpu.lint.framework import FileContext, Project
from modin_tpu.lint.rules._ast_utils import dotted_parts

LOCK_REGISTRY_SUFFIX = "concurrency/registry.py"

#: the two factory names; position 0 argument is the lock's registry name
_FACTORIES = {"named_lock": "lock", "named_rlock": "rlock"}

#: subprocess-module calls that wait on a child process
_SUBPROCESS_WAITS = frozenset(
    {"run", "call", "check_call", "check_output", "communicate", "wait"}
)
#: socket methods that park the thread on the network
_SOCKET_WAITS = frozenset({"recv", "recv_into", "accept", "connect", "sendall"})
#: engine-seam entry points: each one is a device dispatch (or a full
#: host materialization) — seconds of wall, never legal under a lock
_ENGINE_SEAM = frozenset({"deploy", "materialize"})


class Acquisition:
    """One resolved lock acquisition site."""

    __slots__ = ("ctx", "node", "name", "raw", "body")

    def __init__(self, ctx, node, name, raw, body):
        self.ctx = ctx  # FileContext
        self.node = node  # the With or Call node
        self.name = name  # registry name, or the binding's var/attr name
        self.raw = raw  # True: anonymous threading.Lock(), not a DepLock
        self.body = body  # with-block statements ([] for .acquire() calls)


class Blocking:
    """One blocking operation (category + human description)."""

    __slots__ = ("kind", "detail")

    def __init__(self, kind: str, detail: str):
        self.kind = kind
        self.detail = detail

    def key(self) -> Tuple[str, str]:
        return (self.kind, self.detail)


class LockAnalysis:
    """See module docstring.  Get via :func:`get_analysis`."""

    def __init__(self, project: Project):
        self.project = project
        # (rel, var) -> (name, kind, raw) for module-level bindings
        self.module_locks: Dict[Tuple[str, str], Tuple[str, str, bool]] = {}
        # (rel, class_scope, attr) -> (name, kind, raw)
        self.attr_locks: Dict[Tuple[str, str, str], Tuple[str, str, bool]] = {}
        # attr -> {(name, kind, raw)} across the project (unique-attr fallback)
        self.attr_global: Dict[str, Set[Tuple[str, str, bool]]] = {}
        # rel -> {alias: imported module rel}
        self.import_maps: Dict[str, Dict[str, str]] = {}
        # rel -> {local name: (source rel, source symbol)} for from-imports
        self.symbol_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        # declared registry data parsed from concurrency/registry.py
        self.declared_kinds: Dict[str, str] = {}
        self.declared_edges: Set[Tuple[str, str]] = set()
        self.declared_closure: Dict[str, Set[str]] = {}
        # analysis products
        self.acquisitions: List[Acquisition] = []
        # (before, after) -> witness (ctx, node) — first seen
        self.edges: Dict[Tuple[str, str], Tuple[FileContext, ast.AST]] = {}
        # (rel, scope) summaries
        self.fn_locks: Dict[Tuple[str, str], Set[str]] = {}
        self.fn_blocking: Dict[Tuple[str, str], Set[Tuple[str, str]]] = {}
        self.blocking_findings: List[
            Tuple[FileContext, ast.AST, str, Blocking, str]
        ] = []  # (ctx, site, held lock name, blocking op, via)

        self._defs: Dict[Tuple[str, str], Tuple[FileContext, ast.AST]] = {}
        self._thread_bindings: Set[Tuple[str, str, str]] = set()
        self._queue_bindings: Set[Tuple[str, str, str]] = set()
        self._socket_bindings: Set[Tuple[str, str, str]] = set()

        self._parse_registry()
        self._build_imports()
        self._build_bindings()
        self._build_defs()
        self._summarize()
        self._walk_acquisitions()

    # -- registry data --------------------------------------------------- #

    def _parse_registry(self) -> None:
        for ctx in self.project.files_matching(LOCK_REGISTRY_SUFFIX):
            for node in ctx.tree.body:
                # both plain and annotated assignment (the registry
                # declares ``LOCKS: Tuple[...] = (...)``)
                if isinstance(node, ast.Assign):
                    names = {
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    }
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    names = {node.target.id}
                else:
                    continue
                if "LOCKS" in names and isinstance(
                    node.value, (ast.Tuple, ast.List)
                ):
                    for entry in node.value.elts:
                        if (
                            isinstance(entry, (ast.Tuple, ast.List))
                            and len(entry.elts) >= 2
                            and isinstance(entry.elts[0], ast.Constant)
                            and isinstance(entry.elts[1], ast.Constant)
                        ):
                            self.declared_kinds[entry.elts[0].value] = (
                                entry.elts[1].value
                            )
                if "LOCK_ORDER" in names and isinstance(
                    node.value, (ast.Tuple, ast.List)
                ):
                    for entry in node.value.elts:
                        if (
                            isinstance(entry, (ast.Tuple, ast.List))
                            and len(entry.elts) >= 2
                            and isinstance(entry.elts[0], ast.Constant)
                            and isinstance(entry.elts[1], ast.Constant)
                        ):
                            self.declared_edges.add(
                                (entry.elts[0].value, entry.elts[1].value)
                            )
            break
        # transitive closure of the declared order (DFS per node)
        adjacency: Dict[str, Set[str]] = {}
        for before, after in self.declared_edges:
            adjacency.setdefault(before, set()).add(after)
        for start in adjacency:
            seen: Set[str] = set()
            stack = list(adjacency[start])
            while stack:
                node = stack.pop()
                if node in seen:
                    continue
                seen.add(node)
                stack.extend(adjacency.get(node, ()))
            self.declared_closure[start] = seen

    # -- imports --------------------------------------------------------- #

    def _module_rel(self, dotted: str) -> Optional[str]:
        """The project-relative path a dotted module name resolves to."""
        path = dotted.replace(".", "/")
        for candidate in (f"{path}.py", f"{path}/__init__.py"):
            for ctx in self.project.files:
                if ctx.rel == candidate or ctx.rel.endswith("/" + candidate):
                    return ctx.rel
        return None

    def _build_imports(self) -> None:
        for ctx in self.project.files:
            aliases: Dict[str, str] = {}
            symbols: Dict[str, Tuple[str, str]] = {}
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        rel = self._module_rel(alias.name)
                        if rel:
                            aliases[alias.asname or alias.name] = rel
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for alias in node.names:
                        sub = self._module_rel(
                            f"{node.module}.{alias.name}"
                        )
                        if sub:  # `from pkg import module`
                            aliases[alias.asname or alias.name] = sub
                            continue
                        rel = self._module_rel(node.module)
                        if rel:  # `from module import symbol`
                            symbols[alias.asname or alias.name] = (
                                rel,
                                alias.name,
                            )
            self.import_maps[ctx.rel] = aliases
            self.symbol_imports[ctx.rel] = symbols

    # -- bindings -------------------------------------------------------- #

    @staticmethod
    def _lock_ctor(node: ast.AST) -> Optional[Tuple[str, str, bool]]:
        """(name, kind, raw) when ``node`` constructs a lock."""
        if not isinstance(node, ast.Call):
            return None
        parts = dotted_parts(node.func)
        if parts is None:
            return None
        leaf = parts[-1]
        if leaf in _FACTORIES:
            if (
                node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                return (node.args[0].value, _FACTORIES[leaf], False)
            return ("<dynamic>", _FACTORIES[leaf], False)
        if leaf in ("Lock", "RLock") and (
            len(parts) == 1 or parts[-2] == "threading"
        ):
            kind = "rlock" if leaf == "RLock" else "lock"
            return ("<anonymous>", kind, True)
        return None

    @staticmethod
    def _ctor_of(node: ast.AST, names: FrozenSet[str], modules) -> bool:
        """Is ``node`` a call to one of ``names`` (bare or via ``modules``)?"""
        if not isinstance(node, ast.Call):
            return False
        parts = dotted_parts(node.func)
        return bool(
            parts
            and parts[-1] in names
            and (len(parts) == 1 or parts[-2] in modules)
        )

    def _enclosing_class_scope(
        self, ctx: FileContext, node: ast.AST
    ) -> Optional[str]:
        cur = ctx.parent_of(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return ctx.scope_of(cur)
            cur = ctx.parent_of(cur)
        return None

    def _build_bindings(self) -> None:
        # pass 1: direct constructions
        deferred: List[Tuple[FileContext, ast.Assign]] = []
        for ctx in self.project.files:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Assign):
                    continue
                info = self._lock_ctor(node.value)
                if info is None:
                    if isinstance(node.value, (ast.Name, ast.Attribute)):
                        deferred.append((ctx, node))
                    self._note_resource_bindings(ctx, node)
                    continue
                self._bind_targets(ctx, node, info)
        # pass 2: alias assignments (X = other_lock / X = mod.LOCK) — two
        # sweeps so an alias-of-an-alias one file over still lands
        for _ in range(2):
            for ctx, node in deferred:
                info = self.resolve_lock_expr(ctx, node.value)
                if info is not None:
                    self._bind_targets(ctx, node, info)

    def _bind_targets(
        self,
        ctx: FileContext,
        node: ast.Assign,
        info: Tuple[str, str, bool],
    ) -> None:
        for target in node.targets:
            if isinstance(target, ast.Name):
                scope = ctx.scope_of(node)
                if scope == "<module>":
                    self.module_locks[(ctx.rel, target.id)] = info
                else:
                    cls = self._enclosing_class_scope(ctx, node)
                    if cls is not None and ctx.parent_of(node) is not None:
                        # class-body assignment (LOCK = named_lock(...))
                        parent = ctx.parent_of(node)
                        if isinstance(parent, ast.ClassDef):
                            self.attr_locks[
                                (ctx.rel, ctx.scope_of(parent), target.id)
                            ] = info
                            self.attr_global.setdefault(
                                target.id, set()
                            ).add(info)
                    # function-local lock bindings also resolve by name
                    self.module_locks[(ctx.rel, target.id)] = info
            elif (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                cls = self._enclosing_class_scope(ctx, node)
                if cls is not None:
                    self.attr_locks[(ctx.rel, cls, target.attr)] = info
                self.attr_global.setdefault(target.attr, set()).add(info)

    def _note_resource_bindings(
        self, ctx: FileContext, node: ast.Assign
    ) -> None:
        """Track Thread/Queue/socket constructions for blocking-receiver
        resolution (worker.join(), q.get(), sock.recv())."""
        value = node.value
        kind = None
        if self._ctor_of(value, frozenset({"Thread"}), ("threading",)):
            kind = self._thread_bindings
        elif self._ctor_of(
            value,
            frozenset({"Queue", "SimpleQueue", "LifoQueue"}),
            ("queue",),
        ):
            kind = self._queue_bindings
        elif self._ctor_of(value, frozenset({"socket"}), ("socket",)):
            kind = self._socket_bindings
        if kind is None:
            return
        scope = ctx.scope_of(node)
        for target in node.targets:
            if isinstance(target, ast.Name):
                kind.add((ctx.rel, scope, target.id))
                kind.add((ctx.rel, "*", target.id))
            elif (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                kind.add((ctx.rel, "*", target.attr))

    def _is_resource(
        self, ctx: FileContext, expr: ast.AST, bindings
    ) -> bool:
        if isinstance(expr, ast.Name):
            return (ctx.rel, "*", expr.id) in bindings
        if isinstance(expr, ast.Attribute):
            return (ctx.rel, "*", expr.attr) in bindings
        return False

    # -- expression resolution ------------------------------------------- #

    def resolve_lock_expr(
        self, ctx: FileContext, expr: ast.AST
    ) -> Optional[Tuple[str, str, bool]]:
        """(name, kind, raw) when ``expr`` denotes a known lock binding."""
        if isinstance(expr, ast.Name):
            hit = self.module_locks.get((ctx.rel, expr.id))
            if hit is not None:
                return hit
            imported = self.symbol_imports.get(ctx.rel, {}).get(expr.id)
            if imported is not None:
                return self.module_locks.get(imported)
            return None
        if isinstance(expr, ast.Attribute):
            receiver = expr.value
            if isinstance(receiver, ast.Name):
                if receiver.id in ("self", "cls"):
                    cls = self._enclosing_class_scope(ctx, expr)
                    if cls is not None:
                        hit = self.attr_locks.get((ctx.rel, cls, expr.attr))
                        if hit is not None:
                            return hit
                else:
                    target_rel = self.import_maps.get(ctx.rel, {}).get(
                        receiver.id
                    )
                    if target_rel is not None:
                        return self.module_locks.get(
                            (target_rel, expr.attr)
                        )
            # unique-attribute fallback: exactly one class anywhere binds
            # this attribute name to a lock
            candidates = self.attr_global.get(expr.attr, set())
            if len(candidates) == 1:
                return next(iter(candidates))
        return None

    # -- call graph + summaries ------------------------------------------ #

    def _build_defs(self) -> None:
        for ctx in self.project.files:
            for node in ast.walk(ctx.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    self._defs[(ctx.rel, ctx.scope_of(node))] = (ctx, node)

    def _resolve_call(
        self, ctx: FileContext, call: ast.Call
    ) -> Optional[Tuple[str, str]]:
        """The (rel, scope) key of the def a call targets, when resolvable."""
        func = call.func
        if isinstance(func, ast.Name):
            scope = ctx.scope_of(call)
            chain = [scope]
            while "." in scope:
                scope = scope.rsplit(".", 1)[0]
                chain.append(scope)
            chain.append("<module>")
            for s in chain:
                candidate = s + "." + func.id if s != "<module>" else func.id
                if (ctx.rel, candidate) in self._defs:
                    return (ctx.rel, candidate)
            imported = self.symbol_imports.get(ctx.rel, {}).get(func.id)
            if imported is not None and (imported[0], imported[1]) in self._defs:
                return (imported[0], imported[1])
            return None
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            if func.value.id in ("self", "cls"):
                cls = self._enclosing_class_scope(ctx, call)
                if cls is not None:
                    key = (ctx.rel, cls + "." + func.attr)
                    if key in self._defs:
                        return key
            else:
                target_rel = self.import_maps.get(ctx.rel, {}).get(
                    func.value.id
                )
                if target_rel is not None:
                    key = (target_rel, func.attr)
                    if key in self._defs:
                        return key
        return None

    def _blocking_op(
        self, ctx: FileContext, node: ast.AST
    ) -> Optional[Blocking]:
        """Classify ``node`` when it is a blocking call."""
        if not isinstance(node, ast.Call):
            return None
        parts = dotted_parts(node.func)
        if parts is None:
            return None
        leaf = parts[-1]
        if leaf == "sleep" and (len(parts) == 1 or parts[-2] == "time"):
            return Blocking("sleep", "time.sleep")
        if len(parts) >= 2 and parts[0] == "subprocess":
            if leaf in _SUBPROCESS_WAITS or leaf == "Popen":
                return Blocking("subprocess", f"subprocess.{leaf}")
        if len(parts) >= 2 and parts[0] == "pickle" and leaf in (
            "dumps",
            "dump",
            "loads",
            "load",
        ):
            # serializing arbitrarily large state is a CPU wall every
            # lock contender waits out (the views-exporter class)
            return Blocking("pickle", f"pickle.{leaf}")
        if leaf in _ENGINE_SEAM:
            return Blocking(
                "engine-seam", f"{leaf}() (device dispatch/materialization)"
            )
        if isinstance(node.func, ast.Attribute):
            receiver = node.func.value
            if leaf == "join" and self._is_resource(
                ctx, receiver, self._thread_bindings
            ):
                return Blocking("join", "Thread.join")
            if leaf in ("wait", "communicate") and self._is_resource(
                ctx, receiver, self._thread_bindings
            ):
                return Blocking("join", f"process.{leaf}")
            if leaf == "get" and self._is_resource(
                ctx, receiver, self._queue_bindings
            ):
                timeout = next(
                    (kw.value for kw in node.keywords if kw.arg == "timeout"),
                    None,
                )
                if timeout is None or (
                    isinstance(timeout, ast.Constant)
                    and timeout.value is None
                ):
                    return Blocking("queue-get", "queue.get() with no timeout")
            if leaf in _SOCKET_WAITS and (
                self._is_resource(ctx, receiver, self._socket_bindings)
                or (
                    isinstance(receiver, (ast.Name, ast.Attribute))
                    and "sock"
                    in (
                        receiver.id
                        if isinstance(receiver, ast.Name)
                        else receiver.attr
                    )
                )
            ):
                return Blocking("socket", f"socket.{leaf}")
        return None

    @staticmethod
    def _own_nodes(root: ast.AST, include_root: bool = False) -> Iterator[ast.AST]:
        """Walk without descending into nested function/class bodies (they
        run when called, not where defined)."""
        stack: List[ast.AST] = (
            [root] if include_root else list(ast.iter_child_nodes(root))
        )
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
            ):
                stack.extend(ast.iter_child_nodes(node))

    def _direct_effects(
        self, ctx: FileContext, nodes: Iterator[ast.AST]
    ) -> Tuple[Set[str], Set[Tuple[str, str]], List[Tuple[str, str]]]:
        """(lock names, blocking keys, callee keys) directly in ``nodes``."""
        locks: Set[str] = set()
        blocking: Set[Tuple[str, str]] = set()
        callees: List[Tuple[str, str]] = []
        for node in nodes:
            if isinstance(node, ast.With):
                for item in node.items:
                    info = self.resolve_lock_expr(ctx, item.context_expr)
                    if info is not None:
                        locks.add(info[0])
            elif isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    info = self.resolve_lock_expr(ctx, node.func.value)
                    if info is not None:
                        locks.add(info[0])
                        continue
                op = self._blocking_op(ctx, node)
                if op is not None:
                    blocking.add(op.key())
                    continue
                callee = self._resolve_call(ctx, node)
                if callee is not None:
                    callees.append(callee)
        return locks, blocking, callees

    def _summarize(self) -> None:
        direct: Dict[Tuple[str, str], Tuple[Set[str], Set, List]] = {}
        for key, (ctx, fn) in self._defs.items():
            direct[key] = self._direct_effects(ctx, self._own_nodes(fn))
        # fixpoint over the call graph (cycles converge because sets only
        # grow and the universe is finite)
        for key, (locks, blocking, _callees) in direct.items():
            self.fn_locks[key] = set(locks)
            self.fn_blocking[key] = set(blocking)
        changed = True
        while changed:
            changed = False
            for key, (_locks, _blocking, callees) in direct.items():
                for callee in callees:
                    if callee not in self.fn_locks:
                        continue
                    if not self.fn_locks[callee] <= self.fn_locks[key]:
                        self.fn_locks[key] |= self.fn_locks[callee]
                        changed = True
                    if not self.fn_blocking[callee] <= self.fn_blocking[key]:
                        self.fn_blocking[key] |= self.fn_blocking[callee]
                        changed = True

    # -- acquisition walk + edges ---------------------------------------- #

    def _walk_acquisitions(self) -> None:
        for ctx in self.project.files:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.With):
                    resolved_items: List[Tuple[str, str, bool]] = []
                    for item in node.items:
                        info = self.resolve_lock_expr(
                            ctx, item.context_expr
                        )
                        if info is not None:
                            # `with A, B:` acquires in item order
                            for prior in resolved_items:
                                if prior[0] != info[0]:
                                    self.edges.setdefault(
                                        (prior[0], info[0]), (ctx, node)
                                    )
                            resolved_items.append(info)
                            acq = Acquisition(
                                ctx, node, info[0], info[2], node.body
                            )
                            self.acquisitions.append(acq)
                            self._block_effects(acq, info)
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    info = self.resolve_lock_expr(ctx, node.func.value)
                    if info is not None:
                        self.acquisitions.append(
                            Acquisition(ctx, node, info[0], info[2], [])
                        )

    def _block_effects(
        self, acq: Acquisition, info: Tuple[str, str, bool]
    ) -> None:
        """Record ``held -> acquired`` edges and blocking-under-lock hits
        for one with-block: direct nested sites plus everything reachable
        through resolved calls in the block body."""
        ctx = acq.ctx
        held = acq.name
        for stmt in acq.body:
            for node in self._own_nodes(stmt, include_root=True):
                if isinstance(node, ast.With):
                    for item in node.items:
                        nested = self.resolve_lock_expr(
                            ctx, item.context_expr
                        )
                        if nested is not None and nested[0] != held:
                            self.edges.setdefault(
                                (held, nested[0]), (ctx, node)
                            )
                elif isinstance(node, ast.Call):
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "acquire"
                    ):
                        nested = self.resolve_lock_expr(
                            ctx, node.func.value
                        )
                        if nested is not None and nested[0] != held:
                            self.edges.setdefault(
                                (held, nested[0]), (ctx, node)
                            )
                            continue
                    op = self._blocking_op(ctx, node)
                    if op is not None:
                        self.blocking_findings.append(
                            (ctx, node, held, op, "")
                        )
                        continue
                    callee = self._resolve_call(ctx, node)
                    if callee is not None:
                        via = callee[1]
                        for name in self.fn_locks.get(callee, ()):
                            if name != held:
                                self.edges.setdefault(
                                    (held, name), (ctx, node)
                                )
                        for kind, detail in sorted(
                            self.fn_blocking.get(callee, ())
                        ):
                            self.blocking_findings.append(
                                (ctx, node, held, Blocking(kind, detail), via)
                            )


def get_analysis(project: Project) -> LockAnalysis:
    """The per-run LockAnalysis, built once and cached on the Project."""
    cached = getattr(project, "_lock_analysis", None)
    if cached is None:
        cached = LockAnalysis(project)
        project._lock_analysis = cached
    return cached
