"""REGISTRY-DRIFT: metrics, spans, and env vars must be declared/documented.

Three quiet ways observability rots:

1. **metrics** — an ``emit_metric("some.new.counter", 1)`` call ships
   without anyone updating dashboards or docs; months later nobody knows
   what feeds it.  Every emitted metric name (f-string placeholders become
   ``*`` wildcards) must match a pattern declared in ``METRICS`` in
   ``modin_tpu/logging/metrics.py``, every declared pattern must have a
   live emit site, and each pattern's stable dotted prefix must appear in
   ``docs/``.  graftmeter adds the **kind** leg: every ``METRICS`` entry
   must declare a valid meter kind (``counter`` / ``gauge`` /
   ``histogram``) in position 1, and the histogram declarations are
   cross-checked both ways against ``HISTOGRAM_BUCKETS`` in
   ``modin_tpu/observability/meters.py`` — a histogram family without a
   bucket spec would silently aggregate as a counter, and a bucket spec
   without a histogram family is dead configuration.

2. **spans** — graftscope's statically-named span emissions
   (``graftscope.span("...")`` / ``graftscope.start_span("...")``) are held
   to the same contract against the ``SPANS`` registry in
   ``modin_tpu/observability/spans.py``: undeclared span name, dead
   registry pattern, or undocumented family all fail.  Runtime-built names
   go through ``layer_span`` and are exempt (they are covered by the
   layer-tag taxonomy, not the registry).

3. **env vars** — a ``MODIN_TPU_*`` variable read via raw ``os.environ``
   bypasses ``config/envvars.py`` entirely: no default, no type checking,
   no ``_check_vars`` typo warning, no docs.  Every ``MODIN_TPU_*`` literal
   in the package must be a declared ``varname`` in ``config/envvars.py``,
   and every declared varname must be mentioned in ``docs/``.

4. **locks** — graftdep's ``LOCKS`` registry
   (``concurrency/registry.py``) is cross-checked against the actual
   ``named_lock``/``named_rlock`` construction sites both ways: a
   construction whose literal name is undeclared (would raise at import
   time — caught at lint time instead), a declared name no site
   constructs (dead declaration the order table keeps ordering), and a
   kind mismatch (``named_lock`` for an ``"rlock"`` declaration or vice
   versa).  A raw ``threading.Lock()``/``RLock()`` construction outside
   ``concurrency/`` is flagged too — even one never acquired in-tree
   (which LOCK-ORDER would miss) is invisible to lockdep.  Every
   declared lock name must appear in ``docs/``.

Docstrings are exempt from the literal scan (prose references a knob by
name legitimately); docs checks are skipped when the scanned tree has no
``docs/`` directory (snippet unit tests, vendored subsets).
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import is_docstring

METRICS_SUFFIX = "logging/metrics.py"
SPANS_SUFFIX = "observability/spans.py"
METERS_SUFFIX = "observability/meters.py"
ENVVARS_SUFFIX = "config/envvars.py"
LOCKS_SUFFIX = "concurrency/registry.py"
METRIC_REGISTRY_NAME = "METRICS"
SPAN_REGISTRY_NAME = "SPANS"
BUCKETS_NAME = "HISTOGRAM_BUCKETS"
LOCK_REGISTRY_NAME = "LOCKS"

#: lock factory name -> the kind its declaration must carry
LOCK_FACTORIES = {"named_lock": "lock", "named_rlock": "rlock"}

#: meter kinds graftmeter can aggregate (meters.VALID_KINDS, restated here
#: so the lint tree does not import runtime modules)
VALID_METER_KINDS = frozenset({"counter", "gauge", "histogram"})

#: function names whose first string argument is a registry-checked span
#: name (the dynamic-name emitter ``layer_span`` is deliberately absent)
SPAN_EMITTER_NAMES = frozenset({"span", "start_span"})

#: MODIN_TPU_* env var literal; the lookbehind keeps internal tokens like
#: ``__MODIN_TPU_BT_0__`` (eval.py backtick mangling) out of the scan
ENVVAR_RE = re.compile(r"(?<![A-Za-z0-9_])MODIN_TPU_[A-Z0-9_]+")


def _metric_name_pattern(arg: ast.AST) -> Optional[str]:
    """The emitted metric name with f-string placeholders as ``*``."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        out: List[str] = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
                out.append(piece.value)
            else:
                out.append("*")
        return "".join(out)
    return None  # dynamically built name: can't check statically


def _registry_entries(
    ctx: FileContext, registry_name: str
) -> Optional[List[ast.expr]]:
    """The entry nodes of ``<NAME> = ((...), ...)`` — one walk shared by the
    name and kind legs, so a change to how the registry is declared cannot
    fix one leg and silently blind the other.  None when the file has no
    such assignment."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == registry_name
            for t in node.targets
        ):
            value = node.value
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == registry_name
            and node.value is not None
        ):
            value = node.value
        else:
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            return list(value.elts)
        return []
    return None


def _entry_pattern(entry: ast.expr) -> Optional[Tuple[str, int]]:
    """``(pattern, lineno)`` when the entry is a tuple/list whose position 0
    is a string constant; None for any other shape."""
    if (
        isinstance(entry, (ast.Tuple, ast.List))
        and entry.elts
        and isinstance(entry.elts[0], ast.Constant)
        and isinstance(entry.elts[0].value, str)
    ):
        return entry.elts[0].value, entry.lineno
    return None


def _declared_patterns(
    ctx: FileContext, registry_name: str
) -> Optional[Dict[str, int]]:
    """{pattern: lineno} from ``<NAME> = (("pattern", "why"), ...)``."""
    entries = _registry_entries(ctx, registry_name)
    if entries is None:
        return None
    patterns: Dict[str, int] = {}
    for entry in entries:
        named = _entry_pattern(entry)
        if named is not None:
            patterns[named[0]] = named[1]
    return patterns


def _declared_kinds(ctx: FileContext) -> Dict[str, Tuple[Optional[str], int]]:
    """{pattern: (declared kind or None, lineno)} from the METRICS registry.

    The kind is entry position 1 — ``("pattern", "kind", "description")``.
    A 2-tuple entry (the pre-graftmeter shape) or a non-constant kind maps
    to None, which the kind check flags.
    """
    out: Dict[str, Tuple[Optional[str], int]] = {}
    for entry in _registry_entries(ctx, METRIC_REGISTRY_NAME) or ():
        named = _entry_pattern(entry)
        if named is None:
            continue
        kind: Optional[str] = None
        if (
            len(entry.elts) >= 3
            and isinstance(entry.elts[1], ast.Constant)
            and isinstance(entry.elts[1].value, str)
        ):
            kind = entry.elts[1].value
        out[named[0]] = (kind, named[1])
    return out


def _declared_buckets(ctx: FileContext) -> Optional[Dict[str, int]]:
    """{pattern: lineno} from the ``HISTOGRAM_BUCKETS`` dict literal in
    observability/meters.py (plain or annotated assignment)."""
    for node in ctx.tree.body:
        target = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == BUCKETS_NAME:
                    target = node.value
        elif isinstance(node, ast.AnnAssign):
            if (
                isinstance(node.target, ast.Name)
                and node.target.id == BUCKETS_NAME
            ):
                target = node.value
        if target is None:
            continue
        if isinstance(target, ast.Dict):
            return {
                key.value: key.lineno
                for key in target.keys
                if isinstance(key, ast.Constant) and isinstance(key.value, str)
            }
    return None


def _declared_envvars(ctx: FileContext) -> Dict[str, int]:
    """{varname: lineno} from ``varname = "MODIN_TPU_X"`` class attributes."""
    out: Dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "varname"
                for t in node.targets
            )
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.value.value] = node.lineno
    return out


def _doc_mention_key(pattern: str) -> str:
    """The stable dotted prefix of a metric pattern that docs must mention.

    ``resilience.engine.*.*`` -> ``resilience.engine``; a fully static name
    is its own key.
    """
    parts = pattern.split(".")
    stable: List[str] = []
    for part in parts:
        if "*" in part:
            break
        stable.append(part)
    return ".".join(stable) if stable else pattern


@register_rule
class RegistryDriftRule(Rule):
    id = "REGISTRY-DRIFT"
    description = (
        "every emit_metric name must match the METRICS registry (with a "
        "valid meter kind, histogram families cross-checked against "
        "HISTOGRAM_BUCKETS both ways), every graftscope span/start_span "
        "name must match the SPANS registry, every MODIN_TPU_* env var "
        "must be declared in config/envvars.py, and every "
        "named_lock/named_rlock site must match the LOCKS registry "
        "(both ways, kinds included, no raw threading.Lock outside "
        "concurrency/); all must be mentioned in docs/"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        yield from self._check_name_registry(
            project,
            suffix=METRICS_SUFFIX,
            registry_name=METRIC_REGISTRY_NAME,
            kind="metric",
            emit_desc="emit_metric",
            is_emitter=self._is_metric_emitter,
        )
        yield from self._check_metric_kinds(project)
        yield from self._check_name_registry(
            project,
            suffix=SPANS_SUFFIX,
            registry_name=SPAN_REGISTRY_NAME,
            kind="span",
            emit_desc="span/start_span",
            is_emitter=self._is_span_emitter,
        )
        yield from self._check_envvars(project)
        yield from self._check_locks(project)

    # -- named-emission registries (metrics, spans) ---------------------- #

    @staticmethod
    def _is_metric_emitter(node: ast.Call) -> bool:
        return isinstance(node.func, ast.Name) and node.func.id == "emit_metric"

    @staticmethod
    def _is_span_emitter(node: ast.Call) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id in SPAN_EMITTER_NAMES
        if isinstance(func, ast.Attribute):
            return func.attr in SPAN_EMITTER_NAMES
        return False

    def _check_name_registry(
        self,
        project: Project,
        suffix: str,
        registry_name: str,
        kind: str,
        emit_desc: str,
        is_emitter,
    ) -> Iterator[Finding]:
        registry: Optional[Dict[str, int]] = None
        registry_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(suffix):
            registry = _declared_patterns(ctx, registry_name)
            registry_ctx = ctx
            if registry is not None:
                break

        emitted: List[Tuple[FileContext, ast.Call, str]] = []
        for ctx in project.files:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and is_emitter(node) and node.args:
                    name = _metric_name_pattern(node.args[0])
                    if name is not None:
                        emitted.append((ctx, node, name))

        if registry is None:
            if registry_ctx is not None and emitted:
                yield Finding(
                    path=registry_ctx.rel,
                    line=1,
                    rule=self.id,
                    message=f"no {registry_name} registry found in "
                    f"the {kind}s module",
                    fix_hint=f'declare {registry_name} = (("pattern", '
                    '"description"), ...) covering every emitted name',
                    symbol=f"no-{kind}-registry",
                )
            return

        matched_patterns: Set[str] = set()
        for ctx, node, name in emitted:
            hits = [p for p in registry if fnmatch.fnmatchcase(name, p)]
            if hits:
                matched_patterns.update(hits)
                continue
            yield Finding(
                path=ctx.rel,
                line=node.lineno,
                rule=self.id,
                message=f"{kind} '{name}' matches no pattern in "
                f"{registry_name} ({suffix})",
                fix_hint=f"declare the {kind} (pattern, description) in the "
                "registry and document it",
                scope=ctx.scope_of(node),
                symbol=f"undeclared-{kind}-{name}",
            )

        docs = project.docs_text() if project.has_docs() else None
        for pattern, lineno in sorted(registry.items()):
            if pattern not in matched_patterns:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"{kind} pattern '{pattern}' is declared but no "
                    f"{emit_desc} call matches it",
                    fix_hint="remove the dead registry entry or restore the "
                    "emit site",
                    symbol=f"dead-{kind}-{pattern}",
                )
            if docs is not None and _doc_mention_key(pattern) not in docs:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"{kind} '{pattern}' (prefix "
                    f"'{_doc_mention_key(pattern)}') is not mentioned in "
                    "docs/",
                    fix_hint=f"document the {kind} family "
                    "(docs/configuration.md and docs/observability.md hold "
                    "the catalogs)",
                    symbol=f"undocumented-{kind}-{pattern}",
                )

    # -- meter kinds (graftmeter) ---------------------------------------- #

    def _check_metric_kinds(self, project: Project) -> Iterator[Finding]:
        """Every METRICS entry declares a valid meter kind; histogram
        declarations and HISTOGRAM_BUCKETS specs match one-to-one."""
        kinds: Optional[Dict[str, Tuple[Optional[str], int]]] = None
        registry_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(METRICS_SUFFIX):
            kinds = _declared_kinds(ctx)
            registry_ctx = ctx
            if kinds:
                break
        if not kinds:
            return  # no METRICS registry in this tree: nothing to check

        for pattern, (kind, lineno) in sorted(kinds.items()):
            if kind not in VALID_METER_KINDS:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"metric '{pattern}' declares "
                    + (
                        f"invalid meter kind {kind!r}"
                        if kind is not None
                        else "no meter kind"
                    )
                    + " (position 1 must be counter/gauge/histogram)",
                    fix_hint="declare the entry as (pattern, kind, "
                    "description) with a kind graftmeter can aggregate",
                    symbol=f"metric-kind-{pattern}",
                )

        buckets: Optional[Dict[str, int]] = None
        buckets_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(METERS_SUFFIX):
            buckets = _declared_buckets(ctx)
            buckets_ctx = ctx
            if buckets is not None:
                break
        if buckets is None:
            return  # meters module absent (snippet trees): skip bucket legs

        for pattern, (kind, lineno) in sorted(kinds.items()):
            if kind == "histogram" and pattern not in buckets:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"histogram metric '{pattern}' has no bucket "
                    f"spec in {METERS_SUFFIX}:{BUCKETS_NAME} (it would "
                    "silently degrade to a counter)",
                    fix_hint="add fixed bucket bounds for the family to "
                    f"{BUCKETS_NAME}",
                    symbol=f"histogram-without-buckets-{pattern}",
                )
        for pattern, lineno in sorted(buckets.items()):
            declared = kinds.get(pattern)
            if declared is None or declared[0] != "histogram":
                yield Finding(
                    path=buckets_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"{BUCKETS_NAME} declares buckets for "
                    f"'{pattern}' but METRICS does not declare it as a "
                    "histogram",
                    fix_hint="remove the dead bucket spec or declare the "
                    "family with kind 'histogram' in METRICS",
                    symbol=f"buckets-without-histogram-{pattern}",
                )

    # -- env vars ------------------------------------------------------- #

    def _check_envvars(self, project: Project) -> Iterator[Finding]:
        declared: Optional[Dict[str, int]] = None
        envvars_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(ENVVARS_SUFFIX):
            declared = _declared_envvars(ctx)
            envvars_ctx = ctx
            break
        if declared is None:
            return  # no envvars module in this tree: nothing to check against

        for ctx in project.files:
            if ctx is envvars_ctx:
                continue
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Constant) and isinstance(node.value, str)
                ):
                    continue
                if is_docstring(ctx.parents, node):
                    continue
                for var in ENVVAR_RE.findall(node.value):
                    if var not in declared:
                        yield Finding(
                            path=ctx.rel,
                            line=node.lineno,
                            rule=self.id,
                            message=f"env var '{var}' is read/written but "
                            f"not declared in {ENVVARS_SUFFIX}",
                            fix_hint="add an EnvironmentVariable subclass "
                            "with this varname (default, type, docstring) "
                            "and read it through the config layer",
                            scope=ctx.scope_of(node),
                            symbol=f"undeclared-envvar-{var}",
                        )

        if project.has_docs():
            docs = project.docs_text()
            for var, lineno in sorted(declared.items()):
                if var not in docs:
                    yield Finding(
                        path=envvars_ctx.rel,
                        line=lineno,
                        rule=self.id,
                        message=f"declared env var '{var}' is not mentioned "
                        "in docs/",
                        fix_hint="add it to the configuration reference "
                        "(docs/configuration.md)",
                        symbol=f"undocumented-envvar-{var}",
                    )

    # -- locks (graftdep) ------------------------------------------------ #

    def _check_locks(self, project: Project) -> Iterator[Finding]:
        """The LOCKS registry vs the named_lock/named_rlock construction
        sites, both ways, plus the no-raw-locks-outside-concurrency leg."""
        declared: Optional[Dict[str, Tuple[Optional[str], int]]] = None
        registry_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(LOCKS_SUFFIX):
            declared = self._declared_locks(ctx)
            registry_ctx = ctx
            break
        if declared is None:
            return  # no lock registry in this tree: nothing to check against

        constructed: Set[str] = set()
        for ctx in project.files:
            in_concurrency = "concurrency/" in ctx.rel or ctx.rel.startswith(
                "concurrency"
            )
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                leaf = (
                    func.id
                    if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute) else None
                )
                if leaf in LOCK_FACTORIES:
                    if not (
                        node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                    ):
                        continue  # forwarding wrapper (e.g. the factory itself)
                    name = node.args[0].value
                    entry = declared.get(name)
                    if entry is None:
                        yield Finding(
                            path=ctx.rel,
                            line=node.lineno,
                            rule=self.id,
                            message=f"{leaf}({name!r}) constructs a lock not "
                            f"declared in {LOCK_REGISTRY_NAME} "
                            f"({LOCKS_SUFFIX}) — named_lock will raise at "
                            "import time",
                            fix_hint="declare (name, kind, what-it-guards) "
                            "in the LOCKS registry",
                            scope=ctx.scope_of(node),
                            symbol=f"undeclared-lock-{name}",
                        )
                    elif entry[0] != LOCK_FACTORIES[leaf]:
                        yield Finding(
                            path=ctx.rel,
                            line=node.lineno,
                            rule=self.id,
                            message=f"{leaf}({name!r}) contradicts the "
                            f"declared kind {entry[0]!r} — reentrancy "
                            "intent is declared data, not a site-local "
                            "choice",
                            fix_hint="use the factory matching the "
                            "declaration, or change the declaration "
                            "deliberately",
                            scope=ctx.scope_of(node),
                            symbol=f"lock-kind-{name}",
                        )
                    constructed.add(name)
                elif (
                    leaf in ("Lock", "RLock")
                    and isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "threading"
                    and not in_concurrency
                ):
                    yield Finding(
                        path=ctx.rel,
                        line=node.lineno,
                        rule=self.id,
                        message=f"raw threading.{leaf}() outside "
                        "concurrency/ — invisible to the LOCKS registry, "
                        "the declared order, and the lockdep validator "
                        "even if nothing in-tree acquires it yet",
                        fix_hint="declare it in LOCKS and construct it "
                        "with named_lock()/named_rlock()",
                        scope=ctx.scope_of(node),
                        symbol=f"raw-lock-{leaf}",
                    )

        docs = project.docs_text() if project.has_docs() else None
        for name, (kind, lineno) in sorted(declared.items()):
            if name not in constructed:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"lock '{name}' is declared in "
                    f"{LOCK_REGISTRY_NAME} but no "
                    "named_lock/named_rlock site constructs it",
                    fix_hint="remove the dead declaration (and its "
                    "LOCK_ORDER edges) or restore the construction site",
                    symbol=f"dead-lock-{name}",
                )
            if docs is not None and name not in docs:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"lock '{name}' is not mentioned in docs/",
                    fix_hint="add it to the lock-ordering table in "
                    "docs/architecture.md",
                    symbol=f"undocumented-lock-{name}",
                )

    @staticmethod
    def _declared_locks(
        ctx: FileContext,
    ) -> Optional[Dict[str, Tuple[Optional[str], int]]]:
        """{name: (kind, lineno)} from ``LOCKS = ((name, kind, desc), ...)``."""
        entries = _registry_entries(ctx, LOCK_REGISTRY_NAME)
        if entries is None:
            return None
        out: Dict[str, Tuple[Optional[str], int]] = {}
        for entry in entries:
            named = _entry_pattern(entry)
            if named is None:
                continue
            kind: Optional[str] = None
            if (
                len(entry.elts) >= 2
                and isinstance(entry.elts[1], ast.Constant)
                and isinstance(entry.elts[1].value, str)
            ):
                kind = entry.elts[1].value
            out[named[0]] = (kind, named[1])
        return out
