"""REGISTRY-DRIFT: metrics, spans, and env vars must be declared/documented.

Three quiet ways observability rots:

1. **metrics** — an ``emit_metric("some.new.counter", 1)`` call ships
   without anyone updating dashboards or docs; months later nobody knows
   what feeds it.  Every emitted metric name (f-string placeholders become
   ``*`` wildcards) must match a pattern declared in ``METRICS`` in
   ``modin_tpu/logging/metrics.py``, every declared pattern must have a
   live emit site, and each pattern's stable dotted prefix must appear in
   ``docs/``.

2. **spans** — graftscope's statically-named span emissions
   (``graftscope.span("...")`` / ``graftscope.start_span("...")``) are held
   to the same contract against the ``SPANS`` registry in
   ``modin_tpu/observability/spans.py``: undeclared span name, dead
   registry pattern, or undocumented family all fail.  Runtime-built names
   go through ``layer_span`` and are exempt (they are covered by the
   layer-tag taxonomy, not the registry).

3. **env vars** — a ``MODIN_TPU_*`` variable read via raw ``os.environ``
   bypasses ``config/envvars.py`` entirely: no default, no type checking,
   no ``_check_vars`` typo warning, no docs.  Every ``MODIN_TPU_*`` literal
   in the package must be a declared ``varname`` in ``config/envvars.py``,
   and every declared varname must be mentioned in ``docs/``.

Docstrings are exempt from the literal scan (prose references a knob by
name legitimately); docs checks are skipped when the scanned tree has no
``docs/`` directory (snippet unit tests, vendored subsets).
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import is_docstring

METRICS_SUFFIX = "logging/metrics.py"
SPANS_SUFFIX = "observability/spans.py"
ENVVARS_SUFFIX = "config/envvars.py"
METRIC_REGISTRY_NAME = "METRICS"
SPAN_REGISTRY_NAME = "SPANS"

#: function names whose first string argument is a registry-checked span
#: name (the dynamic-name emitter ``layer_span`` is deliberately absent)
SPAN_EMITTER_NAMES = frozenset({"span", "start_span"})

#: MODIN_TPU_* env var literal; the lookbehind keeps internal tokens like
#: ``__MODIN_TPU_BT_0__`` (eval.py backtick mangling) out of the scan
ENVVAR_RE = re.compile(r"(?<![A-Za-z0-9_])MODIN_TPU_[A-Z0-9_]+")


def _metric_name_pattern(arg: ast.AST) -> Optional[str]:
    """The emitted metric name with f-string placeholders as ``*``."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        out: List[str] = []
        for piece in arg.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
                out.append(piece.value)
            else:
                out.append("*")
        return "".join(out)
    return None  # dynamically built name: can't check statically


def _declared_patterns(
    ctx: FileContext, registry_name: str
) -> Optional[Dict[str, int]]:
    """{pattern: lineno} from ``<NAME> = (("pattern", "why"), ...)``."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == registry_name
            for t in node.targets
        ):
            patterns: Dict[str, int] = {}
            value = node.value
            if isinstance(value, (ast.Tuple, ast.List)):
                for entry in value.elts:
                    if (
                        isinstance(entry, (ast.Tuple, ast.List))
                        and entry.elts
                        and isinstance(entry.elts[0], ast.Constant)
                        and isinstance(entry.elts[0].value, str)
                    ):
                        patterns[entry.elts[0].value] = entry.lineno
            return patterns
    return None


def _declared_envvars(ctx: FileContext) -> Dict[str, int]:
    """{varname: lineno} from ``varname = "MODIN_TPU_X"`` class attributes."""
    out: Dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "varname"
                for t in node.targets
            )
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.value.value] = node.lineno
    return out


def _doc_mention_key(pattern: str) -> str:
    """The stable dotted prefix of a metric pattern that docs must mention.

    ``resilience.engine.*.*`` -> ``resilience.engine``; a fully static name
    is its own key.
    """
    parts = pattern.split(".")
    stable: List[str] = []
    for part in parts:
        if "*" in part:
            break
        stable.append(part)
    return ".".join(stable) if stable else pattern


@register_rule
class RegistryDriftRule(Rule):
    id = "REGISTRY-DRIFT"
    description = (
        "every emit_metric name must match the METRICS registry, every "
        "graftscope span/start_span name must match the SPANS registry, "
        "and every MODIN_TPU_* env var must be declared in "
        "config/envvars.py; all must be mentioned in docs/"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        yield from self._check_name_registry(
            project,
            suffix=METRICS_SUFFIX,
            registry_name=METRIC_REGISTRY_NAME,
            kind="metric",
            emit_desc="emit_metric",
            is_emitter=self._is_metric_emitter,
        )
        yield from self._check_name_registry(
            project,
            suffix=SPANS_SUFFIX,
            registry_name=SPAN_REGISTRY_NAME,
            kind="span",
            emit_desc="span/start_span",
            is_emitter=self._is_span_emitter,
        )
        yield from self._check_envvars(project)

    # -- named-emission registries (metrics, spans) ---------------------- #

    @staticmethod
    def _is_metric_emitter(node: ast.Call) -> bool:
        return isinstance(node.func, ast.Name) and node.func.id == "emit_metric"

    @staticmethod
    def _is_span_emitter(node: ast.Call) -> bool:
        func = node.func
        if isinstance(func, ast.Name):
            return func.id in SPAN_EMITTER_NAMES
        if isinstance(func, ast.Attribute):
            return func.attr in SPAN_EMITTER_NAMES
        return False

    def _check_name_registry(
        self,
        project: Project,
        suffix: str,
        registry_name: str,
        kind: str,
        emit_desc: str,
        is_emitter,
    ) -> Iterator[Finding]:
        registry: Optional[Dict[str, int]] = None
        registry_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(suffix):
            registry = _declared_patterns(ctx, registry_name)
            registry_ctx = ctx
            if registry is not None:
                break

        emitted: List[Tuple[FileContext, ast.Call, str]] = []
        for ctx in project.files:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and is_emitter(node) and node.args:
                    name = _metric_name_pattern(node.args[0])
                    if name is not None:
                        emitted.append((ctx, node, name))

        if registry is None:
            if registry_ctx is not None and emitted:
                yield Finding(
                    path=registry_ctx.rel,
                    line=1,
                    rule=self.id,
                    message=f"no {registry_name} registry found in "
                    f"the {kind}s module",
                    fix_hint=f'declare {registry_name} = (("pattern", '
                    '"description"), ...) covering every emitted name',
                    symbol=f"no-{kind}-registry",
                )
            return

        matched_patterns: Set[str] = set()
        for ctx, node, name in emitted:
            hits = [p for p in registry if fnmatch.fnmatchcase(name, p)]
            if hits:
                matched_patterns.update(hits)
                continue
            yield Finding(
                path=ctx.rel,
                line=node.lineno,
                rule=self.id,
                message=f"{kind} '{name}' matches no pattern in "
                f"{registry_name} ({suffix})",
                fix_hint=f"declare the {kind} (pattern, description) in the "
                "registry and document it",
                scope=ctx.scope_of(node),
                symbol=f"undeclared-{kind}-{name}",
            )

        docs = project.docs_text() if project.has_docs() else None
        for pattern, lineno in sorted(registry.items()):
            if pattern not in matched_patterns:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"{kind} pattern '{pattern}' is declared but no "
                    f"{emit_desc} call matches it",
                    fix_hint="remove the dead registry entry or restore the "
                    "emit site",
                    symbol=f"dead-{kind}-{pattern}",
                )
            if docs is not None and _doc_mention_key(pattern) not in docs:
                yield Finding(
                    path=registry_ctx.rel,
                    line=lineno,
                    rule=self.id,
                    message=f"{kind} '{pattern}' (prefix "
                    f"'{_doc_mention_key(pattern)}') is not mentioned in "
                    "docs/",
                    fix_hint=f"document the {kind} family "
                    "(docs/configuration.md and docs/observability.md hold "
                    "the catalogs)",
                    symbol=f"undocumented-{kind}-{pattern}",
                )

    # -- env vars ------------------------------------------------------- #

    def _check_envvars(self, project: Project) -> Iterator[Finding]:
        declared: Optional[Dict[str, int]] = None
        envvars_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(ENVVARS_SUFFIX):
            declared = _declared_envvars(ctx)
            envvars_ctx = ctx
            break
        if declared is None:
            return  # no envvars module in this tree: nothing to check against

        for ctx in project.files:
            if ctx is envvars_ctx:
                continue
            for node in ast.walk(ctx.tree):
                if not (
                    isinstance(node, ast.Constant) and isinstance(node.value, str)
                ):
                    continue
                if is_docstring(ctx.parents, node):
                    continue
                for var in ENVVAR_RE.findall(node.value):
                    if var not in declared:
                        yield Finding(
                            path=ctx.rel,
                            line=node.lineno,
                            rule=self.id,
                            message=f"env var '{var}' is read/written but "
                            f"not declared in {ENVVARS_SUFFIX}",
                            fix_hint="add an EnvironmentVariable subclass "
                            "with this varname (default, type, docstring) "
                            "and read it through the config layer",
                            scope=ctx.scope_of(node),
                            symbol=f"undeclared-envvar-{var}",
                        )

        if project.has_docs():
            docs = project.docs_text()
            for var, lineno in sorted(declared.items()):
                if var not in docs:
                    yield Finding(
                        path=envvars_ctx.rel,
                        line=lineno,
                        rule=self.id,
                        message=f"declared env var '{var}' is not mentioned "
                        "in docs/",
                        fix_hint="add it to the configuration reference "
                        "(docs/configuration.md)",
                        symbol=f"undocumented-envvar-{var}",
                    )
