"""FALLBACK-PARITY: every ``_try_*`` device path degrades, never crashes.

The PR-1 invariant: a ``_try_*`` method in the TPU query compiler is an
*optimized attempt*, not an obligation.  Its contract is (a) return a result,
or (b) return None meaning "use the pandas fallback" — and the resilience
layer adds leg (c): when the breaker for its family is open, None comes back
without touching the device.  Three things must therefore hold:

1. every ``_try_*`` method carries ``@device_path("<family>")`` so it owns a
   named circuit breaker (an unguarded ``_try_*`` crashes on device failure
   instead of striking a breaker and falling back);
2. the family name is declared in ``DEVICE_PATH_FAMILIES`` in
   ``core/execution/resilience.py`` (the registry the docs, metrics, and
   operators key off), and every declared family is actually used — drift in
   either direction is flagged;
3. every call site reaches a pandas fallback: the caller must None-check the
   result in the same function (or itself be a ``_try_*``/forwarder whose
   *own* callers check) — otherwise a breaker-open short-circuit returns
   None straight to user code.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import dotted_parts

RESILIENCE_SUFFIX = "core/execution/resilience.py"
FAMILY_REGISTRY_NAME = "DEVICE_PATH_FAMILIES"


def _device_path_family(fn: ast.FunctionDef) -> Optional[str]:
    """The family string from a @device_path("...") decorator, if any."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            parts = dotted_parts(dec.func)
            if parts and parts[-1] == "device_path" and dec.args:
                arg = dec.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    return arg.value
                return "<dynamic>"
    return None


def _registry_families(ctx: FileContext) -> Optional[Set[str]]:
    """Strings in ``DEVICE_PATH_FAMILIES = frozenset({...})`` (None if absent)."""
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            names = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if FAMILY_REGISTRY_NAME in names:
                return {
                    c.value
                    for c in ast.walk(node.value)
                    if isinstance(c, ast.Constant) and isinstance(c.value, str)
                }
    return None


def _none_checked_names(fn: ast.FunctionDef) -> Set[str]:
    """Names compared against None anywhere in the function."""
    checked: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
        ):
            for side in [node.left, *node.comparators]:
                if isinstance(side, ast.Name):
                    checked.add(side.id)
    return checked


@register_rule
class FallbackParityRule(Rule):
    id = "FALLBACK-PARITY"
    description = (
        "_try_* device paths need @device_path with a family declared in "
        "DEVICE_PATH_FAMILIES, and every call site must reach the pandas "
        "fallback via a None check"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        registry: Optional[Set[str]] = None
        registry_ctx: Optional[FileContext] = None
        for ctx in project.files_matching(RESILIENCE_SUFFIX):
            registry = _registry_families(ctx)
            registry_ctx = ctx
            if registry is not None:
                break

        used_families: Set[str] = set()
        for ctx in project.files:
            if "query_compiler" not in ctx.rel:
                continue
            yield from self._check_compiler_file(ctx, registry, used_families)

        # declared-but-unused families are drift too (a renamed family keeps
        # its dead registry entry and the docs/operators key off a ghost)
        if registry is not None and registry_ctx is not None and used_families:
            for family in sorted(registry - used_families):
                yield Finding(
                    path=registry_ctx.rel,
                    line=self._registry_line(registry_ctx),
                    rule=self.id,
                    message=f"family '{family}' is declared in "
                    f"{FAMILY_REGISTRY_NAME} but no _try_* method uses it",
                    fix_hint="remove the dead entry or restore the "
                    "@device_path usage",
                    scope="<module>",
                    symbol=f"unused-family-{family}",
                )

    def _registry_line(self, ctx: FileContext) -> int:
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == FAMILY_REGISTRY_NAME
                for t in node.targets
            ):
                return node.lineno
        return 1

    def _check_compiler_file(
        self,
        ctx: FileContext,
        registry: Optional[Set[str]],
        used_families: Set[str],
    ) -> Iterator[Finding]:
        # collect methods per class: _try_* defs and their decorators
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods: Dict[str, ast.FunctionDef] = {
                n.name: n for n in cls.body if isinstance(n, ast.FunctionDef)
            }
            try_methods = {
                name: fn for name, fn in methods.items() if name.startswith("_try_")
            }
            if not try_methods:
                continue

            # 1+2: decorator present, family registered
            for name, fn in sorted(try_methods.items()):
                family = _device_path_family(fn)
                if family is None:
                    yield Finding(
                        path=ctx.rel,
                        line=fn.lineno,
                        rule=self.id,
                        message=f"{cls.name}.{name} has no @device_path "
                        "decorator — no circuit breaker guards this device "
                        "path",
                        fix_hint='decorate with @device_path("<family>") and '
                        f"declare the family in {FAMILY_REGISTRY_NAME}",
                        scope=ctx.scope_of(fn),
                        symbol=f"undec-{name}",
                    )
                else:
                    used_families.add(family)
                    if registry is not None and family not in registry:
                        yield Finding(
                            path=ctx.rel,
                            line=fn.lineno,
                            rule=self.id,
                            message=f"{cls.name}.{name} uses breaker family "
                            f"'{family}' which is not declared in "
                            f"{FAMILY_REGISTRY_NAME} "
                            f"(core/execution/resilience.py)",
                            fix_hint="add the family to the registry so "
                            "operators/docs/metrics can enumerate it",
                            scope=ctx.scope_of(fn),
                            symbol=f"unregistered-{name}",
                        )

            # 3: every call site None-checks (or forwards to one that does).
            # Forwarders: methods that `return self._try_x(...)` directly may
            # propagate None to *their* callers, which must then check.
            propagators = set(try_methods)
            changed = True
            while changed:
                changed = False
                for name, fn in methods.items():
                    if name in propagators:
                        continue
                    for node in ast.walk(fn):
                        if (
                            isinstance(node, ast.Return)
                            and isinstance(node.value, ast.Call)
                            and isinstance(node.value.func, ast.Attribute)
                            and node.value.func.attr in propagators
                        ):
                            propagators.add(name)
                            changed = True
                            break

            for name, fn in sorted(methods.items()):
                checked = _none_checked_names(fn)
                for node in ast.walk(fn):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in propagators
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                    ):
                        continue
                    callee = node.func.attr
                    if name in propagators and self._is_direct_return(fn, node):
                        continue  # forwarder: its callers carry the check
                    if self._call_result_checked(ctx, node, checked):
                        continue
                    yield Finding(
                        path=ctx.rel,
                        line=node.lineno,
                        rule=self.id,
                        message=f"result of self.{callee}() is not checked "
                        "against None — a breaker-open short-circuit would "
                        "leak None to the caller instead of reaching the "
                        "pandas fallback",
                        fix_hint="assign the result and fall back via "
                        "`if result is not None: return result` + the "
                        "pandas default",
                        scope=ctx.scope_of(node),
                        symbol=f"unchecked-{name}-{callee}",
                    )

    @staticmethod
    def _is_direct_return(fn: ast.FunctionDef, call: ast.Call) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is call:
                return True
        return False

    def _call_result_checked(
        self, ctx: FileContext, call: ast.Call, checked_names: Set[str]
    ) -> bool:
        """Is this call's result bound to a None-checked name, or used in a
        None comparison / boolean-ish guard directly?"""
        parent = ctx.parent_of(call)
        # climb through conditional wrappers: `x = (call if cond else None)`
        # still binds the result to a (checked) name
        while isinstance(parent, ast.IfExp):
            parent = ctx.parent_of(parent)
        # result = self._try_x(...)  ->  name must be None-checked
        if isinstance(parent, ast.Assign):
            names = [
                n
                for t in parent.targets
                if isinstance(t, ast.Name)
                for n in [t.id]
            ]
            return any(n in checked_names for n in names)
        if isinstance(parent, (ast.AnnAssign,)) and isinstance(
            parent.target, ast.Name
        ):
            return parent.target.id in checked_names
        # (self._try_x(...) is None) / `or` chains with a None-checked result
        if isinstance(parent, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in parent.ops
        ):
            return True
        if isinstance(parent, ast.BoolOp):
            return True  # `self._try_x(...) or fallback` keeps the fallback
        # walrus: (result := self._try_x(...)) is None
        if isinstance(parent, ast.NamedExpr):
            grand = ctx.parent_of(parent)
            if isinstance(grand, ast.Compare):
                return True
            return (
                isinstance(parent.target, ast.Name)
                and parent.target.id in checked_names
            )
        return False
