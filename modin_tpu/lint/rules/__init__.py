"""Built-in graftlint rules.  Importing this package registers them all.

Each module defines one rule guarding one PR-1 invariant (or a registry
invariant that grew out of it) — see docs/linting.md for the catalog:

- HOST-SYNC        every device->host sync goes through the materialize seam
- JIT-HAZARD       jitted functions don't trace Python control flow / shapes
- FALLBACK-PARITY  every _try_* device path has a breaker + pandas fallback
- EXC-HYGIENE      no broad except around device dispatch
- REGISTRY-DRIFT   metrics and MODIN_TPU_* env vars are declared + documented
- LOCK-ORDER       acquisitions follow the declared partial order (graftdep)
- LOCK-BLOCKING    no blocking call reachable while a registry lock is held
- THREAD-HYGIENE   threads are named, daemon-explicit, and seed context
"""

from modin_tpu.lint.rules import (  # noqa: F401
    exc_hygiene,
    fallback_parity,
    host_sync,
    jit_hazard,
    lock_order,
    registry_drift,
    thread_hygiene,
)
