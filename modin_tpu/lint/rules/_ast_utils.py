"""Small AST helpers shared by the graftlint rules."""

from __future__ import annotations

import ast
from typing import List, Optional

#: attribute accesses on a device array that yield *host* metadata, not a
#: device value — safe in Python control flow and shape positions
STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "devices", "sharding"})


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.numpy.sum' for a Name/Attribute chain, None for anything else."""
    parts = dotted_parts(node)
    return ".".join(parts) if parts else None


def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        parts.reverse()
        return parts
    return None


def assigned_names(target: ast.AST) -> List[str]:
    """Plain names bound by an assignment target (tuples flattened)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(assigned_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return assigned_names(target.value)
    return []


def first_line(node: ast.AST) -> int:
    return getattr(node, "lineno", 1)


def is_docstring(ctx_parents, node: ast.Constant) -> bool:
    """Is this string constant a docstring (first stmt of a def/class/module)?"""
    parent = ctx_parents.get(node)
    if not isinstance(parent, ast.Expr):
        return False
    grand = ctx_parents.get(parent)
    if not isinstance(
        grand, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Module)
    ):
        return False
    body = grand.body
    return bool(body) and body[0] is parent
