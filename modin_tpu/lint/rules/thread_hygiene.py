"""THREAD-HYGIENE: every spawned thread is named, daemonized deliberately,
and seeds observability context.

Three conventions every long-lived helper thread in this codebase
(watchdog, prefetch worker, watch sampler) re-derived by hand, now
checked:

1. **name=** — an anonymous ``Thread-12`` in a stack dump, a flight
   record, or lockdep's violation report is undebuggable; every
   ``threading.Thread(...)`` site passes an explicit ``name=``;
2. **daemon=** — whether a thread may outlive interpreter shutdown is a
   decision, not a default; the site must say it either way;
3. **span/QueryStats seeding** — a worker that runs a query's work on the
   caller's behalf must adopt the spawner's observability context
   (``graftscope.seed_thread`` + ``graftmeter.seed_thread_scopes``), or
   its spans float parentless and its metrics bill nobody.  The check
   resolves the ``target=`` function (same-file scope chain / bound
   method) and requires both seeding calls somewhere in its body or one
   call-hop below; an unresolvable target (cross-module callable) is
   exempt — the rule never guesses.

Vetted exceptions (a pure-stdlib thread that touches no observability,
e.g. a build-probe helper) carry ``# graftlint: disable=THREAD-HYGIENE``
with the reason inline.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set, Tuple

from modin_tpu.lint.framework import FileContext, Finding, Project, Rule, register_rule
from modin_tpu.lint.rules._ast_utils import dotted_parts

_SEED_CALLS = ("seed_thread", "seed_thread_scopes")


def _is_thread_ctor(node: ast.Call) -> bool:
    parts = dotted_parts(node.func)
    return bool(
        parts
        and parts[-1] == "Thread"
        and (len(parts) == 1 or parts[-2] == "threading")
    )


@register_rule
class ThreadHygieneRule(Rule):
    id = "THREAD-HYGIENE"
    description = (
        "every threading.Thread(...) site must pass name= and daemon=, and "
        "its target must seed spans/QueryStats (seed_thread + "
        "seed_thread_scopes)"
    )

    def check_file(self, ctx: FileContext, project: Project) -> Iterator[Finding]:
        defs = self._defs_by_scope(ctx)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            scope = ctx.scope_of(node)
            target_label = self._target_label(node)
            if "name" not in kwargs:
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        "Thread() without name= — anonymous threads are "
                        "undebuggable in stack dumps, flight records, and "
                        "lockdep reports"
                    ),
                    fix_hint='pass name="modin-tpu-<role>"',
                    scope=scope,
                    symbol=f"unnamed-{target_label}",
                )
            if "daemon" not in kwargs:
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        "Thread() without daemon= — whether the thread may "
                        "outlive shutdown is a decision, not a default"
                    ),
                    fix_hint="pass daemon=True (helpers) or daemon=False "
                    "(work that must finish)",
                    scope=scope,
                    symbol=f"undaemonized-{target_label}",
                )
            target_fn = self._resolve_target(ctx, node, defs)
            if target_fn is not None and not self._seeds(
                ctx, target_fn, defs
            ):
                yield Finding(
                    path=ctx.rel,
                    line=node.lineno,
                    rule=self.id,
                    message=(
                        f"thread target `{target_label}` never seeds "
                        "observability context (seed_thread + "
                        "seed_thread_scopes) — its spans float parentless "
                        "and its metrics bill nobody"
                    ),
                    fix_hint=(
                        "snapshot at spawn (graftscope.snapshot_stack / "
                        "graftmeter.snapshot_scopes), seed at the top of "
                        "the target body, clear in a finally"
                    ),
                    scope=scope,
                    symbol=f"unseeded-{target_label}",
                )

    # -- target resolution ----------------------------------------------- #

    @staticmethod
    def _target_expr(node: ast.Call) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == "target":
                return kw.value
        if node.args:  # Thread(group, target, ...) positional form
            return node.args[1] if len(node.args) > 1 else None
        return None

    def _target_label(self, node: ast.Call) -> str:
        expr = self._target_expr(node)
        if isinstance(expr, ast.Name):
            return expr.id
        if isinstance(expr, ast.Attribute):
            return expr.attr
        return "thread"

    @staticmethod
    def _defs_by_scope(
        ctx: FileContext,
    ) -> Dict[Tuple[str, str], ast.FunctionDef]:
        """(containing scope, name) -> def — jit_hazard's resolution map."""
        defs: Dict[Tuple[str, str], ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                own = ctx.scope_of(node)
                containing = (
                    own.rsplit(".", 1)[0] if "." in own else "<module>"
                )
                defs[(containing, node.name)] = node
        return defs

    def _resolve_target(
        self,
        ctx: FileContext,
        call: ast.Call,
        defs: Dict[Tuple[str, str], ast.FunctionDef],
    ) -> Optional[ast.FunctionDef]:
        expr = self._target_expr(call)
        if isinstance(expr, ast.Name):
            scope = ctx.scope_of(call)
            chain = [scope]
            while "." in scope:
                scope = scope.rsplit(".", 1)[0]
                chain.append(scope)
            chain.append("<module>")
            for s in chain:
                fn = defs.get((s, expr.id))
                if fn is not None:
                    return fn
        elif isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            if expr.value.id == "self":
                cls = self._enclosing_class(ctx, call)
                if cls is not None:
                    return defs.get((ctx.scope_of(cls), expr.attr))
        return None

    @staticmethod
    def _enclosing_class(
        ctx: FileContext, node: ast.AST
    ) -> Optional[ast.ClassDef]:
        cur = ctx.parent_of(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = ctx.parent_of(cur)
        return None

    # -- seeding check --------------------------------------------------- #

    def _seeds(
        self,
        ctx: FileContext,
        fn: ast.FunctionDef,
        defs: Dict[Tuple[str, str], ast.FunctionDef],
        depth: int = 0,
    ) -> bool:
        """Does ``fn`` (or a same-file callee, one hop) call BOTH seeders?"""
        found: Set[str] = set()
        callees = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                parts = dotted_parts(node.func)
                if parts and parts[-1] in _SEED_CALLS:
                    found.add(parts[-1])
                elif (
                    depth == 0
                    and parts
                    and isinstance(node.func, ast.Name)
                ):
                    callees.append(node)
        if len(found) == len(_SEED_CALLS):
            return True
        for call in callees:
            scope = ctx.scope_of(call)
            chain = [scope]
            while "." in scope:
                scope = scope.rsplit(".", 1)[0]
                chain.append(scope)
            chain.append("<module>")
            for s in chain:
                callee = defs.get((s, call.func.id))
                if callee is not None and callee is not fn:
                    if self._seeds(ctx, callee, defs, depth=1):
                        return True
                    break
        return False
