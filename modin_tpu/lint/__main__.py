"""graftlint CLI: ``python -m modin_tpu.lint [paths...]``.

Exit status: 0 clean (pragma/baseline suppressions are fine), 1 on any
non-baselined finding or stale baseline entry.  Findings print one per line
as ``path:line: RULE message`` so editors and CI logs make them clickable.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from modin_tpu.lint import all_rules, run_lint
from modin_tpu.lint.framework import _detect_root, write_baseline

DEFAULT_BASELINE = ".graftlint-baseline"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m modin_tpu.lint",
        description="AST invariant checks for the device/host seam "
        "(see docs/linting.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["modin_tpu"],
        help="files or directories to lint (default: modin_tpu)",
    )
    parser.add_argument(
        "--root",
        type=Path,
        default=None,
        help="repo root for relative paths / baseline / docs "
        "(default: nearest ancestor with pyproject.toml)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help=f"baseline file (default: <root>/{DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline file entirely",
    )
    parser.add_argument(
        "--baseline-write",
        action="store_true",
        help="rewrite the baseline to accept every current finding "
        "(intentional burn-down checkpoints only) and exit 0",
    )
    parser.add_argument(
        "--select",
        default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="findings only, no summary"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}: {rule.description}")
        return 0

    # resolve the root up front so --baseline defaults land next to
    # pyproject.toml regardless of the caller's cwd
    root = args.root if args.root else _detect_root([Path(p) for p in args.paths])
    baseline = args.baseline if args.baseline else root / DEFAULT_BASELINE
    select = (
        [r.strip() for r in args.select.split(",") if r.strip()]
        if args.select
        else None
    )

    try:
        result = run_lint(
            args.paths,
            root=root,
            baseline=None if args.no_baseline else baseline,
            select=select,
        )
    except ValueError as err:  # unknown --select rule id
        print(f"error: {err}", file=sys.stderr)
        return 2

    if args.baseline_write:
        write_baseline(baseline, result.findings + result.baselined)
        print(
            f"wrote {len(result.findings) + len(result.baselined)} baseline "
            f"entr{'y' if len(result.findings) + len(result.baselined) == 1 else 'ies'} "
            f"to {baseline}"
        )
        return 0

    for finding in result.findings:
        print(finding.render())
    for key in result.stale_baseline:
        print(f"{baseline}:1: GL-STALE-BASELINE dead entry {key} — remove it")

    if not args.quiet:
        per_rule: dict = {}
        for f in result.findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        breakdown = ", ".join(f"{r}={n}" for r, n in sorted(per_rule.items()))
        print(
            f"graftlint: {len(result.findings)} finding(s)"
            + (f" [{breakdown}]" if breakdown else "")
            + f", {len(result.suppressed)} pragma-suppressed,"
            f" {len(result.baselined)} baselined,"
            f" {len(result.stale_baseline)} stale baseline entr"
            f"{'y' if len(result.stale_baseline) == 1 else 'ies'}"
        )
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
