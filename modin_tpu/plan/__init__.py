"""graftplan — the whole-query deferred planner.

Layered ABOVE the elementwise fusion DAG (:mod:`modin_tpu.ops.lazy`): where
``LazyExpr`` batches chained *elementwise* ops into one XLA program, graftplan
batches chained *query operators* — scan / project / filter / map / reduce /
groupby_agg / sort — into a logical plan, rewrites the plan (dead-column
pruning, projection pushdown into the byte-range readers, filter pushdown,
common-subexpression elimination, map→reduce fusion), and only then lowers it
through the existing eager seams.  The acceptance shape::

    read_csv(...).query(...)[cols].agg(...)

executes as ONE scan that never parses dropped columns plus one fused device
program, instead of one dispatch (and one full-width parse) per op.

Module map:

- :mod:`~modin_tpu.plan.ir`        — immutable plan nodes + DAG utilities
- :mod:`~modin_tpu.plan.rules`     — rewrite rules (pure ``Plan -> Plan | None``)
  applied to fixpoint under a bounded pass budget
- :mod:`~modin_tpu.plan.lowering`  — plan -> eager TpuQueryCompiler through
  the existing dispatcher / run_fused / JaxWrapper.deploy seams
- :mod:`~modin_tpu.plan.runtime`   — the glue the query compiler's deferral
  guards call (mode gate, scan sniff, node builders, force)
- :mod:`~modin_tpu.plan.explain`   — the EXPLAIN surface (before/after plan
  rendering with per-rule attribution)

The mode knob is ``MODIN_TPU_PLAN`` (Auto | Off | Force) — see
docs/configuration.md.
"""

from modin_tpu.plan.ir import (  # noqa: F401
    Filter,
    GroupbyAgg,
    Map,
    PlanNode,
    Project,
    Reduce,
    Scan,
    Sort,
    Source,
)
from modin_tpu.plan.rules import RULES, optimize  # noqa: F401
from modin_tpu.plan.runtime import defer_frame, plan_mode  # noqa: F401
from modin_tpu.plan.explain import explain_qc, render  # noqa: F401
