"""graftplan lowering: optimized plan -> eager query compiler.

Every node lowers through the seam the eager mode already uses — scans call
the format dispatcher's ``read`` (io lineage, spans, file-leak tracking all
intact), maps call the eager QC methods (whose device paths build deferred
``LazyExpr`` columns), filters ride ``getitem_array``'s mask-fusing gather,
and reductions consume the lazy columns through ``run_fused``'s tail — so
resilience retry/backoff, graftguard lineage recovery, and the device-memory
ledger see planned execution exactly as they see eager execution.

The walk memoizes per node id: a subtree shared between the filter mask and
the main spine (or merged by CSE) is computed ONCE — the "one scan" half of
the acceptance shape is structural, not an optimization.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import costs as graftcost
from modin_tpu.observability import meters as graftmeter
from modin_tpu.observability import spans as graftscope
from modin_tpu.serving import context as serving_context
from modin_tpu.plan import optimizer
from modin_tpu.plan.ir import (
    Filter,
    GroupbyAgg,
    Map,
    PlanNode,
    Project,
    Reduce,
    Ref,
    Scan,
    Sort,
    Source,
    count_nodes,
)

_tls = threading.local()


def _scan_cache_budget() -> int:
    """Byte bound on each origin's materialized-read cache.

    Entries are (compiler, measured bytes) per distinct projection,
    FIFO-evicted coldest-first once the measured total crosses
    ``MODIN_TPU_PLAN_SCAN_CACHE_BYTES`` — a count bound alone let four
    out-of-core-sized reads pin a multi-GB host/device leak.  0 disables
    caching entirely.
    """
    from modin_tpu.config import PlanScanCacheBytes

    return int(PlanScanCacheBytes.get())

#: One lock for every origin's read cache: concurrent queries (graftgate)
#: can force plans sharing a Scan origin from several threads, and an
#: unguarded dict iteration racing the FIFO eviction is torn state.  The
#: physical read itself happens OUTSIDE the lock (a slow parse must not
#: serialize every other query's scan); the worst case is a duplicate
#: parse, never a corrupt cache.
_SCAN_CACHE_LOCK = named_lock("plan.scan_cache")


def in_lowering() -> bool:
    """Whether a lowering pass is running on this thread.

    The Force-mode deferral guards consult this: lowering replays plan
    nodes through the same guarded eager methods, and re-entering planning
    there would wrap Source nodes forever.
    """
    return getattr(_tls, "lowering", False)


def lower(root: PlanNode) -> Any:
    """Lower an (optimized) plan to an eager query compiler."""
    return lower_traced(root)[0]


def lower_traced(
    root: PlanNode,
    instrument: Optional[Dict[int, dict]] = None,
    strategies: Any = None,
) -> Tuple[Any, Dict[int, Any]]:
    """Lower a plan; also returns the node-id -> lowered-compiler memo
    (the materialization path uses it to adopt a reduction's input).

    ``instrument`` (EXPLAIN ANALYZE) is a dict filled in place with one
    entry per lowered node id: measured total/self wall seconds, engine
    dispatches attributed to the node, and the lowered result's rows/bytes.
    Shared (memoized) subtrees bill their cost to the first consumer, which
    is also how the work actually happened.

    ``strategies`` (a graftopt :class:`~..optimizer.PlanStrategies`) arms
    the adaptive loop for this pass: each node's wall is measured (cheap
    perf_counter pair, no dispatch attribution) and fed back through
    ``optimizer.observe`` so estimate divergence can re-plan the remaining
    segment mid-query.  None (``MODIN_TPU_OPT=Off``) keeps the historical
    fast path untouched.
    """
    memo: Dict[int, Any] = {}
    was_lowering = in_lowering()
    _tls.lowering = True
    if instrument is not None:
        _tls.instrument = instrument
        _tls.inst_stack = []
    if strategies is not None:
        optimizer.begin(strategies, root, memo)
        _tls.opt_active = True
    try:
        with graftscope.span(
            "plan.lower", layer="QUERY-COMPILER", nodes=count_nodes(root)
        ):
            result = _lower(root, memo)
    finally:
        _tls.lowering = was_lowering
        if instrument is not None:
            _tls.instrument = None
            _tls.inst_stack = None
        if strategies is not None:
            _tls.opt_active = False
            optimizer.end()
    emit_metric("plan.lower.nodes", len(memo))
    return result, memo


def _lower(node: PlanNode, memo: Dict[int, Any]) -> Any:
    hit = memo.get(id(node))
    if hit is not None:
        return hit
    if serving_context.CONTEXT_ON:
        # graftgate deadline boundary: between plan nodes is the cheapest
        # safe place to abort a deferred query — nothing is half-lowered
        serving_context.check_deadline("plan.lower")
    instrument = getattr(_tls, "instrument", None)
    if instrument is None:
        if not getattr(_tls, "opt_active", False):
            return _lower_node(node, memo)
        # graftopt adaptive path: the cheapest timing that can still catch
        # estimate divergence — one perf_counter pair per node, observed
        # AFTER the node scope pops so a re-plan runs over a consistent
        # done-set (this node already in the memo)
        optimizer.push_node(node)
        t0 = time.perf_counter()
        try:
            result = _lower_node(node, memo)
        finally:
            optimizer.pop_node()
        optimizer.observe(node, time.perf_counter() - t0)
        return result
    # EXPLAIN ANALYZE: time the node's lowering and attribute engine
    # dispatches; parent frames accumulate child totals so self = total -
    # children even though each lowerer recurses internally
    opt_active = getattr(_tls, "opt_active", False)
    if opt_active:
        optimizer.push_node(node)
    stack = _tls.inst_stack
    frame = {"child_s": 0.0, "child_disp": 0}
    stack.append(frame)
    t0 = time.perf_counter()
    d0 = graftmeter.thread_dispatches()
    # one COST_ON read: a concurrent toggle must not leave c0 set with p0
    # None (the epilogue derives both or neither)
    cost_on = graftcost.COST_ON
    c0 = graftcost.thread_cost() if cost_on else None
    p0 = graftcost.thread_padding() if cost_on else None
    try:
        result = _lower_node(node, memo)
    finally:
        stack.pop()
        if opt_active:
            optimizer.pop_node()
        total_s = time.perf_counter() - t0
        total_disp = graftmeter.thread_dispatches() - d0
        if stack:
            parent = stack[-1]
            parent["child_s"] += total_s
            parent["child_disp"] += total_disp
    if opt_active:
        optimizer.observe(node, total_s)
    entry = {
        "total_s": total_s,
        "self_s": max(total_s - frame["child_s"], 0.0),
        "dispatches": max(total_disp - frame["child_disp"], 0),
        "total_dispatches": total_disp,
        "rows": _result_rows(result),
        "bytes": _result_bytes(result),
    }
    if c0 is not None:
        # graftcost joins: estimated flops/bytes billed while lowering this
        # node (subtree totals, like total_s — a shared subtree bills its
        # first consumer), padding observed, and the roofline fraction at
        # the node's own measured wall
        c1 = graftcost.thread_cost()
        p1 = graftcost.thread_padding()
        entry["est_flops"] = c1[0] - c0[0]
        entry["est_bytes"] = c1[1] - c0[1]
        entry["padded_bytes"] = p1[0] - p0[0]
        entry["padding_waste_bytes"] = p1[1] - p0[1]
    instrument[id(node)] = entry
    return result


def _result_rows(qc: Any) -> Optional[int]:
    """Row count of a lowered compiler, without forcing anything."""
    try:
        frame = qc._frame
        return len(frame) if frame is not None else None
    except Exception:
        return None


def _result_bytes(qc: Any) -> Optional[int]:
    """Concrete bytes held by a lowered compiler's columns (device buffers
    plus host arrays; deferred/lazy columns are skipped, never forced)."""
    try:
        frame = qc._frame
        if frame is None:
            return None
        total = 0
        for col in frame._columns:
            if getattr(col, "is_device", False):
                if col.is_lazy or col._data is None:
                    continue
                total += int(getattr(col._data, "nbytes", 0) or 0)
            else:
                total += int(getattr(col.data, "nbytes", 0) or 0)
        return total
    except Exception:
        return None


def _lower_node(node: PlanNode, memo: Dict[int, Any]) -> Any:
    try:
        result = _LOWERERS[type(node)](node, memo)
    except Exception as exc:
        # deferral moves eager-mode errors (e.g. `df["s"] > 3` on a string
        # column) from the call site to the materialization point; name the
        # failing node so the traceback points back at the logical op
        if (
            not getattr(exc, "_graftplan_node", None)
            and exc.args
            and isinstance(exc.args[0], str)
        ):
            exc._graftplan_node = node.label()
            exc.args = (
                f"{exc.args[0]} [while materializing deferred plan node "
                f"{node.label()}]",
            ) + exc.args[1:]
        raise
    memo[id(node)] = result
    return result


def _lower_scan(node: Scan, memo: Dict[int, Any]) -> Any:
    origin = node.origin
    need = (
        tuple(node.columns)
        if node.pushed and node.pruned is not None
        else None
    )
    # serve from a prior materialization of this source when it covers the
    # need: a scan shared by several plans (or re-forced after a reduction)
    # must not re-parse the file per force()
    hit = None
    with _SCAN_CACHE_LOCK:
        for key, cached in (origin.cache or {}).items():
            if key is None and need is None:
                hit = cached[0]
                break
            if need is not None and (key is None or set(need) <= set(key)):
                hit = cached[0]
                break
    if hit is not None:
        emit_metric("plan.scan.cache_hit", 1)
        return hit if need is None else hit.getitem_column_array(list(need))
    kwargs = scan_read_kwargs(node)
    if need is not None:
        emit_metric(
            "plan.scan.pruned_columns", len(node.all_columns) - len(node.pruned)
        )
    qc = node.dispatcher.read(**kwargs)
    budget = _scan_cache_budget()
    if origin.cache is not None and budget > 0:
        nbytes = _result_bytes(qc) or 0
        evicted = 0
        with _SCAN_CACHE_LOCK:
            origin.cache[need] = (qc, nbytes)
            total = sum(b for _qc, b in origin.cache.values())
            while total > budget and origin.cache:
                oldest = next(iter(origin.cache))
                _dropped, dropped_bytes = origin.cache.pop(oldest)
                total -= dropped_bytes
                evicted += 1
        for _ in range(evicted):
            emit_metric("plan.scan.cache_evict", 1)
    return qc


def scan_read_kwargs(node: Scan) -> dict:
    """The reader kwargs for a scan, with the pushed projection merged in."""
    kwargs = dict(node.read_kwargs)
    if node.pushed and node.pruned is not None:
        keep = [c for c in node.all_columns if c in set(node.pruned)]
        kwargs[node.colarg] = keep
        dtype = kwargs.get("dtype")
        if isinstance(dtype, dict):
            # per-column dtype entries for never-parsed columns would make
            # some parsers complain; the surviving subset is all that matters
            kwargs["dtype"] = {k: v for k, v in dtype.items() if k in set(keep)}
    return kwargs


def _lower_source(node: Source, memo: Dict[int, Any]) -> Any:
    return node.qc


def _lower_project(node: Project, memo: Dict[int, Any]) -> Any:
    child = _lower(node.children[0], memo)
    qc = child.getitem_column_array(list(node.keys), numeric=node.numeric)
    if node.out_hint is not None:
        qc._shape_hint = node.out_hint
    return qc


def _lower_filter(node: Filter, memo: Dict[int, Any]) -> Any:
    child = _lower(node.children[0], memo)
    mask = _lower(node.children[1], memo)
    return child.getitem_array(mask)


def _lower_map(node: Map, memo: Dict[int, Any]) -> Any:
    receiver = _lower(node.children[0], memo)
    args = tuple(
        _lower(node.children[a.index], memo) if isinstance(a, Ref) else a
        for a in node.args
    )
    qc = getattr(receiver, node.method)(*args, **node.kwargs)
    if node.out_hint is not None:
        qc._shape_hint = node.out_hint
    return qc


def _lower_reduce(node: Reduce, memo: Dict[int, Any]) -> Any:
    streamed = _maybe_stream(node, memo, groupby=False)
    if streamed is not None:
        return streamed
    fused = _maybe_fuse(node, memo, groupby=False)
    if fused is not None:
        return fused
    child = _lower(node.children[0], memo)
    return getattr(child, node.method)(**node.call_kwargs)


def _lower_groupby(node: GroupbyAgg, memo: Dict[int, Any]) -> Any:
    streamed = _maybe_stream(node, memo, groupby=True)
    if streamed is not None:
        return streamed
    fused = _maybe_fuse(node, memo, groupby=True)
    if fused is not None:
        return fused
    child = _lower(node.children[0], memo)
    by = node.by
    if isinstance(by, Ref):
        by = _lower(node.children[by.index], memo)
    return child.groupby_agg(by, node.agg_func, **node.call_kwargs)


def _maybe_stream(node: PlanNode, memo: Dict[int, Any], groupby: bool) -> Any:
    """graftstream residency hook: lower a Reduce/GroupbyAgg root through
    the windowed out-of-core executor when the chain below it is one
    streamable scan whose size the residency router judges out-of-core.
    One attribute read while streaming is off (the default)."""
    from modin_tpu import streaming

    if not streaming.STREAM_ON:
        return None
    if groupby:
        return streaming.maybe_stream_groupby(node, memo)
    return streaming.maybe_stream_reduce(node, memo)


def _maybe_fuse(node: PlanNode, memo: Dict[int, Any], groupby: bool) -> Any:
    """graftfuse whole-plan hook: compile the entire post-scan segment
    (filter/map/project chain + this reduce/groupby tail) into ONE donated
    program when the segment shape supports it and the compile router says
    the frame is big enough to pay for the trace (plan/fuse.py).  One
    attribute read while MODIN_TPU_FUSE=Staged."""
    from modin_tpu.plan import fuse

    if not fuse.FUSE_ON:
        return None
    if groupby:
        return fuse.maybe_fuse_groupby(node, memo)
    return fuse.maybe_fuse_reduce(node, memo)


def _lower_sort(node: Sort, memo: Dict[int, Any]) -> Any:
    child = _lower(node.children[0], memo)
    return child.sort_rows_by_column_values(
        node.sort_columns, node.ascending, **node.call_kwargs
    )


_LOWERERS = {
    Scan: _lower_scan,
    Source: _lower_source,
    Project: _lower_project,
    Filter: _lower_filter,
    Map: _lower_map,
    Reduce: _lower_reduce,
    GroupbyAgg: _lower_groupby,
    Sort: _lower_sort,
}
